"""The `Stoke` facade: declarative flags → validated status → one SPMD engine.

TPU-native re-design of the reference facade (stoke/stoke.py:49-1466).  The
public contract is preserved — construct with flags, then drive your own
training loop through four wrapped calls plus a DataLoader factory and
unified save/load (reference README.md:13-43):

    stoke = Stoke(model, optimizer, loss, batch_size_per_device=32,
                  device="tpu", distributed="dp", precision="bf16", fsdp=True)
    loader = stoke.DataLoader(dataset, sampler=...)
    for x, y in loader:
        out = stoke.model(x)          # lazy handle (train) / eager (eval)
        loss = stoke.loss(out, y)     # ONE compiled fused micro-step
        stoke.backward(loss)          # commit accumulated grads
        stoke.step()                  # compiled apply at accum boundary

What changed under the hood (SURVEY.md §7): the reference's dynamically
composed mixin runner (``type("StokeRunner", (dist, fp16, opt, io))``,
stoke.py:599-657) becomes explicit strategy *data* — a device mesh, sharding
rules, a precision policy, and compiled step functions.  There is no wrap
ordering dance (stoke.py:306-324): placement is declared once and XLA derives
the collective schedule.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.configs import (
    ClipGradConfig,
    ClipGradNormConfig,
    DeviceOptions,
    DistributedOptions,
    ParamNormalize,
    PrecisionOptions,
    LossReduction,
)
from stoke_tpu.engine import (
    DeferredOutput,
    PrecisionPolicy,
    StepEngine,
    as_adapter,
    build_optimizer,
    init_scaler_state,
    is_deferred,
)
from stoke_tpu.parallel.mesh import build_mesh, initialize_distributed
from stoke_tpu.parallel.sharding import make_sharding_rules, place_global_tree
from stoke_tpu.status import StokeStatus
from stoke_tpu.telemetry import Telemetry
from stoke_tpu.telemetry.tracing import trace_span
from stoke_tpu.telemetry.health import (
    SENTINEL_INDEX,
    HealthHaltError,
    HealthMonitor,
    unpack_sentinels,
)
from stoke_tpu.telemetry.recorder import FlightRecorder
from stoke_tpu.utils.printing import unrolled_print
from stoke_tpu.utils.trees import tree_count_params

from jax.sharding import NamedSharding, PartitionSpec as P


def _on_accelerator(leaf) -> bool:
    """True when ``leaf`` is a jax Array resident on a non-CPU device (its
    bytes are already in the accelerator's ``bytes_in_use``)."""
    if not isinstance(leaf, jax.Array):
        return False
    try:
        return all(d.platform != "cpu" for d in leaf.sharding.device_set)
    except Exception:
        return False


def _device_memory_stats() -> Optional[dict]:
    """Memory stats of the first local device, or None where the backend
    doesn't report them (CPU simulator).  Delegates to the shared
    None-tolerant reader in ``telemetry/collectors.py`` (the PR-15
    shared-normalizer discipline: one ``device.memory_stats()`` probe,
    not two drifting copies)."""
    from stoke_tpu.telemetry.collectors import hbm_stats

    return hbm_stats() or None


def _check_segment_memory(seg_bytes: int, stats: Optional[dict]) -> None:
    """Raise an actionable error when a ``train_steps`` segment obviously
    cannot fit in device memory (pure function — unit-tested with synthetic
    stats).  A conservative pre-flight: only the stacked-input bytes are
    counted (activations/params need room too), and the guard fires only
    when those alone exceed 90% of free memory — the point is a clear error
    *before* the runtime OOMs mid-compile, not an exact accounting."""
    if not stats:
        return
    limit = stats.get("bytes_limit")
    if not limit:
        return
    free = limit - stats.get("bytes_in_use", 0)
    if seg_bytes > 0.9 * free:
        raise ValueError(
            f"Stoke -- train_steps() segment stacks {seg_bytes / 1e9:.2f} GB "
            f"of inputs but the device has only {free / 1e9:.2f} GB free "
            f"(limit {limit / 1e9:.2f} GB). Pass segment_size=<c> to stream "
            f"the segment host->device in chunks of c optimizer steps, or "
            f"stack fewer steps per call. (docs/performance.md)"
        )


def _timed(phase: str):
    """Method decorator feeding the wall-clock breakdown (no-op overhead of
    one null-context when disabled)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            with self._clock(phase):
                return fn(self, *args, **kwargs)

        return wrapper

    return deco


def _health_guarded(fn):
    """Method decorator for the dispatching step paths (ISSUE 3): arms the
    hang watchdog across the call (a wedged collective hangs the training
    thread inside the dispatch or its result fetch — only the watchdog's
    daemon thread can report it) and writes a post-mortem bundle when the
    call dies on an uncaught exception.  Zero overhead without a
    ``HealthConfig``."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        h = self._health
        if h is None:
            return fn(self, *args, **kwargs)
        # deadline scaled by compile grace until the first step completes;
        # train_steps re-arms with its per-segment step count once known
        h.arm_watchdog()
        try:
            return fn(self, *args, **kwargs)
        except HealthHaltError:
            raise  # the halt path already dumped its bundle
        except Exception as e:
            # one bundle per exception (nested guarded calls — e.g. the
            # chunked train_steps recursion — re-raise through multiple
            # wrappers) and at most max_dumps exception bundles per run
            # (a caller retrying a failing call must not fill the disk)
            if (
                h.cfg.dump_on_exception
                and not getattr(e, "_stoke_health_dumped", False)
                and h.note_exception_dump()
            ):
                try:
                    e._stoke_health_dumped = True
                except Exception:
                    pass
                h.dump(
                    "exception",
                    extra={"method": fn.__name__, "error": repr(e)[:500]},
                )
            raise
        finally:
            h.disarm_watchdog()

    return wrapper


class Stoke:
    """Declarative training-context facade (reference stoke/stoke.py:49-1466).

    Args:
        model: flax ``linen.Module``, plain callable ``fn(params, *args)``,
            or a :class:`~stoke_tpu.engine.ModelAdapter`.
        optimizer: ``StokeOptimizer`` TypedDict (ctor + kwargs, reference
            configs.py:754-770) or an ``optax.GradientTransformation``.
        loss: callable ``loss(out, *targets) -> scalar | tuple | dict``
            (multi-loss supported, reference stoke.py:872-912).
        params: initial model variables — either a flax variables dict
            (``{"params": ..., "batch_stats": ...}``) or a bare params pytree.
            (The reference receives an initialized ``nn.Module``; JAX splits
            module and state, so state is passed explicitly.)  The facade
            TAKES OWNERSHIP of these arrays: compiled steps donate their
            buffers (in-place updates), and placement may alias the passed
            tree, so do not reuse it elsewhere (e.g. to build a second
            ``Stoke``) — read live values via ``stoke.params`` instead, or
            pass a copy.
        batch_size_per_device: micro-batch size per device.
        grad_accum: gradient accumulation steps (reference stoke.py:137).
        grad_clip: ``ClipGradConfig`` / ``ClipGradNormConfig`` / None.
        device: "cpu" | "tpu" (reference ``gpu`` flag).
        distributed: None | "dp" (reference {ddp,horovod,deepspeed} collapse).
        precision: None/"full" | "bf16" | "fp16" (reference FP16Options).
        oss / sddp / fsdp: ZeRO-1/2/3-equivalent sharding tiers (reference
            fairscale flags, stoke.py:147-152).
        configs: list of config-class instances (deduped by class).
        model_train_kwargs / model_eval_kwargs: extra kwargs for flax apply
            in train/eval mode (e.g. ``{"train": True}``), replacing torch's
            implicit module mode bit.
        loss_weights: optional pytree of floats matching the structure of
            ``loss()``'s return; the training objective becomes the weighted
            sum ``Σ wᵢ·lossᵢ``.  Gradient-equivalent to the reference's
            per-loss backward passes with weights (fp16.py:545-579,
            stoke.py:891-902); reported per-loss values stay unweighted.
            ``None`` (default) sums all losses with weight 1 — the
            "summed objective" contract.
        aux_loss_weight: weight for MODEL-internal auxiliary losses sown
            into the flax "losses" collection (e.g. the MoE router's
            load-balancing term, models/moe.py) — they join the training
            objective as ``aux_loss_weight · Σ aux`` (0 disables; default
            0.01, the Switch-Transformer α).  The user's loss report stays
            untouched; latest values are readable via ``aux_losses``.
        seed: PRNG seed for dropout etc.
        ema_weight: EMA coefficient for the rolling loss (reference
            stoke.py:155 ``ema_weight``).
        verbose: rank-0 status printing (reference stoke.py:154).
    """

    def __init__(
        self,
        model: Any,
        optimizer: Any,
        loss: Callable,
        params: Any,
        batch_size_per_device: int,
        grad_accum: Optional[int] = None,
        grad_clip: Optional[Union[ClipGradConfig, ClipGradNormConfig]] = None,
        device: Union[str, DeviceOptions] = "cpu",
        distributed: Optional[Union[str, DistributedOptions]] = None,
        precision: Optional[Union[str, PrecisionOptions]] = None,
        oss: bool = False,
        sddp: bool = False,
        fsdp: bool = False,
        configs: Optional[Sequence[Any]] = None,
        model_train_kwargs: Optional[dict] = None,
        model_eval_kwargs: Optional[dict] = None,
        model_rng_keys: Sequence[str] = ("dropout",),
        loss_weights: Optional[Any] = None,
        aux_loss_weight: float = 0.01,
        seed: int = 0,
        ema_weight: float = 0.1,
        verbose: bool = True,
    ):
        # ----- L3: validated status (reference stoke.py:201) -----
        self._status_obj = StokeStatus(
            batch_size_per_device=batch_size_per_device,
            grad_accum=grad_accum,
            grad_clip=grad_clip,
            device=device,
            distributed=distributed,
            precision=precision,
            oss=oss,
            sddp=sddp,
            fsdp=fsdp,
            configs=configs,
        )
        st = self._status_obj
        self._verbose = verbose

        # ----- multi-host rendezvous + mesh (reference setup_distributed,
        #       stoke.py:220 → distributed.py:491-538) -----
        if st.is_distributed and st.dist_init_config.auto_initialize:
            initialize_distributed(st.dist_init_config)
        self._mesh = build_mesh(st.mesh_config, st.device, st.is_distributed)
        self._rules = make_sharding_rules(
            st.sharding_tier,
            self._mesh,
            st.dp_config.axis_name,
            st.oss_config,
            st.sddp_config,
            st.fsdp_config,
            partition_rules=(
                st.partition_rules_config.rules
                if st.partition_rules_config is not None
                else None
            ),
        )
        if self._mesh is None:
            backend = "cpu" if st.device is DeviceOptions.cpu else None
            self._device = jax.devices(backend)[0] if backend else jax.devices()[0]
        else:
            self._device = None

        # ----- model / loss / optimizer checks (reference stoke.py:214-216) -----
        self._adapter = as_adapter(
            model,
            **(
                dict(
                    train_kwargs=model_train_kwargs,
                    eval_kwargs=model_eval_kwargs,
                    rng_keys=model_rng_keys,
                )
                if hasattr(model, "apply") and not isinstance(model, StepEngine)
                else {}
            ),
        )
        if not callable(loss):
            raise TypeError("Stoke -- loss must be callable")
        self._loss_fn = loss
        self._optimizer = build_optimizer(optimizer)

        # ----- state -----
        variables = params
        if not (isinstance(variables, dict) and "params" in variables):
            variables = {"params": variables}
        self._precision = PrecisionPolicy.make(st.precision, st.precision_config)
        self._engine = StepEngine(
            self._adapter,
            self._loss_fn,
            self._optimizer,
            precision=self._precision,
            precision_config=st.precision_config,
            grad_accum=st.grad_accum,
            grad_clip=st.grad_clip,
            rules=self._rules,
            remat=st.activation_checkpointing_config,
            offload_optimizer=st.offload_optimizer_config,
            offload_params=st.offload_params_config,
            loss_weights=loss_weights,
            aux_loss_weight=aux_loss_weight,
            comm=st.comm_config,
            health=st.health_config,
            numerics=st.numerics_config,
        )
        if self._rules is not None:
            opt_shapes = jax.eval_shape(self._optimizer.init, variables["params"])
            variables = self._engine.resolve_placement_abstract(variables, opt_shapes)
            self._variables = variables
            self._opt_state = self._engine.init_opt_state(variables)
        else:
            self._variables = jax.device_put(variables, self._device)
            opt_target = self._device
            if st.offload_optimizer_config is not None:
                opt_target = self._single_device_offload_target()
            # optimizer init creates fresh scalars (e.g. the adam count) on
            # the DEFAULT backend; pin it to this run's device
            with jax.default_device(self._device):
                opt_state = self._optimizer.init(self._variables["params"])
            self._opt_state = jax.device_put(opt_state, opt_target)
        # disk tier (NVMe-offload equivalent): spill the freshly initialized
        # optimizer state immediately — it is only needed again at the first
        # accumulation boundary
        self._disk_store = None
        if st.offload_disk_config is not None:
            import tempfile

            from stoke_tpu.offload import DiskOptimizerStore

            if st.offload_disk_config.path is not None:
                # unique per process AND per instance/run: concurrent runs
                # pointing at the same NVMe mount must not clobber each other
                base = os.path.join(
                    st.offload_disk_config.path, f"proc{jax.process_index()}"
                )
                os.makedirs(base, exist_ok=True)
                # a killed run cannot clean its spill — reclaim siblings
                # whose recorded pid is dead before adding ours
                from stoke_tpu.offload import reclaim_stale_spills

                reclaim_stale_spills(base)
                spill_dir = tempfile.mkdtemp(prefix="run-", dir=base)
            else:
                spill_dir = tempfile.mkdtemp(prefix="stoke-optspill-")
            with open(os.path.join(spill_dir, "pid"), "w") as f:
                f.write(str(os.getpid()))
            self._disk_store = DiskOptimizerStore(
                os.path.join(spill_dir, "opt"), cleanup_root=spill_dir
            )
            # protect the model variables: some optax transforms alias params
            # inside their init state, and deleting those buffers would kill
            # the live model
            self._disk_store.store(self._opt_state, protect=self._variables)
            self._opt_state = None
        self._grad_buf = self._engine.init_grad_buffer(self._variables)
        self._scaler_state = self._place_scalar_tree(
            init_scaler_state(st.precision_config)
        )
        # gradient-transport state (ISSUE 2): error-feedback residual +
        # stochastic-rounding rng, threaded through every apply path like
        # the scaler state.  Empty dict when no CommConfig (or fp32
        # pass-through) — structurally free.  Transient like the sown
        # "losses" collection: not checkpointed (worst case a restart
        # loses one step's quantization residual).
        self._comm_state = self._engine.init_comm_state(self._variables)
        # analytic per-step bytes-on-wire of the gradient exchange
        # (telemetry counters; None without a CommConfig)
        self._comm_bytes = self._engine.comm_bytes_per_step(self._variables)
        # create the key host-side: PRNGKey dispatches on the DEFAULT
        # backend, which may be a (possibly unreachable) accelerator even
        # when this run targets cpu.  LOCAL device: in multi-process runs
        # jax.devices() lists other processes' (non-addressable) devices
        # first.
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            key = jax.random.PRNGKey(seed)
        self._rng = self._place_scalar_tree(key)

        # ----- counters (reference stoke.py:237-243) -----
        self._grad_accum_counter = 0
        self._optimizer_steps = 0
        self._backward_steps = 0
        self._agg_loss = self._zero_scalar()
        self._agg_count = 0
        self._rolling_mean_loss = self._zero_scalar()
        self._ema_initialized = False
        self._ema_weight = float(ema_weight)
        self._skipped_steps = self._zero_scalar()
        self._last_step_loss = None
        # restart-cost accounting (ISSUE 14 satellite): the step of the
        # last durable save and a host-wall EMA of one optimizer step —
        # the preemption bundle carries both so the supervisor can price
        # an attempt's lost goodput without replaying JSONL
        self._last_save_step = 0
        self._step_wall_ema: Optional[float] = None
        self._last_boundary_t: Optional[float] = None

        # ----- lazy-step bookkeeping -----
        self._training = True
        self._token = 0
        self._stashed_model_call: Optional[tuple] = None
        self._pending: Optional[tuple] = None  # (new_grad_buf, new_scaler, token)

        self._replication_warned: set = set()
        self._materialize_warned = False
        self._tb_writer_obj = None

        # ----- telemetry (ISSUE 1: unified pipeline — registry + sinks +
        #       collectors; a None TelemetryConfig keeps the registry alive
        #       for the wall-clock aliases but attaches no sinks) -----
        self._telemetry = Telemetry(
            st.telemetry_config, rank=jax.process_index()
        )
        # instance-scoped recompile attribution: this engine reports shape-
        # driven recompiles to this run's tracker only (another facade's
        # shape churn in the same process is not this run's problem)
        self._engine._compile_tracker = self._telemetry.compile_tracker
        self._last_grad_norm: Optional[float] = None

        # ----- structured tracing (ISSUE 10: bounded host-span ring +
        #       Perfetto export + per-request serve timelines; default OFF
        #       — without a TraceConfig no recorder is registered and the
        #       composed span helper degrades to the bare xprof
        #       annotation.  Purely host-side either way: step-program
        #       HLO and dispatch counts are bit-identical with the config
        #       absent OR present) -----
        self._tracer = None
        tcfg = st.trace_config
        if tcfg is not None:
            from stoke_tpu.telemetry.tracing import (
                TraceRecorder,
                register_recorder,
            )

            self._tracer = TraceRecorder(
                tcfg,
                rank=jax.process_index(),
                registry=self._telemetry.registry,
            )
            register_recorder(self._tracer)

        # ----- persistent AOT compile cache (ISSUE 6: warm starts load
        #       backend compiles from the persistent XLA disk cache and
        #       the HLO-keyed program ledger books the reclaimed seconds;
        #       step programs ALWAYS dispatch through plain jax.jit —
        #       never through deserialized executables, which lose
        #       donated-input bookkeeping.  Default OFF — without a
        #       CompileConfig the engine dispatches exactly as before)
        # -----
        self._compile_cache = None
        ccfg = st.compile_config
        if ccfg is not None:
            from stoke_tpu.compile_cache import CompileCache

            self._compile_cache = CompileCache(
                ccfg, self._telemetry.registry
            )
            self._engine._compile_cache = self._compile_cache

        # ----- step-time attribution & goodput (ISSUE 4: CostCards, live
        #       MFU/roofline gauges, goodput ledger, anomaly-triggered
        #       xprof capture; default OFF — without an AttributionConfig
        #       the engine runs no cost analysis and the step programs
        #       are untouched) -----
        self._attribution = None
        acfg = st.attribution_config
        if acfg is not None:
            from stoke_tpu.telemetry.attribution import AttributionMonitor

            self._attribution = AttributionMonitor(
                acfg,
                self._telemetry.registry,
                compile_tracker=self._telemetry.compile_tracker,
                trace_dir=st.profiler_config.trace_dir,
            )
            self._telemetry.attribution = self._attribution
            self._engine._attribution = self._attribution.cost_cards

        # ----- health monitor (ISSUE 3: sentinels + detectors + flight
        #       recorder + watchdog; default OFF — without a HealthConfig
        #       the step paths are untouched) -----
        self._health: Optional[HealthMonitor] = None
        self._fleet = None  # assigned below; the recorder's fleet_fn
        self._numerics = None  # assigned below; the recorder's numerics_fn
        self._wire_error_warned = False
        self._last_sentinels = None  # closure may fire before then
        hcfg = st.health_config
        if hcfg is not None:
            bundle_dir = hcfg.bundle_dir
            if bundle_dir is None:
                base = (
                    st.telemetry_config.output_dir
                    if st.telemetry_config is not None
                    else "health"
                )
                bundle_dir = os.path.join(base, "postmortem")
            recorder = FlightRecorder(
                bundle_dir,
                ring_size=hcfg.ring_size,
                status_dict=st.to_dict(),
                mesh_info=self._mesh_info(),
                snapshot_fn=self._telemetry.registry.snapshot,
                install_signal_handlers=hcfg.dump_signals,
                # ISSUE 4 satellite: a post-mortem shows utilization at
                # time of death — the goodput summary and the last
                # analyzed CostCards join every bundle
                goodput_fn=(
                    self._telemetry.goodput_summary
                    if self._attribution is not None
                    else None
                ),
                cost_cards_fn=(
                    self._attribution.cost_cards.last_cards
                    if self._attribution is not None
                    else None
                ),
                # ISSUE 5: late-bound — the fleet monitor is constructed
                # after the health block so it can see the full registry;
                # bundles written before the first exchange carry no
                # fleet.json (snapshot() of a monitor-less run is None)
                fleet_fn=lambda: (
                    self._fleet.snapshot()
                    if self._fleet is not None
                    else None
                ),
                # ISSUE 10: the span ring at time of death — every bundle
                # gains a Perfetto-loadable trace.json when tracing is on
                trace_fn=(
                    self._tracer.to_trace_events
                    if self._tracer is not None
                    else None
                ),
                # ISSUE 12: late-bound like the fleet view — which LAYER
                # was bad at time of death (numerics.json); bundles
                # written before the monitor exists carry none
                numerics_fn=lambda: (
                    self._numerics.snapshot()
                    if self._numerics is not None
                    else None
                ),
            )
            self._health = HealthMonitor(
                hcfg,
                self._telemetry.registry,
                recorder,
                compile_tracker=self._telemetry.compile_tracker,
            )
            # leaf-level NaN provenance (ISSUE 12 satellite): the sentinel
            # row carries the first offending leaf INDEX; this table lets
            # the NonFiniteDetector name its path even without a
            # NumericsConfig
            from stoke_tpu.telemetry.numerics import leaf_path_names

            self._health.leaf_paths = leaf_path_names(
                self._variables["params"]
            )
            if self._attribution is not None:
                # the profiler auto-capture registers as a health
                # detector (PR 3 registry): captures surface in the
                # anomaly counters, ring, and post-mortem bundles
                from stoke_tpu.telemetry.attribution import (
                    AutoCaptureDetector,
                )

                self._health.detectors.append(
                    AutoCaptureDetector(
                        self._attribution, acfg.capture_action
                    )
                )

        # ----- fleet observability (ISSUE 5: cross-host skew aggregation,
        #       straggler detection, barrier-wait attribution; default OFF
        #       — without a FleetConfig no cross-host exchange ever runs
        #       and the step paths are untouched) -----
        fcfg = st.fleet_config
        if fcfg is not None:
            from stoke_tpu.telemetry.fleet import (
                FleetMonitor,
                FleetStragglerDetector,
            )

            self._fleet = FleetMonitor(
                fcfg,
                self._telemetry.registry,
                rank=jax.process_index(),
                n_processes=jax.process_count(),
                dispatch_count_fn=lambda: self._engine.dispatch_count,
            )
            self._telemetry.fleet = self._fleet
            if self._health is not None:
                # the straggler streak surfaces as a health anomaly
                # (PR 3 registry): counted, ringed, and bundled like any
                # other detector firing
                self._health.detectors.append(
                    FleetStragglerDetector(
                        self._fleet, fcfg.straggler_action
                    )
                )

        # ----- per-layer numerics observatory (ISSUE 12: module
        #       sentinels, NaN provenance, quantization-error attribution;
        #       default OFF — without a NumericsConfig the compiled step
        #       programs are bit-identical and no numerics/* field or
        #       gauge exists anywhere) -----
        ncfg = st.numerics_config
        if ncfg is not None:
            from stoke_tpu.telemetry.numerics import (
                NumericsMonitor,
                NumericsProvenanceDetector,
                leaf_path_names as _leaf_paths,
                module_groups,
            )

            self._numerics = NumericsMonitor(
                ncfg,
                self._telemetry.registry,
                module_groups(self._variables["params"]),
                leaf_paths=_leaf_paths(self._variables["params"]),
                rank=jax.process_index(),
            )
            self._telemetry.numerics = self._numerics
            if self._health is not None:
                # NaN provenance surfaces as a health anomaly (PR 3
                # registry): counted, ringed, bundled — and a halt action
                # stops the run at the facade boundary with the layer
                # named
                self._health.detectors.append(
                    NumericsProvenanceDetector(
                        self._numerics, ncfg.provenance_action
                    )
                )

        # ----- pod-scale resilience (ISSUE 7: preemption-aware emergency
        #       save, integrity-verified auto-resume with quarantine, and
        #       the deterministic fault injector; default OFF — without a
        #       ResilienceConfig no signal handler is installed, no
        #       manifest is written, and the step paths are untouched:
        #       bit-identical HLO, dispatch-count equal) -----
        self._resilience = None
        rcfg = st.resilience_config
        if rcfg is not None:
            from stoke_tpu.resilience import ResilienceMonitor

            # constructed AFTER the health block on purpose: with
            # resilience on, the preemption signals mean "drain and save",
            # so this monitor's handlers supersede the flight recorder's
            # dump-and-die disposition for those signals (the emergency
            # path writes a better corpse — a loadable checkpoint, plus a
            # post-mortem bundle when a HealthConfig is present)
            self._resilience = ResilienceMonitor(
                rcfg,
                self._telemetry.registry,
                recorder=(
                    self._health.recorder
                    if self._health is not None
                    else None
                ),
            )
            self._telemetry.resilience = self._resilience
            if self._resilience.chaos.active:
                # engine pre-dispatch hook only when a chaos spec is armed
                self._engine._chaos = self._resilience.chaos

        # ----- HBM capacity observatory (ISSUE 19: per-subsystem memory
        #       ledger, per-program memory_analysis peaks, OOM pre-flight;
        #       default OFF — without a MemoryConfig no observatory is
        #       constructed, no mem/* field or gauge exists anywhere, and
        #       the compiled programs are bit-identical) -----
        self._memory_obs = None
        mcfg = st.memory_config
        if mcfg is not None:
            from stoke_tpu import offload as _offload
            from stoke_tpu.telemetry.memory import (
                MemoryObservatory,
                transport_resident_bytes,
                tree_resident_bytes,
            )

            obs = MemoryObservatory(mcfg, self._telemetry.registry)
            obs.set_component(
                "params", lambda: tree_resident_bytes(self._variables)
            )
            # the disk store spills the optimizer state between steps
            # (self._opt_state is None then) — resident bytes are 0, the
            # transient reload is the step program's temp, not the ledger
            obs.set_component(
                "opt_state",
                lambda: (
                    0
                    if self._opt_state is None
                    else tree_resident_bytes(self._opt_state)
                ),
            )
            # per-shard via the transport's layout descriptor: the PR-8
            # sharded transport ledgers 1/world of the buckets + residual,
            # the PR-2 replicated one a full copy (None when inactive -> 0)
            obs.set_component(
                "transport",
                lambda: transport_resident_bytes(
                    self._engine.transport.layout_descriptor(
                        self._variables["params"]
                    )
                ),
            )
            obs.set_component("snapshot", _offload.staged_nbytes)
            self._memory_obs = obs
            self._telemetry.memory = obs
            # engine dispatch-funnel hook: one memory_analysis per
            # distinct (program, signature) at _aot_call
            self._engine._memory = obs
            # OOM pre-flight at build: resident-only (no program has
            # dispatched yet); warns BEFORE the first step can allocate
            obs.preflight("build")

        # ----- live ops plane (ISSUE 20: scrapeable HTTP observatory —
        #       /metrics via the sink's own renderer, /healthz drain
        #       signal, pinned /statusz, /requests, /trace, bounded
        #       /profile; default OFF — without an OpsPlaneConfig no
        #       thread starts and no socket binds, and with one the
        #       plane adds zero JSONL fields and zero dispatches) -----
        self._opsplane = None
        ocfg = st.opsplane_config
        if ocfg is not None:
            from stoke_tpu.telemetry.opsplane import OpsPlane

            plane = OpsPlane(
                ocfg, self._telemetry, rank=jax.process_index()
            )
            if self._health is not None:
                plane.attach_health(self._health)
            if self._tracer is not None:
                plane.attach_tracer(self._tracer)
            if self._attribution is not None:
                plane.attach_attribution(self._attribution)
            plane.attach_training(
                goodput=(
                    self._telemetry.goodput_summary
                    if self._attribution is not None
                    else None
                ),
                memory=(
                    self._memory_obs.summary
                    if self._memory_obs is not None
                    else None
                ),
                trace_summary=(
                    self._tracer.summary
                    if self._tracer is not None
                    else None
                ),
            )
            plane.start()
            self._opsplane = plane

        # ----- wall-clock breakdown (reference wall_clock_breakdown,
        #       configs.py:540; host-side dispatch times — device work is
        #       async, use profile_trace() for device timelines).  Backed by
        #       the telemetry registry; enabling telemetry implies it -----
        self._wall_clock_enabled = (
            st.profiler_config.wall_clock_breakdown
            or self._telemetry.enabled
            # tracing needs the facade phase sections live: each timed
            # phase is also a trace span (ISSUE 10 consolidation)
            or self._tracer is not None
        )

        # ----- post-init status (reference stoke.py:245) -----
        world = self._mesh.size if self._mesh is not None else 1
        st.set_post_init_values(world, n_processes=jax.process_count())
        if self._verbose and self.is_rank_0:
            unrolled_print(repr(st).splitlines())

    # ------------------------------------------------------------------ #
    # placement helpers
    # ------------------------------------------------------------------ #

    def _single_device_offload_target(self):
        """Host-memory placement for single-device optimizer offload, with
        the same probe/fallback policy as the mesh path."""
        import warnings

        from jax.sharding import SingleDeviceSharding

        try:
            # construction itself validates memory kinds on newer jax
            # (ValueError for backends without pinned_host) — it belongs
            # inside the probe, not before it
            target = SingleDeviceSharding(
                self._device, memory_kind="pinned_host"
            )
            with jax.default_device(self._device):
                jax.device_put(jnp.zeros((1,), jnp.float32), target)
            return target
        except Exception:
            cfg = self._status_obj.offload_optimizer_config
            if cfg is not None and cfg.fallback_to_device:
                warnings.warn(
                    "Stoke -- optimizer-state host offload unsupported on "
                    "this runtime; keeping state on device"
                )
                return self._device
            raise

    def _mesh_info(self) -> dict:
        """Topology description for post-mortem bundles (host-side only)."""
        try:
            if self._mesh is None:
                return {
                    "mesh": None,
                    "device": str(self._device),
                    "n_processes": jax.process_count(),
                }
            return {
                "axes": list(self._mesh.axis_names),
                "shape": {k: int(v) for k, v in self._mesh.shape.items()},
                "n_devices": int(self._mesh.size),
                "device_kinds": sorted(
                    {d.device_kind for d in self._mesh.devices.flat}
                ),
                "n_processes": jax.process_count(),
            }
        except Exception:
            return {"mesh": "unavailable"}

    def _opt_materialize(self):
        """Optimizer state as device arrays (reads the disk tier if the
        state is spilled; otherwise the live tree)."""
        if self._disk_store is not None and self._disk_store.spilled:
            return self._disk_store.load()
        return self._opt_state

    def _opt_commit(self, new_opt) -> None:
        """Hand updated optimizer state back to its tier (disk spill or the
        live facade slot)."""
        if self._disk_store is not None:
            self._disk_store.store(new_opt, protect=self._variables)
            self._opt_state = None
        else:
            self._opt_state = new_opt

    def _zero_scalar(self):
        # np scalar: creation must not touch the default accelerator backend
        return self._place_scalar_tree(np.float32(0.0))

    def _place_scalar_tree(self, tree):
        if self._rules is not None:
            repl = self._rules.replicated()
            return place_global_tree(tree, repl)
        return jax.device_put(tree, self._device)

    def _batch_sharding_for(self, shape, batch_dim: int = 0):
        if self._mesh is None:
            return self._device
        axis = self._rules.axis_name
        if axis not in self._mesh.axis_names:
            # mesh without a dp axis (pure pipeline/TP): batch replicated
            return NamedSharding(self._mesh, P())
        axis_size = self._mesh.shape[axis]
        nproc = jax.process_count()
        if nproc > 1:
            # multi-process: ``shape`` is the process-LOCAL slab; it must
            # divide evenly into this process's shards along the data axis
            # (axis_size/nproc of them).  Indivisible local batches are an
            # ERROR, not a replication fallback — each process holds
            # DIFFERENT local data, so a "replicated" global array would
            # silently mix batches.
            if len(shape) <= batch_dim:
                # batch-dim-less leaf (per-batch scalar/constant): replicate
                # under the same contract as the pure-TP mesh case — the user
                # feeds identical values on every process
                return NamedSharding(self._mesh, P())
            if axis_size % nproc != 0:
                raise ValueError(
                    f"Stoke -- the '{axis}' mesh axis (size {axis_size}) "
                    f"does not divide evenly across {nproc} processes; "
                    f"per-process batch feeding needs each process to own a "
                    f"whole number of data-axis shards. Reshape the mesh so "
                    f"the data axis is a multiple of the process count."
                )
            local_shards = axis_size // nproc
            if shape[batch_dim] % local_shards != 0:
                raise ValueError(
                    f"Stoke -- per-process batch leaf shape {shape} is not "
                    f"divisible by this process's {local_shards} shards of "
                    f"the '{axis}' mesh axis (size {axis_size}, "
                    f"{nproc} processes); in a multi-process run batches "
                    f"cannot be replicated consistently (each process holds "
                    f"different local data). Pad or drop-last so the "
                    f"per-process batch divides its shard count."
                )
        elif len(shape) <= batch_dim or shape[batch_dim] % axis_size != 0:
            # batch not divisible by the data axis: replicate, but tell the
            # user once per shape — they're paying full-batch compute on
            # every device without realizing it
            if len(shape) > batch_dim and shape not in self._replication_warned:
                self._replication_warned.add(shape)
                self.warn(
                    f"batch leaf shape {shape} is not divisible by the "
                    f"'{axis}' mesh axis ({self._mesh.shape[axis]}); "
                    f"replicating it on every device"
                )
            return NamedSharding(self._mesh, P())
        spec = [None] * (batch_dim + 1)
        spec[batch_dim] = axis
        # opt-in sequence-dim sharding (DataParallelConfig.shard_seq_dim):
        # pre-place inputs for sequence-parallel attention
        cfg = self._status_obj.dp_config
        sd = cfg.shard_seq_dim
        if (
            sd is not None
            and cfg.seq_axis_name in self._mesh.axis_names
            and len(shape) > sd
            and sd != batch_dim
            and shape[sd] % self._mesh.shape[cfg.seq_axis_name] == 0
        ):
            spec += [None] * (sd + 1 - len(spec))
            spec[sd] = cfg.seq_axis_name
        return NamedSharding(self._mesh, P(*spec))

    def _place_batch(self, tree, batch_dim: int = 0):
        """Host batch → device, sharded over the data axis (the TPU
        equivalent of ``place_data_on_gpu``, reference utils.py:39-80; for
        multi-host, each process contributes its local slice of the
        logically-global batch).  ``batch_dim=1`` serves stacked
        [grad_accum, micro_batch, ...] windows."""

        def _leaf(x):
            if isinstance(x, jax.Array):
                return x
            if hasattr(x, "detach"):  # torch tensor
                x = x.detach().cpu().numpy()
            x = np.asarray(x)
            sh = self._batch_sharding_for(x.shape, batch_dim)
            if self._mesh is not None and jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        with trace_span("stoke/place", track="facade"):
            return jax.tree_util.tree_map(_leaf, tree)

    # ------------------------------------------------------------------ #
    # mode toggles (torch module.train()/eval() equivalent)
    # ------------------------------------------------------------------ #

    def train(self) -> "Stoke":
        self._training = True
        return self

    def eval(self) -> "Stoke":
        self._training = False
        return self

    @property
    def training(self) -> bool:
        return self._training

    # ------------------------------------------------------------------ #
    # the 4-call contract
    # ------------------------------------------------------------------ #

    @_timed("model")
    def model(self, *args, **kwargs):
        """Wrapped forward (reference stoke.py:853-869).

        Train mode: returns a lazy :class:`DeferredOutput`; the actual
        forward runs fused with loss+grad inside ``loss()`` (one dispatch per
        micro-batch).  Eval mode: runs the compiled eval forward eagerly and
        returns real arrays.
        """
        placed_args = self._place_batch(args)
        placed_kwargs = self._place_batch(kwargs)
        if self._training:
            self._token += 1
            # stash the CURRENT rng: loss() will consume exactly this key for
            # the fused step, so a later .value read reproduces the same
            # dropout masks even after self._rng has advanced (ADVICE r1)
            self._stashed_model_call = (
                placed_args, placed_kwargs, self._token, self._rng
            )
            return DeferredOutput(self._materialize, self._token)
        return self._engine.eval_fwd(self._variables, placed_args, placed_kwargs)

    def _materialize(self, token: int):
        if self._stashed_model_call is None or self._stashed_model_call[2] != token:
            raise RuntimeError(
                "Stoke -- stale DeferredOutput: materialize before the next "
                "model() call"
            )
        margs, mkwargs, _, rng = self._stashed_model_call
        if not self._materialize_warned:
            self._materialize_warned = True
            self.warn(
                "DeferredOutput.value runs a SECOND compiled forward (the "
                "fused step computes its own); reading .value every step "
                "doubles forward compute. Use it for debugging/metrics only."
            )
        return self._engine.train_fwd(self._variables, rng, margs, mkwargs)

    @_health_guarded
    @_timed("loss")
    def loss(self, *args, **kwargs):
        """Wrapped loss (reference stoke.py:872-912).

        Train mode: runs the compiled fused micro-step (forward + loss +
        grad + buffer-accumulate) and returns device-scalar losses already
        divided by ``grad_accum`` (reference stoke.py:901-911).  The
        cross-replica loss sync of the reference (.item() + allreduce every
        micro-batch, distributed.py:619-646) is free here: the loss is
        computed over the logically-global batch.
        """
        flat, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=is_deferred
        )
        deferred_info = []
        arrays = []
        for i, leaf in enumerate(flat):
            if is_deferred(leaf):
                if (
                    self._stashed_model_call is None
                    or leaf._token != self._stashed_model_call[2]
                ):
                    raise RuntimeError(
                        "Stoke -- loss() received a DeferredOutput from a "
                        "previous model() call; call model() then loss() in "
                        "order"
                    )
                deferred_info.append((i, leaf._path))
            else:
                arrays.append(leaf)
        if self._training and deferred_info:
            # consume the rng stashed at model() time — the SAME key a
            # .value materialization uses, so dropout masks always agree
            margs, mkwargs, token, rng = self._stashed_model_call
            arrays = self._place_batch(arrays)
            report, updated, new_buf, new_scaler, new_rng = (
                self._engine.accum_step(
                    self._variables,
                    self._grad_buf,
                    self._scaler_state,
                    rng,
                    margs,
                    mkwargs,
                    arrays,
                    treedef,
                    tuple(deferred_info),
                    True,
                )
            )
            self._rng = new_rng
            if updated:
                self._variables = {**self._variables, **updated}
            # new_scaler (carrying per-loss overflow flags in num_losses>1
            # mode) commits at backward() time together with the buffer —
            # a dropped pending loss must not skip steps or back off scales
            self._pending = (new_buf, new_scaler, token)
            self._update_loss_tracking(report)
            return report
        # eval path (or no deferred handle): materialize + loss-only
        full = [leaf.value if is_deferred(leaf) else leaf for leaf in flat]
        placed = self._place_batch(full)
        report = self._engine.loss_eval(placed, treedef)
        if self._training:
            # this loss produced NO gradients; drop any stale pending buffer
            # so a following backward() errors instead of committing grads
            # from an earlier, unrelated loss() call
            self._pending = None
            # keep the fused-path convention: training losses are returned
            # divided by grad_accum (reference stoke.py:901-911)
            inv = 1.0 / self._status_obj.grad_accum
            report = jax.tree_util.tree_map(lambda l: l * inv, report)
            self._update_loss_tracking(report)
        return report

    @_timed("backward")
    def backward(self, loss: Any = None) -> None:
        """Wrapped backward (reference stoke.py:960-988): commits the grads
        of the last ``loss()`` into the accumulation buffer and advances the
        micro-step counters.  The gradients were already computed inside the
        fused step; an uncommitted pending buffer is simply dropped, so
        "no backward → no gradient contribution" holds."""
        if not self._training:
            raise RuntimeError("Stoke -- backward() called in eval mode")
        if self._pending is None:
            raise RuntimeError(
                "Stoke -- backward() called without a preceding loss() on a "
                "model() output"
            )
        new_buf, new_scaler, _ = self._pending
        self._grad_buf = new_buf
        # per-loss fp16 mode: overflow flags observed in the micro-step
        # join the scaler state only now that its grads are committed
        self._scaler_state = new_scaler
        self._pending = None
        self._grad_accum_counter += 1
        self._backward_steps += 1

    @_health_guarded
    @_timed("step")
    def step(self) -> None:
        """Wrapped optimizer step (reference stoke.py:990-1040): at the
        accumulation boundary runs the compiled apply (unscale → finite-check
        → clip → update → zero buffer → scaler update); otherwise a no-op.
        """
        if self._grad_accum_counter < self._status_obj.grad_accum:
            return
        will_record = self._telemetry_will_record()
        if will_record:
            self._sample_grad_norm()
        t0 = time.perf_counter() if (
            will_record and self._telemetry.will_sample_device()
        ) else None
        (
            self._variables,
            new_opt,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            sentinels,
            numerics,
            finite,
        ) = self._engine.apply_step(
            self._variables,
            self._opt_materialize(),
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._health_loss_input(),
        )
        self._opt_commit(new_opt)
        if t0 is not None:
            # periodic true-device-time sample: one host sync per logging
            # window (async dispatch hides device time otherwise)
            jax.block_until_ready(self._variables)
            self._telemetry.observe_device_step(time.perf_counter() - t0)
        if self._precision.scaled:
            self._skipped_steps = self._skipped_steps + (
                1.0 - finite.astype(jnp.float32)
            )
        self._optimizer_steps += 1
        self._grad_accum_counter = 0
        self._reset_tracking_window()
        self._observe_numerics(numerics)
        self._observe_health(sentinels)
        self._maybe_log_metrics()
        self._maybe_emit_telemetry()
        self._maybe_auto_save()
        self._resilience_boundary()

    @_health_guarded
    @_timed("train_step")
    def train_step(
        self,
        model_args: Any,
        loss_args: Any = (),
        model_kwargs: Optional[dict] = None,
    ):
        """Fused fast path: one compiled dispatch per micro-step, with the
        optimizer apply fused in at the accumulation boundary.

        Semantically identical to ``model → loss → backward → step`` (same
        compiled math, same counters/EMA/scaler behavior) but with half the
        dispatches — with ``grad_accum == 1`` a full optimizer step is ONE
        XLA program.  The 4-call API remains for reference-contract parity;
        use this in throughput-critical loops.

        Args:
            model_args: positional args for the model (a single array or a
                tuple of arrays).
            loss_args: extra args for the loss after the model output (a
                single array or tuple): ``loss_fn(out, *loss_args)``.
            model_kwargs: optional keyword args for the model.

        Returns the loss report (divided by grad_accum, like ``loss()``).
        """
        if not self._training:
            raise RuntimeError("Stoke -- train_step() called in eval mode")
        if not isinstance(model_args, tuple):
            model_args = (model_args,)
        if not isinstance(loss_args, tuple):
            loss_args = (loss_args,)
        margs = self._place_batch(model_args)
        mkwargs = self._place_batch(model_kwargs or {})
        # loss call structure: loss_fn(out, *loss_args) — the model output
        # slot is a deferred leaf at flat index 0 with an empty path
        sentinel = DeferredOutput(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, *loss_args), {}), is_leaf=is_deferred
        )
        arrays = self._place_batch([l for l in flat if not is_deferred(l)])
        deferred_info = tuple(
            (i, l._path) for i, l in enumerate(flat) if is_deferred(l)
        )
        do_apply = self._grad_accum_counter + 1 >= self._status_obj.grad_accum
        will_record = do_apply and self._telemetry_will_record()
        t0 = time.perf_counter() if (
            will_record and self._telemetry.will_sample_device()
        ) else None
        (
            report,
            _updated,
            self._variables,
            new_opt,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            sentinels,
            numerics,
            finite,
        ) = self._engine.fused_step(
            self._variables,
            self._opt_materialize() if do_apply else self._opt_state,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            margs,
            mkwargs,
            arrays,
            treedef,
            deferred_info,
            do_apply,
        )
        if do_apply:
            self._opt_commit(new_opt)
        else:
            self._opt_state = new_opt
        if t0 is not None:
            jax.block_until_ready(self._variables)
            self._telemetry.observe_device_step(time.perf_counter() - t0)
        self._pending = None
        self._backward_steps += 1
        self._update_loss_tracking(report)
        if do_apply:
            if self._precision.scaled:
                self._skipped_steps = self._skipped_steps + (
                    1.0 - finite.astype(jnp.float32)
                )
            self._optimizer_steps += 1
            self._grad_accum_counter = 0
            self._reset_tracking_window()
            self._observe_numerics(numerics)
            self._observe_health(sentinels)
            self._maybe_log_metrics()
            self._maybe_emit_telemetry()
            self._maybe_auto_save()
            self._resilience_boundary()
        else:
            self._grad_accum_counter += 1
        return report

    # ------------------------------------------------------------------ #
    # TensorBoard metrics (reference DeepspeedTensorboardConfig,
    # configs.py:392-405 — passthrough there, first-class here)
    # ------------------------------------------------------------------ #

    @property
    def _tb_writer(self):
        cfg = self._status_obj.tensorboard_config
        if cfg is None or not self.is_rank_0:
            return None
        if self._tb_writer_obj is None:
            import os

            from stoke_tpu.utils.tb_writer import TBEventWriter

            # native event writer (utils/tb_writer.py) — same file format,
            # no torch import on the metrics path (VERDICT r2 weak #7)
            self._tb_writer_obj = TBEventWriter(
                os.path.join(cfg.output_path, cfg.job_name)
            )
        return self._tb_writer_obj

    def log_scalar(self, tag: str, value, step: Optional[int] = None) -> None:
        """Log a user scalar: lands in the telemetry registry (gauge
        ``user/<tag>``, mirrored to sinks at the next cadence) AND — for
        parity with the legacy contract — immediately in TensorBoard when a
        ``TensorboardConfig`` is supplied on rank 0."""
        self._telemetry.log_scalar(tag, float(value))
        w = self._tb_writer
        if w is not None:
            w.add_scalar(tag, float(value), step if step is not None
                         else self._optimizer_steps)

    @staticmethod
    def _crossed_boundary(steps: int, every: int, window: int) -> bool:
        """True if any multiple of ``every`` falls in ``(steps-window,
        steps]`` — the cadence check for step paths that advance the counter
        by more than one (train_steps segments)."""
        return steps > 0 and steps // every > (steps - window) // every

    def _maybe_log_metrics(self, window: int = 1) -> None:
        cfg = self._status_obj.tensorboard_config
        if (
            cfg is None
            or self._optimizer_steps == 0
            or not self._crossed_boundary(
                self._optimizer_steps, cfg.log_every_n_steps, window
            )
        ):
            return
        w = self._tb_writer
        if w is None:
            return
        step = self._optimizer_steps
        w.add_scalar("loss/ema", self.ema_loss, step)
        if self._last_step_loss is not None:
            w.add_scalar("loss/micro", self.step_loss, step)
        if self._precision.scaled:
            ls = self.loss_scale
            if isinstance(ls, list):  # per-loss scalers: one curve each
                for i, v in enumerate(ls):
                    w.add_scalar(f"scaler/loss_scale_{i}", v, step)
            else:
                w.add_scalar("scaler/loss_scale", ls, step)
            w.add_scalar("scaler/skipped_steps", self.skipped_optimizer_steps, step)
        w.add_scalar("counters/backward_steps", self._backward_steps, step)
        w.flush()

    # ------------------------------------------------------------------ #
    # telemetry step records (ISSUE 1: structured per-window events)
    # ------------------------------------------------------------------ #

    def _telemetry_will_record(self, window: int = 1) -> bool:
        """True when the optimizer step(s) about to complete cross the
        telemetry logging cadence (decides whether to pay for the optional
        device-side samples: grad-norm reduction, block_until_ready)."""
        t = self._telemetry
        return t.enabled and self._crossed_boundary(
            self._optimizer_steps + window,
            t.config.log_every_n_steps,
            window,
        )

    def _sample_grad_norm(self) -> None:
        """Global norm of the accumulated gradient buffer (one device
        reduction + fetch; only at the logging cadence and only when
        ``TelemetryConfig.grad_norm``).  In fp16 single-loss mode the
        buffer holds scale-multiplied grads (the apply unscales them,
        engine._apply_core); the norm is divided by the current scale here
        so the logged value is in true-gradient units.  Per-loss mode
        (num_losses > 1) unscales into the buffer immediately, so no
        adjustment applies.

        With health sentinels on this whole extra reduction is skipped:
        the sentinel vector already carries the same norm computed inside
        the compiled apply (``_observe_health`` installs it — ISSUE 3
        satellite: no second reduction/dispatch)."""
        t = self._telemetry
        if not (t.enabled and t.config.grad_norm):
            return
        if self._engine.sentinels_enabled:
            return
        try:
            import optax

            norm = float(jax.device_get(optax.global_norm(self._grad_buf)))
            if (
                self._precision.scaled
                and self._status_obj.precision_config.num_losses == 1
            ):
                scale = float(jax.device_get(self._scaler_state["scale"]))
                if scale > 0:
                    norm /= scale
            self._last_grad_norm = norm
            t.registry.gauge("train/grad_norm").set(norm)
        except Exception:
            self._last_grad_norm = None

    def _sample_comm_residual_norm(self) -> Optional[float]:
        """Global norm of the error-feedback residual (one device
        reduction + fetch, only at the logging cadence) — the
        "quantization error being carried" gauge; near-constant norm over
        training is the error-feedback-working signal."""
        residual = (self._comm_state or {}).get("residual")
        if residual is None:
            return None
        try:
            import optax

            norm = float(jax.device_get(optax.global_norm(residual)))
            self._telemetry.registry.gauge("comm/residual_norm").set(norm)
            return norm
        except Exception:
            return None

    # ------------------------------------------------------------------ #
    # health monitor (ISSUE 3: sentinels / detectors / recorder / watchdog)
    # ------------------------------------------------------------------ #

    def _health_loss_input(self):
        """Boundary loss scalar for the 4-call apply's sentinel vector
        (None — an empty jit input — when sentinels are off, keeping the
        compiled program bit-identical to a health-free build)."""
        if not self._engine.sentinels_enabled:
            return None
        if self._last_step_loss is not None:
            return self._last_step_loss
        return self._zero_scalar()

    def _observe_health(self, sentinels, window: int = 1) -> None:
        """Feed the just-completed optimizer step(s) to the health monitor:
        fetch the on-device sentinel rows (one tiny host transfer — the
        values were computed inside the step's existing dispatch), run the
        detector registry, and cache the latest row for the telemetry step
        event.  A ``halt``-action detector raises
        :class:`~stoke_tpu.telemetry.health.HealthHaltError` from inside
        ``HealthMonitor.observe`` — i.e. at this facade boundary."""
        h = self._health
        if h is None:
            return
        rows = None
        if sentinels is not None:
            rows = np.asarray(jax.device_get(sentinels), np.float32)
            if rows.ndim == 1:
                rows = rows[None]
            self._last_sentinels = rows[-1]
            t = self._telemetry
            if t.enabled and t.config.grad_norm:
                # sentinel delegation (ISSUE 3 satellite): the in-step
                # grad norm replaces _sample_grad_norm's host-side extra
                # reduction — same true-gradient units (the apply core
                # unscales before the norm)
                gn = float(rows[-1][SENTINEL_INDEX["grad_norm"]])
                self._last_grad_norm = gn
                t.registry.gauge("train/grad_norm").set(gn)
        first = self._optimizer_steps - window + 1
        for i in range(window):
            h.observe(first + i, rows[i] if rows is not None else None)

    # ------------------------------------------------------------------ #
    # per-layer numerics (ISSUE 12: module sentinels / provenance / quant)
    # ------------------------------------------------------------------ #

    def _observe_numerics(self, numerics, window: int = 1) -> None:
        """Feed the just-completed optimizer step(s)' per-group stats
        matrices to the numerics monitor (one tiny host transfer — the
        values were computed inside the step's existing dispatch).  NaN
        provenance derived here is drained into the health anomaly
        pipeline by the ``numerics_provenance`` detector at the
        ``_observe_health`` call that immediately follows."""
        m = self._numerics
        if m is None or numerics is None:
            return
        rows = np.asarray(jax.device_get(numerics), np.float32)
        m.observe_window(self._optimizer_steps - window + 1, rows)

    def _sample_wire_error(self) -> None:
        """Per-group error-feedback residual norms at the logging cadence
        (ISSUE 12 signal family 3a): one small host fetch, attributed to
        module groups through the transport's bucket layout.  Skipped
        when no residual is carried, when the config opts out, or when
        the sharded residual's shards are not addressable (multi-host —
        a diagnostic must never wedge the step path)."""
        m = self._numerics
        if m is None or not m.cfg.wire_error:
            return
        try:
            from stoke_tpu.telemetry.numerics import (
                wire_residual_group_norms,
            )

            m.observe_wire(
                wire_residual_group_norms(
                    self._engine.transport,
                    self._comm_state,
                    self._variables["params"],
                    m.groups,
                )
            )
        except Exception as e:
            # non-addressable sharded shards (multi-host) and any future
            # attribution defect degrade to "no wire signal" — but say so
            # ONCE, the bounded-warning discipline: a silently-absent
            # signal family reads as "nothing to report" when it is
            # actually broken
            if not self._wire_error_warned:
                self._wire_error_warned = True
                self.warn(
                    f"per-layer wire-error attribution unavailable "
                    f"({type(e).__name__}: {e}); numerics wire_err will "
                    f"be absent this run"
                )

    @property
    def numerics(self):
        """The run's per-layer numerics monitor (None without a
        ``NumericsConfig``) — per-group stats, NaN provenance history,
        quantization-error attribution."""
        return self._numerics

    @property
    def numerics_summary(self) -> Optional[Dict[str, Any]]:
        """End-of-run per-layer numerics ranking: groups ordered by
        gradient-noise (running std/mean of each group's grad rms) and by
        quantization error, the latest per-group stats, and every
        non-finite provenance event.  None without a
        ``NumericsConfig``."""
        if self._numerics is None:
            return None
        return self._numerics.summary()

    @property
    def memory(self):
        """The run's HBM capacity observatory (None without a
        ``MemoryConfig``) — subsystem ledger callables, per-program
        memory cards, pre-flight verdicts."""
        return self._memory_obs

    @property
    def memory_summary(self) -> Optional[Dict[str, Any]]:
        """HBM capacity ledger (ISSUE 19): subsystems ranked by resident
        bytes (params / optimizer state / grad transport / KV cache /
        staged snapshots — the components recombine exactly into the
        resident total), per-program ``memory_analysis`` peaks, the OOM
        pre-flight verdicts, and the analytic-vs-live reconciliation.
        None without a ``MemoryConfig``."""
        if self._memory_obs is None:
            return None
        return self._memory_obs.summary()

    @property
    def health(self) -> Optional[HealthMonitor]:
        """The run's health monitor (None without a ``HealthConfig``)."""
        return self._health

    @property
    def opsplane(self):
        """The run's live ops plane (None without an ``OpsPlaneConfig``)
        — the bound HTTP observatory serving /metrics, /healthz,
        /statusz, /requests, /trace and /profile for this rank."""
        return self._opsplane

    @property
    def attribution(self):
        """The run's step-time attribution monitor (None without an
        ``AttributionConfig``) — cost cards, live MFU gauges, goodput
        ledger, auto-capture state."""
        return self._attribution

    @property
    def goodput(self) -> Optional[Dict[str, Any]]:
        """End-of-run goodput accounting: cumulative bucket seconds
        (productive/compile/recompile/loader/checkpoint/halt), goodput
        fraction, aggregate achieved TFLOP/s + MFU, capture paths.  None
        without an ``AttributionConfig``."""
        return self._telemetry.goodput_summary()

    @property
    def fleet(self):
        """The run's fleet monitor (None without a ``FleetConfig``) —
        per-host signal matrix, skew aggregates, straggler streak state."""
        return self._fleet

    @property
    def compile_cache(self):
        """The run's persistent AOT compile cache (None without a
        ``CompileConfig``) — hit/miss counts, reclaimed compile seconds
        (``.stats()``), and the cache directory."""
        return self._compile_cache

    @property
    def fleet_summary(self) -> Optional[Dict[str, Any]]:
        """End-of-run fleet accounting: exchange windows, the latest
        per-host signal matrix + aggregates + straggler verdict, and the
        straggler counts.  None without a ``FleetConfig``."""
        return self._telemetry.fleet_summary()

    @property
    def tracer(self):
        """The run's structured-trace recorder (None without a
        ``TraceConfig``) — the bounded span ring, Perfetto exporter, and
        critical-path summary."""
        return self._tracer

    @property
    def trace_summary(self) -> Optional[Dict[str, Any]]:
        """Critical-path/self-time summary of the trace ring's window
        (per-span-name counts, total and self seconds, and the ranked
        ``critical_path`` — host spans are serial, so the top self-time
        entries are where the host wall clock went).  None without a
        ``TraceConfig``.  A nonzero ``trace/dropped_total`` key means the
        bounded ring evicted spans — the window describes the RECENT
        tail, and any span-derived walk (critical path, serve SLO
        attribution) is partial, not complete."""
        if self._tracer is None:
            return None
        return self._tracer.summary()

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the span ring as Chrome/Perfetto trace-event JSON
        (``trace.rank<N>.json`` under ``TraceConfig.output_dir`` unless
        ``path`` overrides); returns the path, or None without a
        ``TraceConfig``.  ``close_telemetry()`` calls this automatically
        when ``TraceConfig.export_on_close`` is set; calling it mid-run
        snapshots the current ring (load in ui.perfetto.dev, or merge
        ranks with ``scripts/merge_rank_traces.py``)."""
        if self._tracer is None:
            return None
        return self._tracer.export(path)

    def audit(
        self,
        serve=None,
        *,
        replicated_bytes_threshold: Optional[int] = None,
        churn_threshold: Optional[int] = None,
        cost_manifest: Optional[dict] = None,
        cost_tolerance: Optional[float] = None,
        mem_manifest: Optional[dict] = None,
        mem_tolerance: Optional[float] = None,
    ):
        """Static program audit of this LIVE build (ISSUE 15): re-lower
        every step program the engine has dispatched (and, with
        ``serve=engine``, a serving engine's prefill/decode/chunk
        programs) from their recorded abstract specs and check the
        repo's codified program invariants — donation integrity (every
        declared ``donate_argnums`` entry actually aliased; no
        deserialized-executable dispatch, the PR-6/PR-14 hazard), hidden
        host round-trips (callbacks/infeed in a step program), recompile
        hazards (weak-typed scalar args, shape-signature churn against
        the engine's 1024-entry memo), and the sharding audit (large
        replicated tensors on a partitioned program; collectives
        cross-checked against the gradient transport's analytic
        ``bytes_per_step``).

        Lowering/tracing only — NO compile, NO dispatch: the compiled
        programs, dispatch count, and training state are untouched
        (dispatch-count equality is acceptance-tested).  Returns an
        :class:`~stoke_tpu.analysis.program.AuditReport`; findings carry
        rule ids and named remedies (the status-rule discipline), tick
        ``analysis/programs_audited_total`` /
        ``analysis/audit_findings_total`` on the telemetry registry, and
        are warned once rank-0 so an interactive audit is never silent.

        Run the step APIs you care about first — the audit covers what
        the engine actually dispatched (``scripts/stoke_lint.py
        --programs`` drives all four step APIs end-to-end; the jax-free
        source lints live there too)."""
        from stoke_tpu.analysis.program import audit_program_specs

        specs = self._engine.audit_specs()
        if serve is not None:
            specs += serve.audit_specs()
        kwargs = {}
        if replicated_bytes_threshold is not None:
            kwargs["replicated_bytes_threshold"] = replicated_bytes_threshold
        if churn_threshold is not None:
            kwargs["churn_threshold"] = churn_threshold
        if cost_manifest is not None:
            # cost-drift gate (ISSUE 18): re-lower each serve spec's cost
            # against the committed analytic manifest
            kwargs["cost_manifest"] = cost_manifest
        if cost_tolerance is not None:
            kwargs["cost_tolerance"] = cost_tolerance
        if mem_manifest is not None:
            # memory-drift gate (ISSUE 19): re-compile each serve spec and
            # compare its memory_analysis temp/peak bytes against the
            # committed manifest (both directions, grew AND shrank)
            kwargs["mem_manifest"] = mem_manifest
        if mem_tolerance is not None:
            kwargs["mem_tolerance"] = mem_tolerance
        report = audit_program_specs(
            specs,
            transport_active=self._engine.transport.active,
            comm_bytes=self._comm_bytes,
            # None (not {}) when the engine never tracked signatures —
            # the churn rule then reports itself unchecked instead of
            # vacuously clean
            shape_sig_counts=(
                self._engine.shape_sig_counts()
                if self._engine._compile_tracker is not None
                else None
            ),
            **kwargs,
        )
        if self._engine._audit_truncated:
            report.notes.append(
                f"program inventory truncated at the engine's "
                f"{self._engine._MAX_AUDIT_SPECS}-spec audit cap — "
                f"programs first dispatched after the cap were NOT "
                f"audited"
            )
        reg = self._telemetry.registry
        reg.counter(
            "analysis/programs_audited_total",
            help="programs checked by Stoke.audit()",
        ).inc(len(report.programs))
        reg.counter(
            "analysis/audit_findings_total",
            help="program-audit findings (docs/analysis.md rule catalog)",
        ).inc(len(report.findings))
        if report.findings and self.is_rank_0:
            import warnings

            warnings.warn(
                "Stoke -- program audit found "
                f"{len(report.findings)} issue(s):\n" + report.format()
            )
        return report

    @property
    def dispatch_count(self) -> int:
        """Compiled-program invocations issued by this run's engine (the
        health acceptance counter: sentinels must not add dispatches)."""
        return self._engine.dispatch_count

    @property
    def comm_bytes(self) -> Optional[Dict[str, int]]:
        """Analytic per-device bytes-on-wire of ONE optimizer step's
        gradient exchange (None without a ``CommConfig``): ``prequant``
        what the schedule moves in fp32, ``onwire`` what the configured
        wire dtype moves, and — under the ISSUE 8 weight-update-sharded
        path — ``param_gather``, the updated-parameter all-gather leg
        (0 under fsdp, where params stay sharded)."""
        return None if self._comm_bytes is None else dict(self._comm_bytes)

    def _maybe_emit_telemetry(self, window: int = 1) -> None:
        """Assemble + emit one structured step event at the telemetry
        cadence (JSONL / Prometheus / TB sinks).  Device->host transfers
        (EMA loss, loss scale) happen only here, never per micro-batch."""
        if self._tracer is not None:
            # tag subsequent spans with the last completed optimizer step
            # (the step anchor the cross-rank trace merge aligns on)
            self._tracer.set_step(self._optimizer_steps)
        t = self._telemetry
        if not t.enabled or self._optimizer_steps == 0:
            return
        if self._attribution is not None:
            # per-boundary hook: closes an in-flight auto-capture trace
            # window once it covered its configured step count
            self._attribution.on_step(self._optimizer_steps)
        # samples/sec source of truth: one optimizer step consumes one
        # (global) effective batch — counted per boundary, emitted at the
        # cadence
        t.add_samples((self._status_obj.effective_batch_size or 0) * window)
        # gradient bytes-on-wire: analytic per-step counts (ISSUE 2) —
        # ``prequant`` what the fp32 schedule would move, ``onwire`` what
        # the configured wire dtype moves; the JSONL record carries the
        # per-window deltas so the compression win is measurable per run
        if self._comm_bytes is not None:
            t.registry.counter("comm/grad_bytes_prequant_total").inc(
                self._comm_bytes["prequant"] * window
            )
            t.registry.counter("comm/grad_bytes_onwire_total").inc(
                self._comm_bytes["onwire"] * window
            )
            # sharded weight-update path (ISSUE 8): the second wire leg —
            # updated-parameter all-gather back to the tier placement
            # (present only for a ShardedGradTransport; 0 under fsdp
            # where params stay sharded)
            if "param_gather" in self._comm_bytes:
                t.registry.counter("comm/param_gather_bytes_total").inc(
                    self._comm_bytes["param_gather"] * window
                )
        if not self._crossed_boundary(
            self._optimizer_steps, t.config.log_every_n_steps, window
        ):
            return
        # per-layer wire-error attribution (ISSUE 12): refresh the
        # per-group residual norms once per logged window so the record
        # assembled below carries them
        self._sample_wire_error()
        scaled = self._precision.scaled
        sent = (
            unpack_sentinels(self._last_sentinels)
            if self._last_sentinels is not None
            else {}
        )
        record = t.record_step(
            self._optimizer_steps,
            window_steps=window,
            ema_loss=self.ema_loss,
            step_loss=self.step_loss,
            grad_norm=self._last_grad_norm,
            loss_scale=self.loss_scale if scaled else None,
            skipped_steps=self.skipped_optimizer_steps if scaled else 0.0,
            comm_residual_norm=self._sample_comm_residual_norm(),
            param_norm=sent.get("param_norm"),
            update_ratio=sent.get("update_ratio"),
            nonfinite_leaves=sent.get("nonfinite_leaves"),
            health_anomalies=(
                float(self._health.anomaly_count)
                if self._health is not None
                else None
            ),
        )
        if record is not None and self._health is not None:
            # flight-recorder ring: the post-mortem bundle replays the
            # last N structured step events alongside the sentinel rows
            self._health.recorder.record_event(record)
        self._last_grad_norm = None

    def close_telemetry(self) -> None:
        """Flush + close the telemetry sinks and the health monitor
        (watchdog thread + signal handlers); idempotent — sinks are
        line-buffered/atomic, so skipping this loses at most nothing."""
        if (
            self._health is not None
            and self._fleet is not None
            and self._fleet._pending_straggler is not None
        ):
            # a straggler streak that completed on the run's FINAL window
            # has no later step observation to drain it — run the
            # detectors once more so the anomaly (and its dump bundle,
            # for action='dump') is recorded instead of silently lost.
            # Sentinel-driven detectors skip on None; a halt from a
            # registry-driven detector must not raise out of shutdown.
            try:
                self._health.observe(self._optimizer_steps, None)
            except HealthHaltError:
                pass
        if self._opsplane is not None:
            # unbind the socket FIRST: a scraper hitting a half-closed
            # run would read torn summaries from closing subsystems
            self._opsplane.close()
        if self._tracer is not None:
            # stop receiving other runs' spans, then export the final ring
            # (idempotent: a second close re-exports the same ring)
            from stoke_tpu.telemetry.tracing import unregister_recorder

            unregister_recorder(self._tracer)
            tcfg = self._status_obj.trace_config
            if tcfg is not None and tcfg.export_on_close:
                try:
                    self._tracer.export()
                except OSError as e:
                    self.warn(f"trace export failed: {e}")
        self._telemetry.close()
        if self._resilience is not None:
            # uninstall the preemption signal handlers BEFORE the health
            # recorder's (reverse install order, idempotent): resilience
            # installed last, so its saved "previous" SIGTERM handler is
            # the recorder's — restoring it AFTER the recorder uninstalled
            # would leave a closed recorder's handler claiming the signal
            # with nothing to chain to, and SIGTERM would be swallowed
            self._resilience.close()
        if self._health is not None:
            self._health.close()

    def _maybe_auto_save(self, window: int = 1) -> None:
        """Periodic checkpoint from the step path when
        ``CheckpointConfig.save_every_n_steps`` is set — the crash-recovery
        half of checkpoint-restart (SURVEY.md §5: the reference has none).
        ``window``: how many optimizer steps the caller just advanced (a
        train_steps segment may cross a save boundary mid-segment)."""
        cfg = self._status_obj.checkpoint_config
        if (
            cfg.save_every_n_steps
            and cfg.auto_path
            and self._crossed_boundary(
                self._optimizer_steps, cfg.save_every_n_steps, window
            )
        ):
            self.save(cfg.auto_path, name=cfg.auto_name)

    def wait_for_checkpoint(self) -> None:
        """Block until in-flight async checkpoint saves finish
        (``CheckpointConfig(async_save=True)``)."""
        from stoke_tpu import io_ops

        io_ops.wait_for_saves()

    def _note_durable_save(self, step: int) -> None:
        """One checkpoint's write fully landed (io_ops ``on_durable``,
        possibly from a background thread — a GIL-atomic max-update).
        The lost-goodput estimate prices steps beyond THIS point."""
        self._last_save_step = max(self._last_save_step, int(step))

    def maybe_resume(self, path: Optional[str] = None) -> bool:
        """Resume from the newest auto-checkpoint if one exists; otherwise
        start fresh.  Returns True when a checkpoint was loaded.  Combined
        with ``CheckpointConfig(save_every_n_steps=..., auto_path=...)`` this
        makes training loops restart-safe:

            stoke.maybe_resume()
            for batch in loader: stoke.train_step(*batch)
        """
        cfg = self._status_obj.checkpoint_config
        target = path or cfg.auto_path
        if not target:
            return False
        try:
            self.load(target, name=cfg.auto_name)
            return True
        except FileNotFoundError:
            return False

    # ------------------------------------------------------------------ #
    # pod-scale resilience (ISSUE 7: preemption-aware save / verified
    # resume / fault injection; every hook below is a no-op without a
    # ResilienceConfig)
    # ------------------------------------------------------------------ #

    @property
    def resilience(self):
        """The run's resilience monitor (None without a
        ``ResilienceConfig``) — preemption flag, chaos injector,
        ``resilience/*`` counters."""
        return self._resilience

    @property
    def resilience_summary(self) -> Optional[Dict[str, Any]]:
        """End-of-run resilience accounting: restarts, preemptions,
        emergency saves, quarantined tags, resumed/lost steps.  None
        without a ``ResilienceConfig``."""
        if self._resilience is None:
            return None
        return self._resilience.summary()

    def topology_descriptor(self) -> Dict[str, Any]:
        """This run's topology/sharding descriptor (ISSUE 14): mesh shape,
        process count, sharding tier, the resolved ``shard_updates``, and
        the gradient transport's state-layout (per-bucket padding is
        world-size-dependent — the ZeRO partition algebra elastic resume
        re-maps through).  Embedded in every manifest this facade writes;
        compared against a checkpoint's saved descriptor at resume."""
        from stoke_tpu.configs import comm_shard_updates

        st = self._status_obj
        mesh = self._mesh
        params = self._variables["params"]
        leaves = jax.tree_util.tree_leaves(params)
        comm = None
        transport = getattr(self._engine, "transport", None)
        if transport is not None:
            comm = transport.layout_descriptor(params)
        return {
            "version": 1,
            "process_count": int(jax.process_count()),
            "device_count": int(mesh.size) if mesh is not None else 1,
            "mesh_axes": (
                list(mesh.axis_names) if mesh is not None else None
            ),
            "mesh_shape": (
                [int(mesh.shape[a]) for a in mesh.axis_names]
                if mesh is not None
                else None
            ),
            "tier": st.sharding_tier.value,
            "shard_updates": bool(
                comm_shard_updates(st.comm_config, st.sharding_tier)
            ),
            "axis_name": (
                self._rules.axis_name if self._rules is not None else None
            ),
            "param_leaves": len(leaves),
            "param_elems": int(
                sum(
                    int(np.prod(l.shape)) if l.shape else 1 for l in leaves
                )
            ),
            "comm": comm,
        }

    def _descriptor_incompatible(
        self, saved: Optional[Dict[str, Any]]
    ) -> Optional[str]:
        """Why a saved topology descriptor CANNOT serve this run (None =
        compatible; topology differences are fine — that is what elastic
        resume re-shards across).  Genuinely incompatible means the state
        itself cannot re-map: a different parameter tree.  The returned
        reason names the remedy (the quarantine record an operator reads)."""
        if not saved:
            return None  # legacy manifest without a descriptor
        cur = self.topology_descriptor()
        for key in ("param_elems", "param_leaves"):
            if key in saved and saved[key] != cur[key]:
                return (
                    f"incompatible checkpoint: saved {key}={saved[key]} "
                    f"vs current {key}={cur[key]} — the checkpoint was "
                    f"written by a different MODEL; resume with the "
                    f"saving architecture, or point resume() at this "
                    f"run's own checkpoint root"
                )
        return None

    @staticmethod
    def _topology_changed(
        saved: Optional[Dict[str, Any]], cur: Dict[str, Any]
    ) -> bool:
        """Did the fleet change shape between save and resume?  (The
        ``resilience/elastic_resumes`` accounting predicate.)"""
        if not saved:
            return False
        return any(
            saved.get(k) != cur.get(k)
            for k in (
                "mesh_shape", "process_count", "device_count", "tier",
                "shard_updates",
            )
        )

    def resume(self, path: Optional[str] = None, name: str = "stoke") -> bool:
        """Restore the newest VALID checkpoint and the step counters; the
        auto-resume half of preemption survival (ISSUE 7).

        Discovery order: the resilience emergency root first (a preempted
        run's freshest state lives there), then the explicit ``path`` (or
        ``CheckpointConfig.auto_path``).  Candidates are ordered by
        backward step across all roots and each is validated against its
        ``manifest.json`` digests before being trusted — a corrupt or
        partially-written tag is QUARANTINED (renamed under
        ``<root>/quarantine/``, never deleted) and discovery falls back to
        the next-newest valid tag.  An emergency checkpoint additionally
        restores the out-of-payload state its extras carried (rng, loss
        EMA, error-feedback residual), so a resumed trajectory is
        bit-identical to an uninterrupted one.

        Multi-host: rank 0 verifies and quarantines (one validator —
        concurrent quarantine renames from N ranks would race), then
        broadcasts its (root, step) pick so every rank restores the same
        tag.

        Returns True when a checkpoint was restored; False when none
        (valid) exists — start fresh.  Works without a
        ``ResilienceConfig`` too (then: no manifest requirement, no
        quarantine — invalid tags are skipped in place)."""
        from stoke_tpu.resilience import (
            find_latest_valid_checkpoint,
            list_checkpoints,
            read_manifest,
        )

        mon = self._resilience
        ckpt_cfg = self._status_obj.checkpoint_config
        roots = []
        if mon is not None:
            roots.append((mon.cfg.save_path, mon.cfg.save_name))
        if path:
            roots.append((path, name))
        elif ckpt_cfg.auto_path:
            roots.append((ckpt_cfg.auto_path, ckpt_cfg.auto_name))
        if not roots:
            return False
        # the newest backward step recorded ANYWHERE (valid or not), taken
        # BEFORE quarantine renames: the lost-steps accounting below
        # charges the gap between it and the tag actually restored
        newest_step = max(
            (
                c["step"]
                for root, nm in roots
                for c in list_checkpoints(root, nm)
            ),
            default=None,
        )
        verify = mon.cfg.verify_on_resume if mon is not None else True
        quarantine = mon.cfg.quarantine if mon is not None else False

        manifest_cache: Dict[str, Any] = {}

        def _validate_descriptor(tag_dir):
            """Post-digest candidate check (ISSUE 14): a checkpoint whose
            topology descriptor cannot serve this run is quarantined with
            the remedy named, never crash-restored.  Topology DIFFERENCES
            pass — re-sharding them is elastic resume's whole point.  The
            parsed manifest is cached so the elastic-resume decision below
            reads the SAME descriptor that passed validation."""
            manifest = read_manifest(tag_dir)
            manifest_cache[tag_dir] = manifest
            topo = (manifest or {}).get("topology")
            reason = self._descriptor_incompatible(topo)
            if reason is not None:
                return False, reason
            return True, "ok"

        def _on_quarantine(tag_dir, dest, reason):
            self.warn(
                f"quarantined corrupt checkpoint {tag_dir} -> "
                f"{dest or '<rename failed>'} ({reason})"
            )
            if mon is not None:
                mon.note_quarantined(tag_dir, dest, reason)

        if jax.process_count() > 1:
            # one validator, one choice: rank 0 verifies/quarantines, then
            # BROADCASTS its (root, step) pick — peers re-discovering by
            # meta.json presence could disagree with rank 0 whenever a
            # quarantine rename failed, quarantine is off, or the roots
            # are per-host local disks, and ranks loading different tags
            # is an SPMD hang or silent divergence.  Every root name in
            # ``roots`` is concrete, so (root index, step) reconstructs
            # the tag deterministically on every rank.
            from jax.experimental import multihost_utils

            from stoke_tpu.io_ops import checkpoint_tag

            pick = np.array([-1, -1], np.int64)
            if self.is_rank_0:
                cand = find_latest_valid_checkpoint(
                    roots,
                    verify=verify,
                    quarantine=quarantine,
                    on_quarantine=_on_quarantine,
                    validate_fn=_validate_descriptor,
                )
                if cand is not None:
                    pick = np.array(
                        [
                            # match root AND name: the emergency root and
                            # auto_path may share a directory (distinct
                            # names keep their prune cadences apart)
                            next(
                                i for i, (r, n) in enumerate(roots)
                                if r == cand["root"] and n == cand["name"]
                            ),
                            cand["step"],
                        ],
                        np.int64,
                    )
            pick = np.asarray(multihost_utils.broadcast_one_to_all(pick))
            if pick[0] < 0:
                cand = None
            else:
                root, nm = roots[int(pick[0])]
                tag = checkpoint_tag(nm, int(pick[1]))
                cand = {
                    "root": root,
                    "tag": tag,
                    "tag_dir": os.path.join(root, tag),
                    "name": nm,
                    "step": int(pick[1]),
                }
        else:
            cand = find_latest_valid_checkpoint(
                roots,
                verify=verify,
                quarantine=quarantine,
                on_quarantine=_on_quarantine,
                validate_fn=_validate_descriptor,
            )
        if cand is None:
            return False
        manifest = manifest_cache.get(cand["tag_dir"])
        if manifest is None:
            # multi-host non-validating path (rank 0 validated + broadcast)
            manifest = read_manifest(cand["tag_dir"])
        saved_topo = (manifest or {}).get("topology")
        extras = self.load(cand["root"], tag=cand["tag"])
        rs = extras.get("resilience") if isinstance(extras, dict) else None
        if rs:
            self._restore_resume_state(rs)
        if mon is not None:
            lost = None
            if newest_step is not None:
                # backward-step gap -> optimizer steps (the unit the
                # resumed_step gauge uses)
                lost = max(0, newest_step - cand["step"]) // max(
                    self._status_obj.grad_accum, 1
                )
            mon.note_resumed(self._optimizer_steps, lost_steps=lost)
            cur_topo = self.topology_descriptor()
            if self._topology_changed(saved_topo, cur_topo):
                # topology-elastic resume (ISSUE 14): the fleet that
                # resumed is NOT the fleet that saved — params/opt/EF
                # state were re-sharded onto the new layout at load
                mon.note_elastic_resume(saved_topo, cur_topo)
                self.info(
                    f"elastic resume: checkpoint saved on mesh "
                    f"{(saved_topo or {}).get('mesh_shape')} "
                    f"(tier {(saved_topo or {}).get('tier')}), resumed "
                    f"onto {cur_topo.get('mesh_shape')} "
                    f"(tier {cur_topo.get('tier')})"
                )
        self.info(
            f"resumed from {cand['tag_dir']} at optimizer step "
            f"{self._optimizer_steps}"
        )
        return True

    def _resilience_boundary(self, window: int = 1) -> None:
        """Optimizer-step-boundary hook: drives the fault injector and —
        when a preemption notice arrived mid-step — runs the
        drain→save→exit sequence HERE, on the training thread, with the
        step complete and the engine state consistent (the signal handler
        itself only sets a flag)."""
        mon = self._resilience
        if mon is None:
            return
        # host-wall EMA of one optimizer step (resilience-on only; two
        # perf_counter reads per boundary): the preemption bundle's
        # lost-goodput price basis
        now = time.perf_counter()
        if self._last_boundary_t is not None and window > 0:
            per_step = (now - self._last_boundary_t) / max(window, 1)
            self._step_wall_ema = (
                per_step
                if self._step_wall_ema is None
                else 0.7 * self._step_wall_ema + 0.3 * per_step
            )
        self._last_boundary_t = now
        mon.chaos.on_step(self._optimizer_steps, window)
        preempt = mon.preempt_requested
        if jax.process_count() > 1:
            # cross-host agreement: SIGTERM delivery is per-VM and skewed
            # (often only the preempted VM is signaled).  One host entering
            # the emergency save's collectives while a peer dispatches the
            # next SPMD step is a pod-wide hang that burns the whole grace
            # window — so every boundary reduces the local flag across
            # hosts and ALL ranks enter the drain at the same step.  One
            # tiny host-level allgather per optimizer step, only with
            # resilience ON under multi-host (single process: no
            # collective at all, the default-OFF HLO/dispatch guarantee
            # is untouched).
            from jax.experimental import multihost_utils

            flags = np.asarray(
                multihost_utils.process_allgather(
                    np.array([1 if preempt else 0], np.int32)
                )
            )
            if int(flags.max()) and not preempt:
                # a PEER got the notice; drain in lockstep with it
                mon.request_preemption("peer-preemption")
            preempt = bool(int(flags.max()))
        if preempt:
            self._handle_preemption()

    def _handle_preemption(self) -> None:
        mon = self._resilience
        mon.note_preemption_honored()
        step = self._optimizer_steps
        self.warn(
            f"preemption notice ({mon.preempt_signal}) honored at "
            f"optimizer step {step}: draining async saves, writing the "
            f"emergency checkpoint"
        )
        tag_dir = None
        try:
            tag_dir = self._emergency_save()
            mon.note_emergency_saved(tag_dir)
        except Exception as e:
            # a failed emergency save must not mask the preemption exit —
            # the supervisor still restarts from the last periodic tag
            self.warn(f"emergency checkpoint failed: {e!r}")
        if self._health is not None:
            # the post-mortem bundle rides along (fleet verdict included):
            # the restart record shows WHY this host died, not just that
            # it did.  step_ema_s + lost_steps_estimate (ISSUE 14
            # satellite) let the supervisor price the attempt's lost
            # goodput straight from the bundle manifest: 0 lost when the
            # emergency save landed, steps-since-last-durable-save when
            # it failed.
            try:
                self._health.dump(
                    "preemption",
                    extra={
                        "step": step,
                        "signal": mon.preempt_signal,
                        "emergency_tag": tag_dir,
                        "step_ema_s": self._step_wall_ema,
                        "lost_steps_estimate": (
                            0
                            if tag_dir is not None
                            else max(0, step - self._last_save_step)
                        ),
                    },
                )
            except Exception:
                pass
        if mon.cfg.exit_on_preempt:
            # flush sinks before the no-teardown exit; for the in-process
            # PreemptedError path the pipeline stays open (the caller owns
            # the facade's shutdown)
            try:
                self.close_telemetry()
            except Exception:
                pass
        mon.exit_or_raise(step, tag_dir)

    def _emergency_save(self) -> str:
        """Synchronous emergency checkpoint under the resilience root:
        drain the in-flight async saves first (their tags must finish or
        fail before this one claims 'newest'), then write with the
        emergency keep window.  The extras carry the out-of-payload resume
        state (rng / loss EMA / EF residual / counters)."""
        import dataclasses as _dc

        mon = self._resilience
        try:
            # facade drain (not bare wait_for_saves): a successful drain
            # also promotes the pending async save into the durable
            # lost-goodput accounting
            self.wait_for_checkpoint()
        except RuntimeError as e:
            # failed EARLIER async saves must not block the emergency save
            self.warn(f"async checkpoint drain reported failures: {e}")
        cfg = _dc.replace(
            self._status_obj.checkpoint_config,
            async_save=False,
            max_to_keep=mon.cfg.max_to_keep,
        )
        return self._save_with_config(
            mon.cfg.save_path,
            mon.cfg.save_name,
            cfg,
            {"resilience": self._resume_state()},
        )

    def _resume_state(self) -> Dict[str, Any]:
        """Host-side snapshot of the training state that lives OUTSIDE the
        checkpoint payload trees — pickled into the emergency checkpoint's
        extras so a resumed run is bit-identical, not just close."""
        mon = self._resilience
        state: Dict[str, Any] = {
            "optimizer_step": self._optimizer_steps,
            "backward_step": self._backward_steps,
            "preempt_signal": mon.preempt_signal if mon is not None else None,
            "restart_attempt": mon.restarts if mon is not None else 0,
            "rng": self._rng_to_host(),
            "ema_loss": float(jax.device_get(self._rolling_mean_loss)),
            "ema_initialized": self._ema_initialized,
            "skipped_steps": float(jax.device_get(self._skipped_steps)),
        }
        if self._comm_state:
            # error-feedback residual (ISSUE 2 state): without it a
            # resumed int8 run would drop the carried quantization error.
            # _gather_to_host, not device_get: the ISSUE 8 sharded residual
            # spans the GLOBAL data axis, and device_get raises on arrays
            # with non-addressable shards — the consolidation gather is
            # safe here because every rank enters the emergency save
            # (the resilience boundary agreed on the flag collectively)
            from stoke_tpu.io_ops import _gather_to_host

            state["comm_state"] = _gather_to_host(self._comm_state)
            # layout descriptor (ISSUE 14): the key that lets a resume on
            # a DIFFERENT topology re-partition the residual instead of
            # dropping it — bucket padding is world-size-dependent
            state["comm_layout"] = self._engine.transport.layout_descriptor(
                self._variables["params"]
            )
        return state

    def _restore_resume_state(self, rs: Dict[str, Any]) -> None:
        try:
            if rs.get("rng") is not None:
                self._rng_from_host(rs["rng"])
            if rs.get("ema_loss") is not None:
                self._rolling_mean_loss = self._place_scalar_tree(
                    np.float32(rs["ema_loss"])
                )
                self._ema_initialized = bool(rs.get("ema_initialized", True))
            if rs.get("skipped_steps") is not None:
                self._skipped_steps = self._place_scalar_tree(
                    np.float32(rs["skipped_steps"])
                )
            host_comm = rs.get("comm_state")
            if host_comm and self._comm_state:
                saved_desc = rs.get("comm_layout")
                cur_desc = self._engine.transport.layout_descriptor(
                    self._variables["params"]
                )
                if (
                    saved_desc
                    and cur_desc
                    and "residual" in host_comm
                    and "residual" in self._comm_state
                    and (
                        saved_desc["kind"] != cur_desc["kind"]
                        or saved_desc["buckets"] != cur_desc["buckets"]
                        or saved_desc["world"] != cur_desc["world"]
                    )
                ):
                    # topology-elastic residual re-map (ISSUE 14): the
                    # saved layout (bucket padding, sharded vs replicated
                    # packing) differs from this run's — unpack to the
                    # flat per-element vector under the SAVED descriptor,
                    # repack under the CURRENT one (zero.py partition
                    # algebra), then place as usual below
                    from stoke_tpu.parallel.zero import remap_residual

                    host_comm = {
                        **host_comm,
                        "residual": remap_residual(
                            host_comm["residual"],
                            saved_desc,
                            cur_desc,
                            self._comm_state["residual"],
                        ),
                    }

                def _leaf(cur, new):
                    if isinstance(cur, jax.Array):
                        arr = np.asarray(new)
                        if self._rules is not None:
                            return place_global_tree(arr, cur.sharding)
                        return jax.device_put(arr, self._device)
                    return new

                self._comm_state = jax.tree_util.tree_map(
                    _leaf, self._comm_state, host_comm
                )
        except Exception as e:
            # a structurally-incompatible extras blob (model/transport
            # changed between save and resume) degrades to a plain
            # counter-restoring resume instead of failing it
            self.warn(f"could not restore emergency resume extras: {e!r}")

    def _rng_to_host(self) -> Dict[str, Any]:
        k = self._rng
        try:
            if jnp.issubdtype(k.dtype, jax.dtypes.prng_key):
                return {
                    "typed": True,
                    "data": np.asarray(jax.random.key_data(k)),
                }
        except (AttributeError, TypeError):
            pass
        return {"typed": False, "data": np.asarray(jax.device_get(k))}

    def _rng_from_host(self, d: Dict[str, Any]) -> None:
        data = jnp.asarray(np.asarray(d["data"]))
        key = jax.random.wrap_key_data(data) if d.get("typed") else data
        self._rng = self._place_scalar_tree(key)

    @_health_guarded
    @_timed("train_step_window")
    def train_step_window(
        self,
        model_args: Any,
        loss_args: Any = (),
        model_kwargs: Optional[dict] = None,
    ):
        """A whole accumulation window (``grad_accum`` micro-batches) in ONE
        compiled dispatch via ``lax.scan``, apply included.

        Args are stacked micro-batches: each array leaf has shape
        ``[grad_accum, micro_batch, ...]``.  Must be called at a window
        boundary (``grad_accum_counter == 0``).  Returns the per-micro loss
        reports stacked on axis 0.
        """
        if not self._training:
            raise RuntimeError("Stoke -- train_step_window() called in eval mode")
        if self._grad_accum_counter != 0:
            raise RuntimeError(
                "Stoke -- train_step_window() must start at an accumulation "
                f"boundary (counter={self._grad_accum_counter}); finish the "
                "window with backward()/step() or reset() first"
            )
        k = self._status_obj.grad_accum
        if not isinstance(model_args, tuple):
            model_args = (model_args,)
        if not isinstance(loss_args, tuple):
            loss_args = (loss_args,)
        for leaf in jax.tree_util.tree_leaves(
            (model_args, loss_args, model_kwargs or {})
        ):
            if hasattr(leaf, "shape") and (not leaf.shape or leaf.shape[0] != k):
                raise ValueError(
                    f"Stoke -- train_step_window() expects leaves stacked to "
                    f"[grad_accum={k}, ...]; got shape {getattr(leaf, 'shape', ())}"
                )
        margs = self._place_batch(model_args, batch_dim=1)
        mkwargs = self._place_batch(model_kwargs or {}, batch_dim=1)
        sentinel = DeferredOutput(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, *loss_args), {}), is_leaf=is_deferred
        )
        arrays = self._place_batch(
            [l for l in flat if not is_deferred(l)], batch_dim=1
        )
        deferred_info = tuple(
            (i, l._path) for i, l in enumerate(flat) if is_deferred(l)
        )
        (
            reports,
            self._variables,
            new_opt,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            sentinels,
            numerics,
            finite,
        ) = self._engine.window_step(
            self._variables,
            self._opt_materialize(),
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            margs,
            mkwargs,
            arrays,
            treedef,
            deferred_info,
        )
        self._opt_commit(new_opt)
        self._pending = None
        self._backward_steps += k
        # track the window-mean micro loss once (per-micro EMA would need k
        # host round trips; the stacked reports carry the detail)
        mean_report = jax.tree_util.tree_map(lambda r: r.mean(axis=0), reports)
        self._update_loss_tracking(mean_report)
        if self._precision.scaled:
            self._skipped_steps = self._skipped_steps + (
                1.0 - finite.astype(jnp.float32)
            )
        self._optimizer_steps += 1
        self._reset_tracking_window()
        self._observe_numerics(numerics)
        self._observe_health(sentinels)
        self._maybe_log_metrics()
        self._maybe_emit_telemetry()
        self._maybe_auto_save()
        self._resilience_boundary()
        return reports

    @_health_guarded
    @_timed("train_steps")
    def train_steps(
        self,
        model_args: Any,
        loss_args: Any = (),
        model_kwargs: Optional[dict] = None,
        segment_size: Optional[int] = None,
    ):
        """N complete optimizer steps in ONE compiled dispatch (outer
        ``lax.scan`` over steps, inner scan over each accumulation window,
        fused apply per step).

        The TPU-idiomatic answer to dispatch-bound loops: a whole training
        segment is one XLA program, so host dispatch overhead (and, through
        remote-device links, per-dispatch round-trip latency) is amortized
        over ``n x grad_accum`` micro-batches.

        Args are stacked micro-batches: each array leaf has shape
        ``[total_micro, micro_batch, ...]`` where ``total_micro`` is a
        multiple of ``grad_accum``; ``n = total_micro // grad_accum``
        optimizer steps run.  Must be called at a window boundary.  Returns
        per-micro loss reports stacked to ``[n, grad_accum, ...]``.

        **Memory**: the whole stacked segment is resident in device memory
        for the dispatch (it competes with activations for HBM — see
        docs/performance.md).  ``segment_size=c`` bounds this by streaming
        the segment host→device in chunks of ``c`` optimizer steps (one
        dispatch per chunk, identical numerics and loss tracking); without
        it, a guard raises a clear error when the stack obviously exceeds
        the device's free memory instead of letting the runtime OOM.

        Loss tracking: the EMA advances once per optimizer step with that
        step's window-mean loss (same semantics as ``n`` calls to
        ``train_step_window``).  Auto-save and metric logging fire at the end
        of the segment whenever their step cadence was crossed anywhere
        inside it (a save_every_n_steps boundary mid-segment is honored, just
        deferred to the segment end).
        """
        if not self._training:
            raise RuntimeError("Stoke -- train_steps() called in eval mode")
        if self._grad_accum_counter != 0:
            raise RuntimeError(
                "Stoke -- train_steps() must start at an accumulation "
                f"boundary (counter={self._grad_accum_counter}); finish the "
                "window with backward()/step() or reset() first"
            )
        k = self._status_obj.grad_accum
        if not isinstance(model_args, tuple):
            model_args = (model_args,)
        if not isinstance(loss_args, tuple):
            loss_args = (loss_args,)
        n = None
        seg_bytes = 0
        for leaf in jax.tree_util.tree_leaves(
            (model_args, loss_args, model_kwargs or {})
        ):
            if hasattr(leaf, "shape") and leaf.shape:
                if leaf.shape[0] % k:
                    raise ValueError(
                        f"Stoke -- train_steps() leaves must stack "
                        f"[total_micro, micro_batch, ...] with total_micro a "
                        f"multiple of grad_accum={k}; got {leaf.shape}"
                    )
                if n is None:
                    n = leaf.shape[0] // k
                elif leaf.shape[0] // k != n:
                    raise ValueError(
                        "Stoke -- train_steps() leaves disagree on the "
                        "number of stacked micro-batches"
                    )
                # the memory guard estimates the upcoming host->device
                # transfer: arrays already resident on an accelerator are
                # counted in the device's bytes_in_use (double-billing
                # them would spuriously trip the guard), while host-side
                # data — numpy OR jax Arrays committed to a CPU device —
                # still has to cross the wire and counts
                if not _on_accelerator(leaf):
                    seg_bytes += getattr(leaf, "nbytes", 0)
        if not n:
            raise ValueError(
                "Stoke -- train_steps() found no stacked array leaves"
            )
        # the batch dim shards over the data axis, so each device holds only
        # its 1/world_size share of the stacked segment
        seg_bytes_per_device = seg_bytes // max(self.world_size, 1)
        if segment_size is not None and segment_size < 1:
            raise ValueError(
                f"Stoke -- segment_size must be >= 1, got {segment_size}"
            )
        if segment_size is not None and segment_size < n:
            # chunked variant: stream the segment host->device one chunk at
            # a time; each chunk is a full train_steps dispatch, so counters,
            # EMA, auto-save and metric cadence compose exactly
            def _slice(t, sl):
                return jax.tree_util.tree_map(
                    lambda l: l[sl]
                    if hasattr(l, "shape") and getattr(l, "shape", ())
                    else l,
                    t,
                )

            chunk_reports = []
            for c0 in range(0, n, segment_size):
                c1 = min(c0 + segment_size, n)
                sl = slice(c0 * k, c1 * k)
                chunk_reports.append(
                    self.train_steps(
                        _slice(model_args, sl),
                        _slice(loss_args, sl),
                        _slice(model_kwargs, sl)
                        if model_kwargs is not None
                        else None,
                    )
                )
            return jax.tree_util.tree_map(
                lambda *rs: jnp.concatenate(rs, axis=0), *chunk_reports
            )
        _check_segment_memory(seg_bytes_per_device, _device_memory_stats())

        def _fold(t):
            return jax.tree_util.tree_map(
                lambda l: l.reshape((n, k) + tuple(l.shape[1:]))
                if hasattr(l, "shape") and l.shape
                else l,
                t,
            )

        margs = self._place_batch(_fold(model_args), batch_dim=2)
        mkwargs = self._place_batch(_fold(model_kwargs or {}), batch_dim=2)
        sentinel = DeferredOutput(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, *loss_args), {}), is_leaf=is_deferred
        )
        arrays = self._place_batch(
            _fold([l for l in flat if not is_deferred(l)]), batch_dim=2
        )
        deferred_info = tuple(
            (i, l._path) for i, l in enumerate(flat) if is_deferred(l)
        )
        if self._health is not None:
            # one dispatch legitimately covers n optimizer steps: re-arm
            # the watchdog with the per-segment deadline (n x timeout)
            self._health.arm_watchdog(steps=n)
        (
            reports,
            self._variables,
            new_opt,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            sentinels,
            numerics,
            skipped,
        ) = self._engine.multi_step(
            self._variables,
            self._opt_materialize(),
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            margs,
            mkwargs,
            arrays,
            treedef,
            deferred_info,
        )
        self._opt_commit(new_opt)
        self._pending = None
        self._backward_steps += n * k
        # EMA per optimizer step: ONE device reduction ([n, k, ...] ->
        # [n, ...]) and ONE host transfer for the whole segment, then a pure
        # host loop — not n per-step device dispatches (VERDICT r2 weak #8)
        step_means = jax.device_get(
            jax.tree_util.tree_map(lambda r: r.mean(axis=1), reports)
        )
        for i in range(n):
            self._update_loss_tracking(
                jax.tree_util.tree_map(lambda m: m[i], step_means)
            )
            self._reset_tracking_window()
        if self._precision.scaled:
            self._skipped_steps = self._skipped_steps + skipped
        self._optimizer_steps += n
        self._observe_numerics(numerics, window=n)
        self._observe_health(sentinels, window=n)
        self._maybe_log_metrics(window=n)
        self._maybe_emit_telemetry(window=n)
        self._maybe_auto_save(window=n)
        self._resilience_boundary(window=n)
        return reports

    def reset(self) -> None:
        """Zero the accumulation buffer and counters without stepping
        (reference ``reset`` helpers, stoke.py:1042-1058)."""
        self._grad_buf = self._engine.init_grad_buffer(self._variables)
        self._grad_accum_counter = 0
        self._pending = None
        self._reset_tracking_window()

    # ------------------------------------------------------------------ #
    # loss tracking (reference stoke.py:371-520, :914-958)
    # ------------------------------------------------------------------ #

    def _loss_total(self, report) -> jax.Array:
        leaves = jax.tree_util.tree_leaves(report)
        total = leaves[0]
        for l in leaves[1:]:
            total = total + l
        return total

    def _update_loss_tracking(self, report) -> None:
        # losses arrive divided by grad_accum; track the undivided micro loss
        micro = self._loss_total(report) * self._status_obj.grad_accum
        self._last_step_loss = micro
        self._agg_loss = self._agg_loss + micro
        self._agg_count += 1
        w = self._ema_weight
        if not self._ema_initialized:
            self._rolling_mean_loss = micro
            self._ema_initialized = True
        else:
            self._rolling_mean_loss = (
                1.0 - w
            ) * self._rolling_mean_loss + w * micro

    def _reset_tracking_window(self) -> None:
        self._agg_loss = self._zero_scalar()
        self._agg_count = 0

    def detach_and_sync_loss(self, loss: Any, user_reduction: str = "mean") -> float:
        """Host float of a (possibly structured) loss, synced across the mesh
        (reference detach_and_sync_loss, distributed.py:619-646 — there a
        barrier + allreduce + ``.item()``; here the value is already the
        global-batch loss, so this is just the host transfer).

        ``LossReduction.sum`` reproduces the reference's summed-across-ranks
        value (hvd Sum, distributed.py:1461-1490).  With a **mean**-reduced
        ``loss_fn`` (the default contract) that is exactly
        ``world_size × global-batch mean`` — per-device batches are equal, so
        the sum of per-rank means equals world × global mean.  If your
        ``loss_fn`` **sums** over the batch instead, pass
        ``user_reduction="sum"``: the value is then already a global sum and
        no scaling is applied."""
        if user_reduction not in ("mean", "sum"):
            raise ValueError(
                f"user_reduction must be 'mean' or 'sum', got {user_reduction!r}"
            )
        val = float(jax.device_get(self._loss_total(loss)))
        if (
            self._status_obj.dp_config.loss_reduction is LossReduction.sum
            and user_reduction == "mean"
        ):
            val *= self.world_size
        return val

    @property
    def ema_loss(self) -> float:
        """Rolling EMA of the (undivided) micro losses (reference
        stoke.py:914-958)."""
        return float(jax.device_get(self._rolling_mean_loss))

    @property
    def step_loss(self) -> Optional[float]:
        if self._last_step_loss is None:
            return None
        return float(jax.device_get(self._last_step_loss))

    @property
    def mean_accumulated_loss(self) -> Optional[float]:
        if self._agg_count == 0:
            return None
        return float(jax.device_get(self._agg_loss)) / self._agg_count

    def print_ema_loss(self, prepend_msg: str = "EMA Loss") -> None:
        """(reference print_ema_loss, stoke.py:447-460)"""
        self.print_on_devices(f"{prepend_msg}: {self.ema_loss:.6f}")

    def print_mean_accumulated_synced_loss(
        self, prepend_msg: str = "Mean accumulated loss"
    ) -> None:
        """(reference stoke.py:462-482)"""
        v = self.mean_accumulated_loss
        self.print_on_devices(
            f"{prepend_msg}: {v:.6f}" if v is not None else f"{prepend_msg}: n/a"
        )

    def print_synced_loss(
        self, loss: Any, prepend_msg: str = "Step loss", scale_by_accum: bool = True
    ) -> None:
        """(reference print_synced_loss, stoke.py:484-505)"""
        v = self.detach_and_sync_loss(loss)
        if scale_by_accum:
            v *= self._status_obj.grad_accum
        self.print_on_devices(f"{prepend_msg}: {v:.6f}")

    # ------------------------------------------------------------------ #
    # printing / rank helpers (reference distributed.py:238-271)
    # ------------------------------------------------------------------ #

    @property
    def rank(self) -> int:
        """Process index (reference rank property; on TPU, one process per
        host, each feeding its local devices)."""
        return jax.process_index()

    @property
    def is_rank_0(self) -> bool:
        return self.rank == 0

    @property
    def world_size(self) -> int:
        return self._status_obj.world_size or 1

    @property
    def n_processes(self) -> int:
        return jax.process_count()

    def print_on_devices(self, msg: str, rank: Optional[int] = 0) -> None:
        """Print on a specific process rank, or all when rank=None
        (reference print_device, distributed.py:238-271)."""
        if rank is None or self.rank == rank:
            unrolled_print(f"(rank {self.rank}) {msg}")

    def info(self, msg: str) -> None:
        if self.is_rank_0:
            unrolled_print(f"INFO: {msg}")

    def warn(self, msg: str) -> None:
        if self.is_rank_0:
            unrolled_print(f"WARN: {msg}")

    def barrier(self) -> None:
        """Cross-process sync (reference barrier/hvd.join,
        distributed.py:671-692).  In-step SPMD needs no barriers; this exists
        for host-side coordination around IO.

        Instrumented (ISSUE 5 satellite): the elapsed wait — near zero for
        the last arrival, the full skew for the first — lands in
        ``sync/barrier_wait_s`` / ``sync/barriers_total`` of every live
        telemetry registry, FleetConfig or not, so cross-process sync time
        is visible in the wall-clock breakdown and (with a ``FleetConfig``)
        chargeable to the straggler host."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            from stoke_tpu.telemetry.fleet import timed_sync

            with timed_sync("barrier"):
                multihost_utils.sync_global_devices("stoke_barrier")

    def block_until_ready(self) -> None:
        """Wait for all in-flight device work (bench/test helper)."""
        jax.block_until_ready(
            (self._variables, self._opt_state, self._grad_buf)
        )

    # ------------------------------------------------------------------ #
    # profiling / observability (SURVEY.md §5 — first-class here vs the
    # reference's DeepSpeed flops-profiler passthrough, configs.py:252-279)
    # ------------------------------------------------------------------ #

    def _clock(self, phase: str):
        """Accumulating host-side timer for the wall-clock breakdown —
        a thin alias onto the telemetry registry (``facade/<phase>_s``
        counters) plus a labeled xprof span."""
        import contextlib

        if not self._wall_clock_enabled:
            return contextlib.nullcontext()
        return self._telemetry.phase(phase)

    @property
    def telemetry(self) -> Telemetry:
        """The run's telemetry pipeline (registry always live; sinks and
        collectors attach when a ``TelemetryConfig`` is supplied)."""
        return self._telemetry

    @property
    def wall_clock_breakdown(self) -> Dict[str, float]:
        """Cumulative host seconds per facade phase (enable via
        ``ProfilerConfig(wall_clock_breakdown=True)`` or any
        ``TelemetryConfig``; reference configs.py:540).  Host dispatch time
        only — device execution is asynchronous; use :meth:`profile_trace`
        for device timelines.  Registry-backed alias: the same numbers flow
        into the telemetry sinks as ``facade/<phase>_s``."""
        return self._telemetry.wall_clock_breakdown()

    def print_wall_clock_breakdown(self) -> None:
        # the goodput/* entries (attribution on) partition TOTAL wall
        # clock, not host-dispatch time — percentaging each group against
        # its own total keeps both reports truthful side by side
        breakdown = self.wall_clock_breakdown
        phases = {
            k: v for k, v in breakdown.items() if not k.startswith("goodput/")
        }
        goodput = {
            k: v for k, v in breakdown.items() if k.startswith("goodput/")
        }
        for group in (phases, goodput):
            total = sum(group.values()) or 1.0
            for phase, secs in sorted(group.items(), key=lambda kv: -kv[1]):
                self.print_on_devices(
                    f"wall_clock {phase}: {secs:.3f}s "
                    f"({100 * secs / total:.1f}%)"
                )

    def profile_trace(self, name: str = "stoke"):
        """Context manager capturing a ``jax.profiler`` trace (serves the
        TensorBoard profile plugin / xprof) when ``ProfilerConfig.trace_dir``
        is set; no-op otherwise.

        Usage:
            with stoke.profile_trace():
                for batch in loader: ...
        """
        import contextlib

        cfg = self._status_obj.profiler_config
        if cfg.trace_dir is None:
            return contextlib.nullcontext()

        @contextlib.contextmanager
        def _trace():
            jax.profiler.start_trace(cfg.trace_dir)
            try:
                yield
            finally:
                jax.profiler.stop_trace()
                self.info(f"profiler trace written to {cfg.trace_dir}")

        return _trace()

    def estimate_step_flops(
        self, model_args: Any, loss_args: Any = ()
    ) -> Optional[float]:
        """XLA cost-analysis FLOPs estimate of one fused optimizer step
        (replaces the reference's DeepSpeed flops profiler passthrough,
        distributed.py:985-1004).  Thin wrapper over
        :meth:`estimate_step_cost` (the shared CostCard path, ISSUE 4);
        returns None if the backend does not report cost analysis —
        warned ONCE per backend, with the negative result cached so
        repeated calls neither warn nor re-lower."""
        card = self.estimate_step_cost(model_args, loss_args)
        if card is None or not card.flops:
            return None
        return float(card.flops)

    def estimate_step_cost(self, model_args: Any, loss_args: Any = ()):
        """Analytic :class:`~stoke_tpu.telemetry.attribution.CostCard` of
        one fused optimizer step at these batch shapes: FLOPs, bytes
        accessed, and (when an ``AttributionConfig`` supplies peaks) the
        roofline-optimal step time.  The same cost-analysis funnel the
        live attribution gauges and ``scripts/flops_probe.py`` use.
        Returns None when the backend reports no cost analysis."""
        if not isinstance(model_args, tuple):
            model_args = (model_args,)
        if not isinstance(loss_args, tuple):
            loss_args = (loss_args,)
        from stoke_tpu.engine import DeferredOutput as _D
        from stoke_tpu.telemetry.attribution import (
            CostCard,
            cost_analysis_of,
        )

        margs = self._place_batch(model_args)
        sentinel = _D(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, *loss_args), {}), is_leaf=is_deferred
        )
        arrays = self._place_batch([l for l in flat if not is_deferred(l)])
        deferred_info = tuple(
            (i, l._path) for i, l in enumerate(flat) if is_deferred(l)
        )
        fn = self._engine._build_fused(treedef, deferred_info, True)
        # abstract avals for spilled state: lowering must not page the whole
        # optimizer state back into HBM just to trace shapes
        if self._disk_store is not None and self._disk_store.spilled:
            opt_arg = self._disk_store.abstract()
        else:
            opt_arg = self._opt_state
        cost = cost_analysis_of(
            fn,
            self._variables,
            opt_arg,
            self._grad_buf,
            self._scaler_state,
            self._comm_state,
            self._rng,
            margs,
            {},
            arrays,
        )
        if cost is None:
            return None
        acfg = self._status_obj.attribution_config
        return CostCard.from_cost(
            cost,
            "fused",
            1,
            peak_tflops=acfg.peak_tflops if acfg is not None else 0.0,
            peak_hbm_gbps=acfg.peak_hbm_gbps if acfg is not None else 0.0,
        )

    # ------------------------------------------------------------------ #
    # DataLoader factory (reference stoke.py:737-851)
    # ------------------------------------------------------------------ #

    def DataLoader(self, dataset, **kwargs):
        """Build a :class:`~stoke_tpu.data.StokeDataLoader` wired to this
        run's topology: the per-process loader batch is
        ``batch_size_per_device × local-mesh-share`` and batches land sharded
        over the mesh data axis (reference stoke.py:737-851 + SURVEY.md §3.3).
        A DistributedSampler is required when multiple processes each load a
        slice (reference stoke.py:822-826)."""
        from stoke_tpu.data import StokeDataLoader

        world = self.world_size
        per_process = world // max(jax.process_count(), 1)
        batch_size = self._status_obj.batch_size * max(per_process, 1)
        if jax.process_count() > 1 and kwargs.get("sampler") is None:
            raise ValueError(
                "Stoke -- multi-process runs require a distributed sampler "
                "(see BucketedDistributedSampler / DistributedSampler) — "
                "reference stoke.py:822-826"
            )
        fcfg = self._status_obj.fleet_config
        if (
            "rebalancer" not in kwargs
            and fcfg is not None
            and getattr(fcfg, "rebalance", False)
            and self._fleet is not None
            and jax.process_count() > 1
        ):
            # skew-reactive input rebalancing (ISSUE 14): build the
            # actuator and hand it to both sides — the fleet monitor
            # proposes bounded share shifts at straggler-streak
            # boundaries, the loader applies them at an agreed future
            # fetch index.  Single-process runs skip it entirely (a fleet
            # of one has nothing to rebalance; behavior is untouched).
            from stoke_tpu.data import InputRebalancer

            rb = InputRebalancer(
                n_hosts=jax.process_count(),
                rank=jax.process_index(),
                batch_size=batch_size,
                max_frac=fcfg.rebalance_max_frac,
                # apply strictly past every host's prefetch lookahead
                apply_slack=int(kwargs.get("prefetch", 2)) + 2,
            )
            self._fleet.attach_rebalancer(rb)
            kwargs["rebalancer"] = rb
        return StokeDataLoader(
            dataset,
            batch_size=batch_size,
            place_fn=self._place_batch,
            telemetry=self._telemetry if self._telemetry.enabled else None,
            **kwargs,
        )

    # ------------------------------------------------------------------ #
    # serving (ISSUE 9: continuous-batching inference behind the facade)
    # ------------------------------------------------------------------ #

    def serve(self, **overrides):
        """Build the continuous-batching inference engine over this run's
        model + current params (ISSUE 9 tentpole entry point).

        Requires a :class:`~stoke_tpu.configs.ServeConfig` in
        ``Stoke(configs=[...])`` and a :class:`~stoke_tpu.models.gpt.GPT`
        model — serving is the paged-KV decode path models/gpt.py grew;
        ``overrides`` are ``ServeConfig`` field replacements applied for
        this engine only (e.g. ``stoke.serve(max_seqs=16)``).

        The engine inherits this facade's plumbing: the telemetry
        pipeline (``serve/*`` JSONL fields + Prometheus gauges land in
        the same sinks), and — with a ``CompileConfig`` — the PR-6
        AOT program ledger, so prefill/decode warm-start like the step
        programs do.  The config's presence alone changes NOTHING about
        training (it is only read here; tests assert step-program HLO
        bit-identity).

        SLOs (ISSUE 16): ``engine.submit(..., slo=RequestSLO(...))``
        tags requests with a priority class + TTFT/TPOT deadlines
        (defaults from ``ServeConfig.slo_ttft_target_s`` /
        ``slo_tpot_target_s``); ``engine.summary()["slo"]`` then carries
        per-class attainment, goodput-under-SLO tokens/s, and queue-ETA
        forecasts, and ``engine.slo.attributions`` the span-walked
        queue/prefill/decode violation buckets (docs/serving.md, "SLOs &
        priority classes").

        Params note: the engine reads the facade's LIVE params.  The
        int8/bf16 quantized stores copy into their own (smaller) buffers;
        ``quant="none"`` ALIASES the training params — build the engine
        after training finishes, and rebuild it (``serve()`` again) if
        you train further, since the step programs donate those buffers.
        """
        import dataclasses as _dc

        from stoke_tpu.models.gpt import GPT
        from stoke_tpu.serving import ServingEngine
        from stoke_tpu.status import StokeValidationError

        scfg = self._status_obj.serve_config
        if scfg is None:
            raise StokeValidationError(
                "Stoke.serve() requires a ServeConfig — add one to "
                "Stoke(configs=[ServeConfig(...)]) (the serving stack is "
                "opt-in; docs/serving.md)"
            )
        if overrides:
            scfg = _dc.replace(scfg, **overrides)
            # replaced fields re-validate through the same status rules a
            # constructor-supplied config passes — with THIS run's device:
            # the pallas-decode-needs-TPU rule (ISSUE 13) must judge the
            # override against the facade's real backend, not the
            # StokeStatus default.  Cross-config rules (cost_cards needs
            # an AttributionConfig, ISSUE 18) must see the run's real
            # companions, so they ride along.
            companions = [
                c
                for c in (
                    self._status_obj.attribution_config,
                    self._status_obj.telemetry_config,
                )
                if c is not None
            ]
            StokeStatus(
                batch_size_per_device=self._status_obj.batch_size,
                device=self._status_obj.device,
                configs=[scfg] + companions,
            )
        module = getattr(self._adapter, "module", None)
        if not isinstance(module, GPT):
            raise TypeError(
                f"Stoke.serve() serves GPT models (the paged-KV decode "
                f"forward lives in models/gpt.py); this facade wraps "
                f"{type(module or self._adapter).__name__}"
            )
        kv_sharding = None
        if self._mesh is not None:
            # replicated pool on the mesh: each data-parallel serving
            # replica owns a full cache (model-sharded pools are a
            # placement change in PagedKVCache, not an engine change)
            kv_sharding = NamedSharding(self._mesh, P())
        engine = ServingEngine(
            module,
            self.params,
            scfg,
            telemetry=self._telemetry,
            compile_cache=self._compile_cache,
            kv_sharding=kv_sharding,
            # roofline observatory (ISSUE 18): the run's AttributionConfig
            # carries the hardware peaks the serve roofline divides by
            attribution=(
                self._status_obj.attribution_config
                if scfg.cost_cards
                else None
            ),
            # HBM capacity observatory (ISSUE 19): the engine constructs
            # its OWN observatory (quantized weights + KV pool components)
            # and runs the serve-side OOM pre-flight at construction
            memory=self._status_obj.memory_config,
        )
        if self._opsplane is not None:
            # the live ops plane's /requests + /statusz serving block
            # (ISSUE 20) follow the newest engine this facade built
            self._opsplane.attach_engine(engine)
        if self._numerics is not None and engine.quant_errors_by_group:
            # per-layer dequant-error attribution (ISSUE 12): the engine
            # computed it once at quantize time; installing it here is
            # what surfaces numerics/quant_err_max / quant_err_group in
            # this run's JSONL records and numerics_summary
            self._numerics.set_quant_errors(engine.quant_errors_by_group)
        return engine

    # ------------------------------------------------------------------ #
    # save / load (reference stoke.py:1060-1142)
    # ------------------------------------------------------------------ #

    def save(
        self,
        path: str,
        name: str = "stoke",
        extras: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Unified checkpoint save (reference stoke.py:1060-1106).  Layout is
        chosen by ``CheckpointConfig.format``; the payload schema mirrors the
        reference (io_ops.py:224-236): counters, status dict, model/optimizer
        /scaler state, user extras."""
        return self._save_with_config(
            path, name, self._status_obj.checkpoint_config, extras
        )

    @_timed("save")
    def _save_with_config(
        self,
        path: str,
        name: str,
        config,
        extras: Optional[Dict[str, Any]],
    ) -> str:
        """The shared save body, parameterized on the ``CheckpointConfig``
        so the emergency path (ISSUE 7) can force a synchronous write with
        its own keep window without mutating the run's config."""
        from stoke_tpu import io_ops

        # the sown "losses" collection is transient per-step output (MoE aux
        # terms), not state: excluding it keeps checkpoints loadable across
        # model versions that add/remove sown losses, and it is regenerated
        # by the first forward after a restore anyway
        vars_to_save = {
            k: v for k, v in self._variables.items() if k != "losses"
        }
        mon = self._resilience
        with_manifest = mon is not None and mon.cfg.manifest
        with trace_span("stoke/io", track="io"):
            tag_dir = io_ops.save_checkpoint(
                path=path,
                name=name,
                variables=vars_to_save,
                opt_state=self._opt_materialize(),
                scaler_state=self._scaler_state,
                counters={
                    "backward_step": self._backward_steps,
                    "grad_accum_step": self._grad_accum_counter,
                    "optimizer_step": self._optimizer_steps,
                },
                status=self._status_obj.to_dict(),
                extras=extras,
                config=config,
                backward_step=self._backward_steps,
                grad_buf=(
                    self._grad_buf if self._grad_accum_counter > 0 else None
                ),
                # integrity manifests (ISSUE 7): every checkpoint this
                # facade writes under a ResilienceConfig carries per-file
                # digests — the record resume() validates before trusting
                manifest=with_manifest,
                # topology/sharding descriptor (ISSUE 14): what elastic
                # resume re-shards against — rides the manifest
                topology=(
                    self.topology_descriptor() if with_manifest else None
                ),
                # kill_during_save injector hook (ISSUE 14 satellite)
                chaos=(
                    mon.chaos
                    if mon is not None and mon.chaos.active
                    else None
                ),
                # restart-cost accounting (ISSUE 14 satellite): each save
                # promotes ITS OWN step into "last durable save" only when
                # its write fully lands — sync saves on return, async ones
                # from the background thread after meta.json.  Per-save,
                # so an older save that completed stays counted even when
                # a newer one is still in flight or fails.
                on_durable=functools.partial(
                    self._note_durable_save, self._optimizer_steps
                ),
            )
        if mon is not None and mon.chaos.active:
            # corrupt_save injection (the quarantine path's deterministic
            # trigger) needs the payload bytes on disk; chaos is a test
            # harness, so draining an async save here is acceptable
            if config.async_save and mon.chaos.spec.corrupt_save is not None:
                io_ops.wait_for_saves()
            mon.chaos.note_saved(tag_dir)
        return tag_dir

    @_timed("load")
    def load(
        self, path: str, tag: Optional[str] = None, name: str = "stoke"
    ) -> Dict[str, Any]:
        """Unified checkpoint load (reference stoke.py:1108-1142): restores
        model/optimizer/scaler state *onto the current sharding layout* (the
        FSDP shard-extraction of the reference, io_ops.py:298-306, is just
        "load into the declared shardings" here) and the step counters.  A
        mid-accumulation-window save restores its partial gradient buffer;
        if the checkpoint carries none, the window restarts cleanly."""
        from stoke_tpu import io_ops

        # abstract avals when spilled: the restore template only needs
        # shape/dtype/sharding, and materializing would put ~2x the state in
        # HBM during restore — the exact memory the disk tier exists to avoid
        if self._disk_store is not None and self._disk_store.spilled:
            opt_like = self._disk_store.abstract()
        else:
            opt_like = self._opt_state
        # mirror save(): "losses" is transient output, never checkpointed —
        # load against the stripped template, then re-attach the live
        # collection so the compiled step's state structure is unchanged
        vars_like = {
            k: v for k, v in self._variables.items() if k != "losses"
        }

        def _load(like):
            with trace_span("stoke/io", track="io"):
                return io_ops.load_checkpoint(
                    path=path,
                    tag=tag,
                    variables_like=like,
                    opt_state_like=opt_like,
                    scaler_like=self._scaler_state,
                    config=self._status_obj.checkpoint_config,
                    name=name if tag is None else None,
                    grad_buf_like=self._grad_buf,
                )

        try:
            payload = _load(vars_like)
            loaded_vars = payload["variables"]
            if "losses" in self._variables:
                loaded_vars = {
                    **loaded_vars, "losses": self._variables["losses"]
                }
        except Exception as first_err:
            # legacy layout: a checkpoint saved before sown losses were
            # excluded mismatches the stripped template (consolidated:
            # leaf-count ValueError; sharded: orbax structure errors, which
            # surface as KeyError/TypeError or orbax-specific types — so the
            # retry decision cannot key on the exception class).  Retry with
            # the full template — but if that fails too, surface the
            # ORIGINAL error (a genuine incompatibility), not the retry's.
            # Errors that cannot possibly be a template mismatch skip the
            # retry — a second full restore of a multi-GB sharded checkpoint
            # is expensive and would surface the same error anyway
            if isinstance(first_err, (FileNotFoundError, NotADirectoryError,
                                      PermissionError, IsADirectoryError)):
                raise
            if "losses" not in self._variables:
                raise
            try:
                payload = _load(self._variables)
            except Exception:
                raise first_err
            loaded_vars = payload["variables"]
        self._variables = loaded_vars
        self._opt_commit(payload["opt_state"])
        self._scaler_state = payload["scaler_state"]
        counters = payload["counters"]
        self._backward_steps = counters["backward_step"]
        self._optimizer_steps = counters["optimizer_step"]
        if payload.get("grad_buf") is not None:
            self._grad_buf = payload["grad_buf"]
            self._grad_accum_counter = counters["grad_accum_step"]
        else:
            # no saved buffer → restart the accumulation window from zero
            # rather than under-filling the next optimizer step
            self._grad_buf = self._engine.init_grad_buffer(self._variables)
            self._grad_accum_counter = 0
        return payload.get("extras") or {}

    # ------------------------------------------------------------------ #
    # introspection properties (reference stoke.py:1271-1466)
    # ------------------------------------------------------------------ #

    @property
    def status(self) -> Dict[str, Any]:
        return self._status_obj.status

    def print_status(self) -> None:
        """Pretty-print the full run status (reference status repr,
        status.py:629-654; printed automatically at init when verbose)."""
        if self.is_rank_0:
            unrolled_print(repr(self._status_obj).splitlines())

    @property
    def model_access(self):
        """The underlying model adapter (reference model_access property)."""
        return self._adapter

    @property
    def loss_access(self) -> Callable:
        return self._loss_fn

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def variables(self) -> Dict[str, Any]:
        return self._variables

    @property
    def params(self) -> Any:
        return self._variables["params"]

    @property
    def aux_losses(self) -> Optional[Any]:
        """Latest model-internal auxiliary losses (the flax "losses"
        collection, e.g. MoE load-balancing terms) as of the last training
        step — ``None`` for models that sow none.  These feed the objective
        weighted by ``aux_loss_weight``; they are not part of ``loss()``'s
        report."""
        return self._variables.get("losses")

    @property
    def opt_state(self) -> Any:
        return self._opt_materialize()

    @property
    def scaler(self) -> Dict[str, Any]:
        """Loss-scaler state (reference scaler property / fp16_state_dict,
        stoke.py:1300-1316)."""
        return self._scaler_state

    @property
    def loss_scale(self):
        """Current dynamic loss scale: a float, or (per-loss mode,
        ``PrecisionConfig.num_losses > 1``) a list of one scale per loss."""
        s = jax.device_get(self._scaler_state["scale"])
        if getattr(s, "ndim", 0):
            return [float(v) for v in s]
        return float(s)

    @property
    def mesh(self):
        return self._mesh

    @property
    def sharding_rules(self):
        return self._rules

    @property
    def batch_size(self) -> int:
        return self._status_obj.batch_size

    @property
    def effective_batch_size(self) -> int:
        return self._status_obj.effective_batch_size

    @property
    def grad_accum_steps(self) -> int:
        return self._status_obj.grad_accum

    @property
    def grad_clip(self):
        return self._status_obj.grad_clip

    @property
    def grad_accum_counter(self) -> int:
        return self._grad_accum_counter

    @property
    def optimizer_steps(self) -> int:
        return self._optimizer_steps

    @property
    def backward_steps(self) -> int:
        return self._backward_steps

    @property
    def skipped_optimizer_steps(self) -> float:
        """fp16 steps skipped on overflow (GradScaler semantics)."""
        return float(jax.device_get(self._skipped_steps))

    @property
    def is_distributed(self) -> bool:
        return self._status_obj.is_distributed

    @property
    def is_scaled_precision(self) -> bool:
        return self._status_obj.is_scaled_precision

    @property
    def precision(self) -> PrecisionOptions:
        return self._status_obj.precision

    @property
    def oss(self) -> bool:
        return self._status_obj.oss

    @property
    def sddp(self) -> bool:
        return self._status_obj.sddp

    @property
    def fsdp(self) -> bool:
        return self._status_obj.fsdp

    # ----- reference-parity aliases & config accessors (stoke.py:1271-1466,
    #       status.py:473-627) -----

    @property
    def grad_accum(self) -> int:
        """Alias of grad_accum_steps (reference ``grad_accum`` property)."""
        return self._status_obj.grad_accum

    @property
    def sharded(self) -> bool:
        """Gradient sharding active (reference ``sharded`` ≈ SDDP)."""
        return self._status_obj.sddp

    @property
    def fully_sharded(self) -> bool:
        """Parameter sharding active (reference ``fully_sharded`` ≈ FSDP)."""
        return self._status_obj.fsdp

    @property
    def tpu(self) -> bool:
        """Running on the TPU backend (reference ``gpu``/``cuda`` probes)."""
        return self._status_obj.is_tpu

    @property
    def is_fp16(self) -> bool:
        return self._status_obj.precision is PrecisionOptions.fp16

    @property
    def is_bf16(self) -> bool:
        return self._status_obj.precision is PrecisionOptions.bf16

    @property
    def precision_config(self):
        """(reference amp_config/apex_config, status.py:473-627)"""
        return self._status_obj.precision_config

    @property
    def dp_config(self):
        """(reference ddp_config/horovod_config/deepspeed_config)"""
        return self._status_obj.dp_config

    @property
    def mesh_config(self):
        return self._status_obj.mesh_config

    @property
    def oss_config(self):
        return self._status_obj.oss_config

    @property
    def sddp_config(self):
        return self._status_obj.sddp_config

    @property
    def fsdp_config(self):
        return self._status_obj.fsdp_config

    @property
    def checkpoint_config(self):
        return self._status_obj.checkpoint_config

    @property
    def profiler_config(self):
        return self._status_obj.profiler_config

    def reset_ema(self) -> None:
        """Restart the EMA loss series (reference ``reset_ema``)."""
        self._rolling_mean_loss = self._zero_scalar()
        self._ema_initialized = False

    def reset_tracking(self) -> None:
        """Clear all loss tracking AND step counters (reference
        ``reset_tracking``, stoke.py:1209-1221, zeroes the counters too);
        the partial gradient window is discarded with them."""
        self.reset_ema()
        self._reset_tracking_window()
        self._last_step_loss = None
        self._grad_accum_counter = 0
        self._optimizer_steps = 0
        self._backward_steps = 0
        self._pending = None
        self._grad_buf = self._engine.init_grad_buffer(self._variables)

    def num_model_parameters(
        self, normalize: Optional[ParamNormalize] = None
    ) -> float:
        """Total parameter count (reference stoke.py:1144-1162)."""
        n = tree_count_params(self._variables["params"])
        return n / normalize.value if normalize is not None else n

    def print_num_model_parameters(
        self, normalize: Optional[ParamNormalize] = None
    ) -> None:
        n = self.num_model_parameters(normalize)
        suffix = f" ({normalize.name})" if normalize else ""
        self.print_on_devices(f"Model parameters: {n}{suffix}")

    def dump_model_parameter_info(self) -> None:
        """Per-leaf name/shape/dtype dump (reference stoke.py:1226-1240).
        Names use the SAME rendering as the per-layer numerics surfaces
        (leaf provenance, quantization-error join keys), so they
        cross-reference exactly."""
        from stoke_tpu.telemetry.numerics import leaf_path_names

        params = self._variables["params"]
        leaves = jax.tree_util.tree_leaves(params)
        for name, leaf in zip(leaf_path_names(params), leaves):
            self.print_on_devices(
                f"param {name}: shape={tuple(leaf.shape)} dtype={leaf.dtype}"
            )

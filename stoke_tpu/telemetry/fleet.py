"""Fleet observability (ISSUE 5 tentpole): cross-host skew aggregation,
straggler detection, and barrier-wait attribution.

PRs 1/3/4 made a single process self-observing, but every signal stayed
rank-local and the sinks rank-0-only — on a multi-host pod nobody could
answer "which host is slow?", the dominant failure mode at pod scale
(MLPerf-0.6 on TPU-v3 pods attributes most lost scaling to per-host input
and step-time skew, arXiv:1909.09756).  This module makes the FLEET the
unit of observation:

- **Packed signal vector** — each host packs a small fixed-layout float32
  vector of window-local signals (:data:`FLEET_SIGNALS`: step wall time,
  dispatch count, loader wait, starvation, compile time, barrier wait,
  goodput buckets, health-anomaly count, comm bytes).  The layout is a
  wire format: never reorder, only append.
- **In-band exchange** — every ``FleetConfig.window_steps`` optimizer
  steps, one tiny ``process_allgather`` (a single [n_hosts, N] f32
  collective, piggybacked on the telemetry record cadence — zero extra
  dispatches on the compiled step path, which is asserted by the default-
  OFF bit-identity tests) gives EVERY host the full per-host matrix.
- **Aggregated views** — min/median/max/p99 + argmax-host per signal
  (Prometheus ``fleet/*`` gauges), per-host step-time skew vs the fleet
  median, a loader-vs-compute skew classification, and barrier-wait
  attribution (wait time charged to the straggler that arrived last, not
  the waiters) — emitted into the JSONL step events (``fleet/*`` schema
  fields), the end-of-run summary, and flight-recorder bundles.
- **Straggler detector** — ``fleet_straggler``: fires when one host's lag
  exceeds the z-score / relative-skew threshold for K consecutive
  windows; registered in the PR 3 health-detector registry when a
  ``HealthConfig`` is present, self-applied (warn) otherwise.

Everything is default-OFF; without a ``FleetConfig`` the compiled step
programs, dispatch counts, and telemetry records are untouched.

Barrier-wait timing (the always-on satellite) also lives here:
:func:`timed_sync` brackets every ``Stoke.barrier()`` /
checkpoint ``sync_global_devices`` with a ``sync/barrier_wait_s`` timer
feeding every live telemetry registry — cross-process sync time is
visible in the wall-clock breakdown even with fleet observability off.
"""

from __future__ import annotations

import contextlib
import time
import warnings
import weakref
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from stoke_tpu.telemetry.events import (
    FLEET_REBALANCE_FIELDS,
    FLEET_STEP_FIELDS,
)
from stoke_tpu.telemetry.health import Detector as _HealthDetector

#: the goodput buckets mirrored into the packed vector (must match
#: attribution.GOODPUT_BUCKETS — asserted in tests, not imported, so this
#: module stays importable without the attribution machinery)
_GOODPUT_BUCKETS = (
    "productive", "compile", "recompile", "loader", "checkpoint", "halt",
)

#: packed per-host signal vector layout: field name -> index.  This is the
#: WIRE FORMAT of the cross-host exchange; never reorder or remove, only
#: append (hosts on mixed code versions would silently misread each other).
FLEET_SIGNALS = (
    "step",                  # optimizer step the window ends at
    "wall_s",                # window wall seconds on this host
    "dispatches",            # engine compiled-program dispatches this window
    "loader_wait_s",         # host seconds blocked on the data loader
    "starvation_s",          # post-warmup loader wait (device-starving part)
    "compile_s",             # XLA compile seconds this window
    "barrier_wait_s",        # seconds waiting inside cross-process syncs
    "goodput_productive_s",  # goodput ledger buckets (0 without attribution)
    "goodput_compile_s",
    "goodput_recompile_s",
    "goodput_loader_s",
    "goodput_checkpoint_s",
    "goodput_halt_s",
    "health_anomalies",      # health detector firings this window
    "comm_bytes_onwire",     # gradient-transport bytes this window
)
FLEET_INDEX = {name: i for i, name in enumerate(FLEET_SIGNALS)}
N_FLEET_SIGNALS = len(FLEET_SIGNALS)

#: fleet fields of the JSONL step event — the schema (events.py
#: STEP_EVENT_FIELDS, where each field's semantics are documented) is the
#: single source of truth; :meth:`FleetMonitor.window_stats` returns
#: exactly these keys (minus the rebalance subset when
#: ``FleetConfig.rebalance`` is off — ISSUE 14's zero-new-fields contract)
FLEET_EVENT_FIELDS = FLEET_STEP_FIELDS

#: the fleet fields every FleetConfig run emits (rebalance keys ride only
#: with the actuator configured)
FLEET_BASE_FIELDS = tuple(
    f for f in FLEET_STEP_FIELDS if f not in FLEET_REBALANCE_FIELDS
)

#: below this fraction of the median window wall, skew is reported as
#: class "none" (measurement noise, not a straggler signal)
_SKEW_NOISE_FRAC = 0.02


# --------------------------------------------------------------------------- #
# packed vector
# --------------------------------------------------------------------------- #


def pack_fleet_vector(signals: Dict[str, float]) -> np.ndarray:
    """``{signal: value}`` → the fixed-layout ``[N_FLEET_SIGNALS]`` f32
    vector (missing signals pack as 0; unknown keys raise — a typo must not
    silently drop a signal on the floor)."""
    unknown = set(signals) - set(FLEET_SIGNALS)
    if unknown:
        raise ValueError(f"unknown fleet signals {sorted(unknown)}")
    vec = np.zeros(N_FLEET_SIGNALS, np.float32)
    for name, value in signals.items():
        vec[FLEET_INDEX[name]] = np.float32(value or 0.0)
    return vec


def unpack_fleet_vector(vec) -> Dict[str, float]:
    """Host-side view of one packed row as ``{signal: float}``."""
    arr = np.asarray(vec, np.float64).reshape(-1)
    if arr.shape[0] != N_FLEET_SIGNALS:
        raise ValueError(
            f"fleet vector has {arr.shape[0]} entries; expected "
            f"{N_FLEET_SIGNALS} (mixed code versions across hosts?)"
        )
    return {name: float(arr[i]) for i, name in enumerate(FLEET_SIGNALS)}


# --------------------------------------------------------------------------- #
# aggregation / skew math (pure functions — unit-tested on synthetic
# matrices, shared by the in-band view and scripts/merge_rank_jsonl.py)
# --------------------------------------------------------------------------- #


def fleet_aggregates(matrix: np.ndarray) -> Dict[str, Dict[str, float]]:
    """Per-signal fleet aggregates of a ``[n_hosts, N_FLEET_SIGNALS]``
    matrix: ``{signal: {min, median, max, p99, argmax_host}}``."""
    m = np.asarray(matrix, np.float64)
    if m.ndim != 2 or m.shape[1] != N_FLEET_SIGNALS:
        raise ValueError(
            f"fleet matrix must be [n_hosts, {N_FLEET_SIGNALS}], got "
            f"{m.shape}"
        )
    out: Dict[str, Dict[str, float]] = {}
    for name, i in FLEET_INDEX.items():
        col = m[:, i]
        out[name] = {
            "min": float(col.min()),
            "median": float(np.median(col)),
            "max": float(col.max()),
            "p99": float(np.percentile(col, 99)),
            "argmax_host": int(col.argmax()),
        }
    return out


def straggler_verdict(
    matrix: np.ndarray,
    *,
    rel_threshold: float = 0.2,
    zscore_threshold: float = 3.0,
) -> Dict[str, Any]:
    """Who (if anyone) is dragging this fleet window, and why.

    Per-host **lag** combines the three ways a host can be late:

        lag_h = (wall_h - median(wall))            # step-time skew
              + (loader_h - median(loader))        # input-pipeline skew
              + (max(barrier) - barrier_h)         # barrier lateness

    The barrier term is the attribution flip: the host that waited LEAST
    inside cross-process syncs is the one everyone else was waiting FOR,
    so the fleet's barrier wait is charged to it, not to the waiters.

    A host is flagged as straggler when its lag exceeds
    ``rel_threshold x median(wall)`` (meaningful at any fleet size) or
    when its **leave-one-out** lag z-score — the host against the mean/
    std of the OTHER hosts, with the std floored at 0.1% of the median
    wall so a tight fleet doesn't divide by zero — exceeds
    ``zscore_threshold``.  Leave-one-out matters: an all-host z-score is
    mathematically bounded by sqrt(n_hosts - 1), so on small fleets a
    3-sigma threshold could never fire.  The z path needs >= 3 hosts (a
    1-sample "rest of the fleet" has no spread to speak of) and a lag
    above the noise floor; with 2 hosts the relative threshold is the
    only live signal.

    Returns a dict: flagged, host, lag_s, lag_frac, zscore, step_skew_s,
    loader_skew_s, barrier_wait_s, barrier_charged_host, skew_class
    ("none" | "loader" | "compute"), wall_median_s, wall_max_s, hosts.
    """
    m = np.asarray(matrix, np.float64)
    if m.ndim != 2 or m.shape[1] != N_FLEET_SIGNALS:
        raise ValueError(
            f"fleet matrix must be [n_hosts, {N_FLEET_SIGNALS}], got "
            f"{m.shape}"
        )
    n_hosts = m.shape[0]
    wall = m[:, FLEET_INDEX["wall_s"]]
    loader = m[:, FLEET_INDEX["loader_wait_s"]]
    barrier = m[:, FLEET_INDEX["barrier_wait_s"]]
    wall_median = float(np.median(wall))
    wall_skew = wall - np.median(wall)
    loader_skew = loader - np.median(loader)
    barrier_late = barrier.max() - barrier  # lateness: last arrival waits 0
    lag = wall_skew + loader_skew + barrier_late
    host = int(lag.argmax())
    lag_s = float(lag[host])
    denom = max(wall_median, 1e-9)
    lag_frac = lag_s / denom
    z: Optional[float] = None
    if n_hosts >= 3:
        # leave-one-out needs a "rest of the fleet" with actual spread;
        # below 3 hosts the value would be statistically meaningless and
        # reporting it (JSONL, warnings) would invite misreading — None
        others = np.delete(lag, host)
        std = max(float(others.std()), 1e-3 * denom)
        z = (lag_s - float(others.mean())) / std
    flagged = n_hosts > 1 and (
        lag_frac >= rel_threshold
        or (
            z is not None
            and z >= zscore_threshold
            and lag_frac >= _SKEW_NOISE_FRAC
        )
    )
    # classification: does the straggler's lag come from its input
    # pipeline or from its compute/step time?  Below the noise floor the
    # honest answer is "none".
    loader_part = max(float(loader_skew[host]), 0.0)
    compute_part = max(float(wall_skew[host]), 0.0)
    if lag_s <= _SKEW_NOISE_FRAC * denom or n_hosts <= 1:
        skew_class = "none"
    elif loader_part >= 0.5 * max(loader_part + compute_part, 1e-12):
        skew_class = "loader"
    else:
        skew_class = "compute"
    barrier_max = float(barrier.max())
    # barrier-wait attribution: the cost is what the earliest arrival
    # paid; it is charged to the LAST arrival (min wait), who is the
    # host the fleet was actually waiting for.  Charging needs SPREAD:
    # when every host waited equally (the sync's own coordination
    # round-trip), nobody was late and naming argmin (always host 0 on
    # ties) would send triage after an innocent host.
    barrier_spread = barrier_max - float(barrier.min())
    return {
        "hosts": n_hosts,
        "flagged": bool(flagged),
        "host": host,
        "lag_s": lag_s,
        "lag_frac": lag_frac,
        "zscore": z,
        "step_skew_s": float(wall_skew[host]),
        "loader_skew_s": float(loader_skew[host]),
        "skew_class": skew_class,
        "wall_median_s": wall_median,
        "wall_max_s": float(wall.max()),
        "barrier_wait_s": barrier_max,
        "barrier_charged_host": (
            int(barrier.argmin())
            if barrier_spread > _SKEW_NOISE_FRAC * denom
            else None
        ),
    }


# --------------------------------------------------------------------------- #
# barrier-wait timing (always-on satellite: visible without a FleetConfig)
# --------------------------------------------------------------------------- #

#: live telemetry registries receiving cross-process sync timings; a
#: WeakSet so a dropped Telemetry/Stoke never leaks its registry here
_SYNC_REGISTRIES: "weakref.WeakSet" = weakref.WeakSet()


def unregister_sync_registry(registry) -> None:
    """Unsubscribe a registry from sync timings (``Telemetry.close``
    calls this — a closed run's counters must not keep accruing later
    runs' barrier waits into its post-run summary).  Idempotent."""
    _SYNC_REGISTRIES.discard(registry)


def register_sync_registry(registry) -> None:
    """Subscribe a metrics registry to cross-process sync timings (every
    ``Telemetry`` registers its registry at construction).  Idempotent."""
    _SYNC_REGISTRIES.add(registry)
    # pre-register so scrapes/breakdowns carry zeros before the first sync
    registry.counter(
        "sync/barrier_wait_s",
        help="host seconds spent inside cross-process barriers "
        "(Stoke.barrier + checkpoint sync_global_devices)",
    )
    registry.counter(
        "sync/barriers_total", help="cross-process barrier crossings"
    )


def observe_sync_wait(seconds: float, tag: Optional[str] = None) -> None:
    """Record one completed cross-process sync into every live registry:
    the aggregate ``sync/barrier_wait_s`` / ``sync/barriers_total`` pair
    always, plus a per-source ``sync/<tag>_wait_s`` when the caller names
    one (so "is it checkpoint coordination or explicit barriers" is
    answerable from the exposition).  Process-scoped by design:
    concurrent Stoke instances in one process each see the process's
    total sync time."""
    seconds = max(float(seconds), 0.0)
    for registry in list(_SYNC_REGISTRIES):
        registry.counter("sync/barrier_wait_s").inc(seconds)
        registry.counter("sync/barriers_total").inc()
        if tag:
            registry.counter(f"sync/{tag}_wait_s").inc(seconds)


@contextlib.contextmanager
def timed_sync(tag: Optional[str] = None):
    """Bracket a cross-process sync (``sync_global_devices`` & friends):
    the elapsed host wall time — which IS the barrier wait, near zero for
    the last arrival and the full skew for the first — lands in
    ``sync/barrier_wait_s`` (and ``sync/<tag>_wait_s``) of every
    registered registry."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        observe_sync_wait(time.perf_counter() - t0, tag)


# --------------------------------------------------------------------------- #
# the monitor
# --------------------------------------------------------------------------- #

#: registry counters FleetMonitor deltas per window, keyed by signal name
_COUNTER_SOURCES = {
    "loader_wait_s": "data/loader_wait_s",
    "starvation_s": "data/starvation_s",
    "compile_s": "jax/compile_time_s",
    "barrier_wait_s": "sync/barrier_wait_s",
    "health_anomalies": "health/anomalies_total",
    **{
        f"goodput_{b}_s": f"goodput/{b}_s_total" for b in _GOODPUT_BUCKETS
    },
}

#: warnings emitted by the self-applied (health-less) straggler action
#: before degrading to record-only
_MAX_STRAGGLER_WARNINGS = 5

#: straggler verdict dicts retained for the end-of-run summary / bundles
_RECENT_STRAGGLERS_MAX = 64


class FleetMonitor:
    """Owns the per-window signal accumulator, the in-band exchange, the
    aggregated views, and the straggler streak state.

    The facade constructs one per run when a ``FleetConfig`` is supplied
    and attaches it to the telemetry pipeline; ``Telemetry.record_step``
    calls :meth:`window_stats` with the window wall time and the already-
    collected registry deltas (the same piggyback the attribution monitor
    rides) — the exchange itself fires only when ``step`` crosses a
    ``window_steps`` boundary, so the collective cost is one tiny
    allgather per fleet window, nothing per step.
    """

    def __init__(
        self,
        cfg,
        registry,
        *,
        rank: int = 0,
        n_processes: int = 1,
        dispatch_count_fn: Optional[Callable[[], int]] = None,
    ):
        self.cfg = cfg
        self.registry = registry
        self.rank = int(rank)
        self.n_processes = max(int(n_processes), 1)
        self._dispatch_count_fn = dispatch_count_fn
        self._acc = np.zeros(N_FLEET_SIGNALS, np.float64)
        self._last_counters: Dict[str, float] = {}
        self._last_dispatches = (
            float(dispatch_count_fn()) if dispatch_count_fn else 0.0
        )
        self._last_bucket: Optional[int] = None
        self.windows = 0
        self.last_matrix: Optional[np.ndarray] = None
        self.last_aggregates: Optional[Dict[str, Dict[str, float]]] = None
        self.last_verdict: Optional[Dict[str, Any]] = None
        # straggler streak state: K consecutive flagged windows on the
        # SAME host before the detector fires (one anomaly per streak)
        self._streak = 0
        self._streak_host: Optional[int] = None
        self._pending_straggler: Optional[Dict[str, Any]] = None
        self._straggler_events: List[Dict[str, Any]] = []
        self._warnings = 0
        # skew-reactive input rebalancing (ISSUE 14 tentpole c): the
        # actuator is a data.InputRebalancer the DataLoader factory
        # attaches; None (rebalance off / no loader built) keeps every
        # path below byte-identical to pre-ISSUE-14 behavior
        self.rebalancer = None
        self._rebalance_on = bool(getattr(cfg, "rebalance", False))
        self._event_keys = (
            FLEET_EVENT_FIELDS if self._rebalance_on else FLEET_BASE_FIELDS
        )
        self._last_shift: Optional[Dict[str, int]] = None
        # pre-register so scrapes carry zeros before the first exchange
        registry.counter(
            "fleet/windows_total", help="fleet exchange windows completed"
        )
        registry.counter(
            "fleet/straggler_windows_total",
            help="windows with a flagged straggler host",
        )
        registry.counter(
            "fleet/anomalies_total",
            help="fleet_straggler detector firings (streak >= K windows)",
        )
        if self._rebalance_on:
            registry.counter(
                "fleet/rebalance_shifts_total",
                help="input-rebalance actuations (loader-classified "
                "straggler streaks acted on)",
            )
            registry.counter(
                "fleet/rebalance_rows_moved_total",
                help="per-slice read rows moved off straggler hosts",
            )

    # ------------------------------ window ----------------------------- #

    def _counter_delta(self, name: str) -> float:
        inst = self.registry.get(name)
        now = inst.value if inst is not None else 0.0
        prev = self._last_counters.get(name, 0.0)
        self._last_counters[name] = now
        return max(0.0, now - prev)

    def _accumulate(
        self,
        step: int,
        wall_s: Optional[float],
        loader_wait_s: Optional[float],
        comm_bytes_onwire: Optional[float],
    ) -> None:
        acc = self._acc
        acc[FLEET_INDEX["step"]] = float(step)
        if wall_s:
            acc[FLEET_INDEX["wall_s"]] += float(wall_s)
        # loader wait arrives pre-delta'd from record_step (the telemetry
        # pipeline already consumed the counter delta); the rest are
        # delta'd here against our own snapshots
        if loader_wait_s:
            acc[FLEET_INDEX["loader_wait_s"]] += float(loader_wait_s)
        for signal, counter in _COUNTER_SOURCES.items():
            if signal == "loader_wait_s":
                continue
            acc[FLEET_INDEX[signal]] += self._counter_delta(counter)
        if self._dispatch_count_fn is not None:
            now = float(self._dispatch_count_fn())
            acc[FLEET_INDEX["dispatches"]] += max(
                0.0, now - self._last_dispatches
            )
            self._last_dispatches = now
        if comm_bytes_onwire:
            acc[FLEET_INDEX["comm_bytes_onwire"]] += float(comm_bytes_onwire)

    def _exchange(self, vec: np.ndarray) -> np.ndarray:
        """One in-band allgather of the packed vector → the full
        ``[n_hosts, N]`` matrix on EVERY host.  Single-process runs skip
        the collective entirely (a fleet of one)."""
        if self.n_processes <= 1:
            return vec[None, :].astype(np.float32)
        from jax.experimental import multihost_utils

        out = multihost_utils.process_allgather(vec)
        return np.asarray(out, np.float32).reshape(
            self.n_processes, N_FLEET_SIGNALS
        )

    def window_stats(
        self,
        *,
        step: int,
        wall_s: Optional[float],
        loader_wait_s: Optional[float] = None,
        comm_bytes_onwire: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Accumulate one telemetry record into the current fleet window
        and — when ``step`` crosses a ``window_steps`` boundary — run the
        exchange and return the populated ``fleet/*`` JSONL fields.
        Between boundaries every field is None (the schema keys stay
        present so consumers see a stable shape)."""
        self._accumulate(step, wall_s, loader_wait_s, comm_bytes_onwire)
        bucket = int(step) // max(int(self.cfg.window_steps), 1)
        if self._last_bucket is None:
            # first record: anchor the cadence AND discard the warm-up
            # accumulation — its wall covers init->first-record time
            # (warm-up compiles), and hosts compile at different speeds
            # (cold caches), so folding it into the first closed window
            # would hand the first cross-host verdict pure compile skew
            # and could seed a spurious straggler streak.  Applies at
            # every window_steps, 1 included: the first verdict is always
            # steady-state.
            self._last_bucket = bucket
            self._acc = np.zeros(N_FLEET_SIGNALS, np.float64)
            return {k: None for k in self._event_keys}
        if bucket <= self._last_bucket:
            return {k: None for k in self._event_keys}
        self._last_bucket = bucket
        return self._close_window()

    def _close_window(self) -> Dict[str, Any]:
        vec = self._acc.astype(np.float32)
        self._acc = np.zeros(N_FLEET_SIGNALS, np.float64)
        matrix = self._exchange(vec)
        self.windows += 1
        self.registry.counter("fleet/windows_total").inc()
        aggregates = fleet_aggregates(matrix)
        verdict = straggler_verdict(
            matrix,
            rel_threshold=self.cfg.straggler_rel_frac,
            zscore_threshold=self.cfg.straggler_zscore,
        )
        self.last_matrix = matrix
        self.last_aggregates = aggregates
        self.last_verdict = verdict
        self._publish_gauges(aggregates)
        self._update_streak(verdict)
        return self._event_fields(verdict)

    def _publish_gauges(
        self, aggregates: Dict[str, Dict[str, float]]
    ) -> None:
        g = self.registry.gauge
        for signal, stats in aggregates.items():
            if signal == "step":
                continue
            for stat in ("min", "median", "max", "p99"):
                g(f"fleet/{signal}_{stat}").set(stats[stat])
            g(f"fleet/{signal}_argmax_host").set(stats["argmax_host"])

    def _update_streak(self, verdict: Dict[str, Any]) -> None:
        if not verdict["flagged"]:
            self._streak = 0
            self._streak_host = None
            return
        self.registry.counter("fleet/straggler_windows_total").inc()
        if verdict["host"] == self._streak_host:
            self._streak += 1
        else:
            self._streak_host = verdict["host"]
            self._streak = 1
        if self._streak < max(int(self.cfg.straggler_windows), 1):
            return
        # fire once per streak, then re-arm (a permanently-slow host must
        # not fire every window for a 3-day run)
        self._streak = 0
        self._streak_host = None
        event = {
            **verdict,
            "window": self.windows,
            "step": int(self.last_matrix[verdict["host"],
                                         FLEET_INDEX["step"]]),
            "windows_in_streak": int(self.cfg.straggler_windows),
        }
        self._straggler_events.append(event)
        del self._straggler_events[:-_RECENT_STRAGGLERS_MAX]
        self.registry.counter("fleet/anomalies_total").inc()
        self._pending_straggler = event
        self._maybe_rebalance(event)
        self._self_apply(event)

    def attach_rebalancer(self, rebalancer) -> None:
        """Attach the run's input-rebalance actuator (ISSUE 14; called by
        ``Stoke.DataLoader`` when ``FleetConfig.rebalance`` is on).  The
        monitor only PROPOSES share shifts — the rebalancer owns the
        bounded shares and the agreement protocol that makes every host
        apply them at the same fetch index."""
        self.rebalancer = rebalancer

    def _maybe_rebalance(self, event: Dict[str, Any]) -> None:
        """Act on a completed loader-classified straggler streak (the
        K-window hysteresis IS the actuation gate): shift
        ``rebalance_rows`` of per-slice read work from the flagged host to
        the host with the least loader wait this window.  Every host runs
        this on the IDENTICAL exchanged matrix, so the decision — and the
        share state it evolves — is deterministic fleet-wide without any
        extra collective."""
        rb = self.rebalancer
        if (
            rb is None
            or not self._rebalance_on
            or self.n_processes <= 1
            or event.get("skew_class") != "loader"
            or self.last_matrix is None
        ):
            return
        slow = int(event["host"])
        loader_col = self.last_matrix[:, FLEET_INDEX["loader_wait_s"]]
        fast = int(loader_col.argmin())
        if fast == slow:
            return
        moved = rb.propose_shift(
            slow, fast, int(getattr(self.cfg, "rebalance_rows", 1))
        )
        if not moved:
            return  # bound reached: the share floor/ceiling holds
        self._last_shift = {"rows": moved, "from": slow, "to": fast}
        self.registry.counter("fleet/rebalance_shifts_total").inc()
        self.registry.counter("fleet/rebalance_rows_moved_total").inc(moved)
        self.registry.gauge(
            "fleet/rebalance_share_self",
            help="this host's per-slice read share (rows)",
        ).set(float(rb.share_of(self.rank)))

    def _self_apply(self, event: Dict[str, Any]) -> None:
        """Warn-path fallback when no health registry will consume the
        pending event (the facade clears ``_pending_straggler`` through
        :class:`FleetStragglerDetector` when a ``HealthConfig`` is
        present; this warning is the only surfacing otherwise)."""
        if self.cfg.straggler_action == "record":
            return
        if self._warnings >= _MAX_STRAGGLER_WARNINGS:
            return
        self._warnings += 1
        warnings.warn(
            f"Stoke -- fleet: {self._describe(event)} "
            f"(see docs/observability.md 'Fleet view & stragglers')"
        )

    @staticmethod
    def _describe(event: Dict[str, Any]) -> str:
        z = event.get("zscore")
        return (
            f"host {event['host']} straggled "
            f"{event['windows_in_streak']} consecutive windows "
            f"(lag {event['lag_s']:.3f}s = {event['lag_frac']:.0%} of the "
            f"median window{f', z={z:.1f}' if z is not None else ''}; "
            f"skew class: {event['skew_class']})"
        )

    def consume_straggler(self) -> Optional[Dict[str, Any]]:
        """Pop the pending straggler event (the
        :class:`FleetStragglerDetector` adapter drains this into the
        health anomaly pipeline)."""
        event, self._pending_straggler = self._pending_straggler, None
        return event

    def _event_fields(self, verdict: Dict[str, Any]) -> Dict[str, Any]:
        flagged = verdict["flagged"]
        out = self._base_event_fields(verdict, flagged)
        if self._rebalance_on:
            rb = self.rebalancer
            shift = self._last_shift
            self._last_shift = None  # report each actuation exactly once
            out.update({
                "fleet/rebalance_share_self": (
                    None if rb is None else float(rb.share_of(self.rank))
                ),
                "fleet/rebalance_shift_rows": (
                    None if shift is None else shift["rows"]
                ),
                "fleet/rebalance_from_host": (
                    None if shift is None else shift["from"]
                ),
                "fleet/rebalance_to_host": (
                    None if shift is None else shift["to"]
                ),
                "fleet/rebalance_shifts": (
                    None if rb is None else float(rb.shifts)
                ),
            })
        return out

    def _base_event_fields(
        self, verdict: Dict[str, Any], flagged: bool
    ) -> Dict[str, Any]:
        return {
            "fleet/hosts": verdict["hosts"],
            "fleet/window": self.windows,
            "fleet/wall_median_s": verdict["wall_median_s"],
            "fleet/wall_max_s": verdict["wall_max_s"],
            "fleet/step_skew_s": verdict["step_skew_s"],
            "fleet/loader_skew_s": verdict["loader_skew_s"],
            "fleet/lag_s": verdict["lag_s"],
            "fleet/lag_frac": verdict["lag_frac"],
            "fleet/straggler_host": verdict["host"] if flagged else None,
            "fleet/straggler_zscore": verdict["zscore"],
            "fleet/skew_class": verdict["skew_class"],
            "fleet/barrier_wait_s": verdict["barrier_wait_s"],
            "fleet/barrier_charged_host": verdict["barrier_charged_host"],
        }

    # ----------------------------- summary ----------------------------- #

    def snapshot(self) -> Dict[str, Any]:
        """Bundle/summary payload: the latest per-host matrix (as
        ``{host: {signal: value}}`` rows), its aggregates, the latest
        straggler verdict, and the recent straggler events — "which host
        was slow at time of death"."""
        rows = None
        if self.last_matrix is not None:
            rows = {
                str(h): unpack_fleet_vector(self.last_matrix[h])
                for h in range(self.last_matrix.shape[0])
            }
        return {
            "rank": self.rank,
            "n_processes": self.n_processes,
            "windows": self.windows,
            "window_steps": int(self.cfg.window_steps),
            "last_matrix": rows,
            "last_aggregates": self.last_aggregates,
            "last_verdict": self.last_verdict,
            "straggler_events": list(self._straggler_events),
        }

    def summary(self) -> Dict[str, Any]:
        """End-of-run fleet accounting (the ``Stoke.fleet_summary``
        surface)."""
        out = self.snapshot()
        out["straggler_windows"] = int(
            self.registry.counter("fleet/straggler_windows_total").value
        )
        out["straggler_anomalies"] = int(
            self.registry.counter("fleet/anomalies_total").value
        )
        if self._rebalance_on:
            rb = self.rebalancer
            out["rebalance"] = {
                "shifts": int(
                    self.registry.counter(
                        "fleet/rebalance_shifts_total"
                    ).value
                ),
                "rows_moved": int(
                    self.registry.counter(
                        "fleet/rebalance_rows_moved_total"
                    ).value
                ),
                "shares": None if rb is None else list(rb.shares),
            }
        return out


class FleetStragglerDetector(_HealthDetector):
    """Health-registry adapter (PR 3 registry contract): when the fleet
    monitor completed a flagged straggler streak since the last health
    observation, surface it as a ``fleet_straggler`` anomaly (action from
    ``FleetConfig.straggler_action``) so it lands in the anomaly counters,
    the flight-recorder ring, and post-mortem bundles."""

    name = "fleet_straggler"

    def __init__(self, monitor: FleetMonitor, action: str = "warn"):
        super().__init__(action)
        self.monitor = monitor
        # the monitor's own warn fallback would double-report next to the
        # health pipeline's warning
        monitor._warnings = _MAX_STRAGGLER_WARNINGS

    def check(self, step, sentinels, ctx):
        event = self.monitor.consume_straggler()
        if event is None:
            return None
        return self._fire(
            step,
            f"fleet straggler: {FleetMonitor._describe(event)}",
            value=float(event["lag_s"]),
        )

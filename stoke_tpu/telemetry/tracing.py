"""Always-on structured host tracing (ISSUE 10 tentpole): a bounded span
ring, one composed span helper, Perfetto export, and a critical-path
summary.

The observability verticals so far report *aggregates* (MFU, goodput
buckets, fleet skew); the only span mechanism has been ``xprof_span`` — a
``jax.profiler.TraceAnnotation`` that is invisible outside an active xprof
capture.  At pod scale, lost scaling hides in exactly the host-side gaps
between dispatches (arXiv:1909.09756), and serving triage leans on
per-request latency decomposition (arXiv:2605.25645) — both need a span
timeline that is ALWAYS recorded, not only when a profiler happens to be
attached.  Three pieces:

1. :class:`TraceRecorder` — a bounded ring of completed host spans
   ``(name, track, t_start, dur, self, step, request_id, parent_id,
   attrs)`` recorded from ``perf_counter`` pairs.  O(1) per span, no IO,
   no device touches; per-span self-time (duration minus child durations)
   is maintained incrementally on a thread-local open-span stack, so the
   critical-path summary never has to rebuild the tree.
2. :func:`trace_span` — ONE composed context manager emitting the xprof
   ``TraceAnnotation`` AND a host span into every registered recorder
   (plus an optional registry timer).  This subsumes the hand-rolled
   (span, timer) pairing the facade/telemetry layers previously
   duplicated.  With no recorder registered it degrades to the bare
   annotation — the pre-ISSUE-10 behavior, at the pre-ISSUE-10 cost.
3. Chrome/Perfetto trace-event export (``trace.rank<N>.json``): ``"X"``
   duration events on per-track (and per-request) threads, loadable in
   ``ui.perfetto.dev`` / ``chrome://tracing``;
   ``scripts/merge_rank_traces.py`` aligns multiple ranks' files by step
   anchor into one pod-wide timeline.

Recorder registration is module-global (the ``_SYNC_REGISTRIES`` pattern
from ``telemetry.fleet``): the engine/data/io layers call
:func:`trace_span` with no plumbing, and whichever facade holds an active
``TraceConfig`` receives the spans.  Default OFF — without a registered
recorder no ring exists, and the compiled step programs are untouched
either way (tracing is purely host-side).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from stoke_tpu.telemetry.collectors import xprof_span

#: keys every exported ``"X"`` duration event carries (the
#: Perfetto/chrome-trace minimum; tests pin the schema)
TRACE_EVENT_KEYS = ("name", "ph", "ts", "dur", "pid", "tid")

# --------------------------------------------------------------------------- #
# module-global recorder registry
# --------------------------------------------------------------------------- #

_RECORDERS: "weakref.WeakSet[TraceRecorder]" = weakref.WeakSet()


def register_recorder(recorder: "TraceRecorder") -> None:
    """Subscribe a recorder to every :func:`trace_span` /
    :func:`trace_point` site in the process (idempotent).  Kept weak — a
    dropped facade must not leak its ring forever."""
    _RECORDERS.add(recorder)


def unregister_recorder(recorder: "TraceRecorder") -> None:
    """Stop routing spans to ``recorder`` (idempotent)."""
    _RECORDERS.discard(recorder)


# --------------------------------------------------------------------------- #
# spans
# --------------------------------------------------------------------------- #


class Span:
    """One completed host span (immutable once ringed)."""

    __slots__ = (
        "span_id", "parent_id", "name", "track", "t_start", "dur_s",
        "self_s", "step", "request_id", "attrs",
    )

    def __init__(self, span_id, parent_id, name, track, t_start, dur_s,
                 self_s, step, request_id, attrs):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.track = track
        self.t_start = t_start
        self.dur_s = dur_s
        self.self_s = self_s
        self.step = step
        self.request_id = request_id
        self.attrs = attrs

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "span_id": self.span_id,
            "name": self.name,
            "track": self.track,
            "t_start": self.t_start,
            "dur_s": self.dur_s,
            "self_s": self.self_s,
            "step": self.step,
        }
        if self.parent_id is not None:
            out["parent_id"] = self.parent_id
        if self.request_id is not None:
            out["request_id"] = self.request_id
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out


class _OpenSpan:
    """Stack entry for an in-flight span (thread-local; never shared)."""

    __slots__ = ("span_id", "parent_id", "name", "track", "request_id",
                 "attrs", "t0", "child_s")

    def __init__(self, span_id, parent_id, name, track, request_id, attrs):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.track = track
        self.request_id = request_id
        self.attrs = attrs
        self.t0 = 0.0
        self.child_s = 0.0


class _SpanCtx:
    """Context manager recording one span into its recorder on exit."""

    __slots__ = ("_rec", "_name", "_track", "_rid", "_attrs", "_open")

    def __init__(self, rec, name, track, request_id, attrs):
        self._rec = rec
        self._name = name
        self._track = track
        self._rid = request_id
        self._attrs = attrs

    def __enter__(self):
        self._open = self._rec._push(
            self._name, self._track, self._rid, self._attrs
        )
        # last so the span never times its own bookkeeping
        self._open.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()  # first, same reason
        self._rec._pop(self._open, t1)
        return False


class TraceRecorder:
    """Bounded ring of host spans + Perfetto exporter + summary.

    Thread-safe: the serving loop, loader generators, and the training
    thread may all record concurrently (nesting is tracked per thread).
    Ring appends are O(1); a full ring evicts oldest-first and counts the
    eviction (``dropped`` / ``trace/dropped_total``) — a long run's ring
    is the *recent* window, which is what a post-mortem wants anyway.
    """

    def __init__(
        self,
        config=None,
        *,
        rank: int = 0,
        registry=None,
        ring_size: Optional[int] = None,
        output_dir: Optional[str] = None,
    ):
        self.config = config
        self.rank = int(rank)
        if ring_size is None:
            ring_size = config.ring_size if config is not None else 4096
        self.output_dir = (
            output_dir
            if output_dir is not None
            else (config.output_dir if config is not None else "trace")
        )
        self._ring: "deque[Span]" = deque(maxlen=int(ring_size))
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._next_id = 0
        self._step = 0
        self.dropped = 0
        self._registry = registry
        # counter handles cached here: the record path must be plain
        # .inc() calls, not name lookups through the registry lock (the
        # serving loop, loader threads, and the training thread all
        # record concurrently — and the <1% overhead claim rides on it)
        self._spans_counter = self._dropped_counter = None
        self._track_counters: Dict[str, Any] = {}
        if registry is not None:
            # pre-register so snapshots carry zeros before the first span
            self._spans_counter = registry.counter(
                "trace/spans_total", help="host trace spans recorded"
            )
            self._dropped_counter = registry.counter(
                "trace/dropped_total",
                help="spans evicted from the bounded trace ring",
            )
        # wall-clock anchor: perf_counter origin is arbitrary, so the
        # export stamps both clocks at construction — readers (and the
        # rank merger) can map span ts to wall time
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()

    # ------------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------------ #

    def set_step(self, step: int) -> None:
        """Tag subsequently recorded spans with ``step`` (the facade sets
        the last completed optimizer step at each boundary)."""
        self._step = int(step)

    @property
    def step(self) -> int:
        return self._step

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def span(self, name: str, *, track: str = "host",
             request_id=None, attrs=None) -> _SpanCtx:
        """Context manager timing one span into the ring."""
        return _SpanCtx(self, name, track, request_id, attrs)

    def _push(self, name, track, request_id, attrs) -> _OpenSpan:
        stack = self._stack()
        parent_id = stack[-1].span_id if stack else None
        with self._lock:
            self._next_id += 1
            span_id = self._next_id
        entry = _OpenSpan(span_id, parent_id, name, track, request_id, attrs)
        stack.append(entry)
        return entry

    def _pop(self, entry: _OpenSpan, t1: float) -> None:
        stack = self._stack()
        # tolerate exit-order surprises (a generator span closed by GC on
        # another frame): unwind to the entry rather than corrupt nesting
        while stack and stack[-1] is not entry:
            stack.pop()
        if stack:
            stack.pop()
        dur = max(t1 - entry.t0, 0.0)
        self_s = max(dur - entry.child_s, 0.0)
        if stack:
            stack[-1].child_s += dur
        self._record(Span(
            entry.span_id, entry.parent_id, entry.name, entry.track,
            entry.t0, dur, self_s, self._step, entry.request_id,
            entry.attrs,
        ))

    def add(self, name: str, t_start: float, t_end: float, *,
            track: str = "host", request_id=None, step=None,
            attrs=None, count_self: bool = True) -> None:
        """Record an explicit ``perf_counter`` interval (no nesting
        participation) — the serving path uses this for admission waits
        and per-request decode slices whose brackets are not lexical.

        ``count_self=False`` records the span with zero self-time: the
        per-request timeline slices deliberately OVERLAP each other (all
        live requests ride one batch decode interval) and the spans that
        already own that wall clock — charging them too would multiply-
        count the window in the critical-path summary and the
        ``trace/<track>_self_s`` counters."""
        with self._lock:
            self._next_id += 1
            span_id = self._next_id
        dur = max(float(t_end) - float(t_start), 0.0)
        self._record(Span(
            span_id, None, name, track, float(t_start), dur,
            dur if count_self else 0.0,
            self._step if step is None else int(step), request_id, attrs,
        ))

    def point(self, name: str, *, track: str = "host", request_id=None,
              attrs=None) -> None:
        """Record a zero-duration marker span (eviction, arrivals)."""
        now = time.perf_counter()
        self.add(name, now, now, track=track, request_id=request_id,
                 attrs=attrs)

    def _record(self, span: Span) -> None:
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
                dropped = True
            else:
                dropped = False
            self._ring.append(span)
        if self._registry is not None:
            self._spans_counter.inc()
            if dropped:
                self._dropped_counter.inc()
            # per-track self-seconds: tracks are a small closed set
            # (facade/step/data/io/serve), so cardinality stays bounded
            # and the handle cache stays tiny
            track_counter = self._track_counters.get(span.track)
            if track_counter is None:
                track_counter = self._registry.counter(
                    f"trace/{span.track}_self_s"
                )
                self._track_counters[span.track] = track_counter
            track_counter.inc(span.self_s)

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def summary(self, top: int = 10) -> Dict[str, Any]:
        """Critical-path/self-time summary of the ring window.

        Host spans on one thread are serial, so total wall is (to ring
        resolution) the sum of self-times — the top self-time entries ARE
        the host critical path.  Returns per-name totals plus the ranked
        ``critical_path`` list.
        """
        spans = self.spans()
        # aggregate by (name, track): the same name can appear on several
        # tracks ("stoke/step" is both the facade phase and the engine
        # apply dispatch; "stoke/io" both loader fetch and checkpoint
        # IO) and merging them would mislabel the critical path
        agg_by_key: Dict[tuple, Dict[str, Any]] = {}
        for s in spans:
            agg = agg_by_key.setdefault(
                (s.name, s.track),
                {"count": 0, "total_s": 0.0, "self_s": 0.0,
                 "track": s.track},
            )
            agg["count"] += 1
            agg["total_s"] += s.dur_s
            agg["self_s"] += s.self_s
        # display labels: the bare name when it is track-unique, else
        # "name [track]" so no two rows collide
        name_tracks: Dict[str, set] = {}
        for name, track in agg_by_key:
            name_tracks.setdefault(name, set()).add(track)
        by_name = {
            (name if len(name_tracks[name]) == 1 else f"{name} [{track}]"):
                agg
            for (name, track), agg in agg_by_key.items()
        }
        total_self = sum(a["self_s"] for a in by_name.values())
        ranked = sorted(
            by_name.items(), key=lambda kv: -kv[1]["self_s"]
        )[:max(int(top), 0)]
        return {
            "spans": len(spans),
            "dropped": self.dropped,
            # registry-name alias (ISSUE 16): a truncated ring must not
            # masquerade as a complete critical path — dashboards keyed on
            # the counter name read the same figure off the summary
            "trace/dropped_total": self.dropped,
            "tracks": sorted({s.track for s in spans}),
            "window_self_s": total_self,
            "by_name": by_name,
            "critical_path": [
                {
                    "name": name,
                    "track": agg["track"],
                    "count": agg["count"],
                    "self_s": agg["self_s"],
                    "frac": (agg["self_s"] / total_self) if total_self else 0.0,
                }
                for name, agg in ranked
            ],
        }

    # ------------------------------------------------------------------ #
    # Chrome/Perfetto export
    # ------------------------------------------------------------------ #

    def to_trace_events(self) -> List[Dict[str, Any]]:
        """The ring as chrome-trace events: one ``"X"`` duration event per
        span on a per-track thread (requests get their own
        ``serve/req<id>`` thread — the per-request timeline), preceded by
        ``"M"`` process/thread-name metadata."""
        spans = self.spans()
        tids: Dict[str, int] = {}

        def tid_for(label: str) -> int:
            if label not in tids:
                tids[label] = len(tids) + 1
            return tids[label]

        events: List[Dict[str, Any]] = []
        for s in spans:
            label = (
                f"{s.track}/req{s.request_id}"
                if s.request_id is not None
                else s.track
            )
            args: Dict[str, Any] = {"step": s.step, "span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            if s.request_id is not None:
                args["request_id"] = s.request_id
            if s.attrs:
                args.update(s.attrs)
            events.append({
                "name": s.name,
                "ph": "X",
                "ts": s.t_start * 1e6,
                "dur": s.dur_s * 1e6,
                "pid": self.rank,
                "tid": tid_for(label),
                "args": args,
            })
        meta: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": self.rank, "tid": 0,
            "args": {"name": f"stoke rank{self.rank}"},
        }]
        for label, tid in sorted(tids.items(), key=lambda kv: kv[1]):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": self.rank,
                "tid": tid, "args": {"name": label},
            })
        return meta + events

    def export(self, path: Optional[str] = None) -> str:
        """Write ``trace.rank<N>.json`` (chrome-trace JSON object format);
        returns the path.  Every rank writes its own file — the merge tool
        aligns them by step anchor."""
        if path is None:
            os.makedirs(self.output_dir, exist_ok=True)
            path = os.path.join(self.output_dir, f"trace.rank{self.rank}.json")
        doc = {
            "traceEvents": self.to_trace_events(),
            "displayTimeUnit": "ms",
            "stoke": {
                "rank": self.rank,
                "dropped": self.dropped,
                "anchor_wall_s": self._anchor_wall,
                "anchor_perf_s": self._anchor_perf,
            },
        }
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.write("\n")
        os.replace(tmp, path)
        return path


# --------------------------------------------------------------------------- #
# the composed span helper (subsumes the old telemetry._ComposedContext)
# --------------------------------------------------------------------------- #


class ComposedContext:
    """Enter/exit a sequence of context managers as one (annotation +
    host span + timer)."""

    __slots__ = ("_cms",)

    def __init__(self, *cms):
        self._cms = cms

    def __enter__(self):
        for cm in self._cms:
            cm.__enter__()
        return self

    def __exit__(self, *exc):
        result = False
        for cm in reversed(self._cms):
            if cm.__exit__(*exc):
                result = True
        return result


def trace_span(
    name: str,
    *,
    track: str = "host",
    request_id=None,
    attrs: Optional[Dict[str, Any]] = None,
    annotate: bool = True,
    timer=None,
):
    """THE span primitive every timed section routes through: emits the
    xprof ``TraceAnnotation`` (when ``annotate``), a host span into every
    registered :class:`TraceRecorder`, and accumulates ``timer`` (a
    registry ``_Timer``) — one context manager instead of three
    hand-rolled pairings.  With no recorder registered and no timer it
    returns the bare annotation: exactly the pre-tracing call sites'
    behavior and cost."""
    recs = list(_RECORDERS) if _RECORDERS else ()
    cms: List[Any] = []
    if annotate:
        cms.append(xprof_span(name))
    for rec in recs:
        cms.append(rec.span(name, track=track, request_id=request_id,
                            attrs=attrs))
    if timer is not None:
        cms.append(timer)
    if len(cms) == 1:
        return cms[0]
    return ComposedContext(*cms)


def trace_point(name: str, *, track: str = "host", request_id=None,
                attrs: Optional[Dict[str, Any]] = None) -> None:
    """Zero-duration marker into every registered recorder (no-op when
    none is registered — the default-OFF fast path)."""
    if not _RECORDERS:
        return
    for rec in list(_RECORDERS):
        rec.point(name, track=track, request_id=request_id, attrs=attrs)


def trace_add(name: str, t_start: float, t_end: float, *,
              track: str = "host", request_id=None,
              attrs: Optional[Dict[str, Any]] = None,
              count_self: bool = True) -> None:
    """Explicit ``perf_counter`` interval into every registered recorder
    (no-op when none is registered).  ``count_self=False`` for timeline
    spans that overlap wall clock another span already owns."""
    if not _RECORDERS:
        return
    for rec in list(_RECORDERS):
        rec.add(name, t_start, t_end, track=track, request_id=request_id,
                attrs=attrs, count_self=count_self)


def tracing_active() -> bool:
    """True when at least one recorder is registered (serving uses this to
    skip per-request slice bookkeeping entirely when tracing is off)."""
    return bool(_RECORDERS)


def request_spans(request_id) -> List[Span]:
    """Every ringed span tagged with ``request_id`` across the registered
    recorders — the SLO violation attribution (ISSUE 16) re-walks a
    finished request's timeline through this.  Empty when tracing is off
    (the attribution then reports span coverage ``"none"``, never a
    vacuously-complete walk)."""
    if not _RECORDERS:
        return []
    out: List[Span] = []
    for rec in list(_RECORDERS):
        out.extend(s for s in rec.spans() if s.request_id == request_id)
    return out


def dropped_total() -> int:
    """Spans evicted across the registered recorders' rings.  Nonzero
    means any span-derived walk (critical path, SLO attribution) may be
    missing intervals and must report itself partial."""
    return sum(rec.dropped for rec in list(_RECORDERS))

"""Structured step-event schema: one JSONL record per logged step window.

The JSONL sink writes these; dashboards/regression tooling read them back
with :func:`read_step_events`.  The schema is versioned (``schema`` field)
and validated on both ends (:func:`validate_step_event`) so a field drifting
type silently is a test failure, not a 3am dashboard mystery.

Field semantics (all times in seconds, all rates per second):

- ``step``: optimizer step the window ENDS at.
- ``window_steps``: optimizer steps covered by this record (a train_steps
  segment emits ONE record covering the whole segment when any cadence
  boundary was crossed inside it, window > 1).
- ``host_dispatch_s``: host wall-clock spent inside facade phases since the
  previous record (dispatch cost, NOT device time — device work is async).
- ``device_step_s``: sampled device time of one optimizer step, measured by
  bracketing a dispatch with ``block_until_ready`` at the logging cadence;
  ``null`` when sampling is disabled or no sample landed in the window.
- ``loader_wait_s``: host time the training loop spent blocked on the data
  loader since the previous record (starvation indicator — compare against
  ``host_dispatch_s``).
- ``samples_per_s`` / ``tokens_per_s``: window rates from the data-layer
  counters (tokens only when a sequence pipeline reports them).
- ``grad_norm``: global gradient norm of the accumulated buffer at the
  boundary (only when ``TelemetryConfig.grad_norm`` — costs one reduction).
- ``loss_scale`` / ``loss_scale_events``: fp16 dynamic scale and the count
  of backoff/growth transitions observed so far (``null``/0 outside fp16).
- ``compiles_total`` / ``recompiles`` / ``compile_time_s``: XLA compile
  activity (recompiles = compiles beyond each jit entry's first — the
  silent TPU perf killer this subsystem exists to surface).
- ``hbm_*``: device-0 memory stats high-watermark (``null`` on backends
  that report none, e.g. CPU).
"""

from __future__ import annotations

import json
import numbers
from typing import Any, Dict, List, Optional

#: schema identifier embedded in every record
STEP_EVENT_SCHEMA = "stoke_tpu.telemetry.step/v1"

#: field -> (required, allowed python kinds); "number" accepts int/float,
#: "nullable_number" also accepts None
STEP_EVENT_FIELDS: Dict[str, tuple] = {
    "schema": (True, "string"),
    "ts": (True, "number"),
    "step": (True, "int"),
    "rank": (True, "int"),
    "window_steps": (True, "int"),
    "host_dispatch_s": (True, "number"),
    "device_step_s": (False, "nullable_number"),
    "loader_wait_s": (True, "number"),
    "samples_per_s": (False, "nullable_number"),
    "tokens_per_s": (False, "nullable_number"),
    "samples_total": (True, "number"),
    "ema_loss": (False, "nullable_number"),
    "step_loss": (False, "nullable_number"),
    "grad_norm": (False, "nullable_number"),
    "loss_scale": (False, "nullable_number_or_list"),
    "loss_scale_events": (False, "int"),
    "skipped_steps": (False, "number"),
    "compiles_total": (True, "int"),
    "recompiles": (True, "int"),
    "compile_time_s": (True, "number"),
    # gradient-transport accounting (ISSUE 2; null without a CommConfig):
    # per-window bytes the gradient exchange moves per device — prequant is
    # the fp32 schedule's bytes, onwire the configured wire dtype's;
    # compression = prequant/onwire; residual_norm gauges the carried
    # error-feedback residual (the SHARDED residual's global norm under the
    # ISSUE 8 weight-update-sharded path — same units, 1/N of it per
    # replica).  param_gather (ISSUE 8; null unless the sharded path is
    # active) is the second wire leg: the updated-parameter all-gather back
    # to the replicated tier placement after the shard-local step
    "comm_bytes_prequant": (False, "nullable_number"),
    "comm_bytes_onwire": (False, "nullable_number"),
    "comm_bytes_param_gather": (False, "nullable_number"),
    "comm_compression": (False, "nullable_number"),
    "comm_residual_norm": (False, "nullable_number"),
    # health sentinels (ISSUE 3; null without a HealthConfig): per-step
    # diagnostics computed inside the compiled step — param_norm is the
    # global norm of the updated parameters, update_ratio the step's
    # ||delta param|| / ||param||, nonfinite_leaves the count of gradient
    # leaves carrying any non-finite value; health_anomalies is the
    # cumulative detector-firing count
    "param_norm": (False, "nullable_number"),
    "update_ratio": (False, "nullable_number"),
    "nonfinite_leaves": (False, "nullable_number"),
    "health_anomalies": (False, "nullable_number"),
    # step-time attribution (ISSUE 4; null without an AttributionConfig):
    # per-window achieved TFLOP/s and MFU from the analytic CostCard
    # FLOPs of every dispatched program, HBM-bandwidth utilization
    # against the configured peak, and the compute/memory/comm/host
    # bound classification
    "achieved_tflops": (False, "nullable_number"),
    "mfu": (False, "nullable_number"),
    "hbm_bw_util": (False, "nullable_number"),
    "bound": (False, "nullable_string"),
    # goodput ledger (ISSUE 4): this window's wall clock partitioned
    # into productive compute vs accounted losses; the buckets sum to
    # the window wall time (ts delta to the previous record)
    "goodput_productive_s": (False, "nullable_number"),
    "goodput_compile_s": (False, "nullable_number"),
    # compile split (ISSUE 6; additive): the compile+recompile seconds
    # partitioned into fresh XLA backend compiles vs AOT-compile-cache
    # warm-start loads (fresh + cached == compile + recompile within
    # rounding); null without an AttributionConfig
    "goodput_compile_fresh_s": (False, "nullable_number"),
    "goodput_compile_cached_s": (False, "nullable_number"),
    "goodput_recompile_s": (False, "nullable_number"),
    "goodput_loader_s": (False, "nullable_number"),
    "goodput_checkpoint_s": (False, "nullable_number"),
    "goodput_halt_s": (False, "nullable_number"),
    # persistent compile cache (ISSUE 6; additive, null without a
    # CompileConfig): cumulative AOT hit/miss counts and the original
    # compile seconds the cache's hits reclaimed this run
    "compile_cache_hits": (False, "nullable_number"),
    "compile_cache_misses": (False, "nullable_number"),
    "compile_cache_saved_s": (False, "nullable_number"),
    # fleet view (ISSUE 5; keys absent without a FleetConfig, null between
    # exchange windows): cross-host skew aggregates derived from the
    # in-band per-host signal exchange — hosts/window identify the
    # exchange, wall_median/max the fleet step-time spread, step/loader
    # skew + lag the straggler's excess over the fleet median,
    # straggler_host/zscore/skew_class the verdict ("loader" = input-
    # pipeline-bound host, "compute" = slow step), and barrier fields the
    # barrier-wait attribution (the max wait, charged to the LAST arrival
    # — the host the fleet was waiting for, not the waiters)
    "fleet/hosts": (False, "nullable_number"),
    "fleet/window": (False, "nullable_number"),
    "fleet/wall_median_s": (False, "nullable_number"),
    "fleet/wall_max_s": (False, "nullable_number"),
    "fleet/step_skew_s": (False, "nullable_number"),
    "fleet/loader_skew_s": (False, "nullable_number"),
    "fleet/lag_s": (False, "nullable_number"),
    "fleet/lag_frac": (False, "nullable_number"),
    "fleet/straggler_host": (False, "nullable_number"),
    "fleet/straggler_zscore": (False, "nullable_number"),
    "fleet/skew_class": (False, "nullable_string"),
    "fleet/barrier_wait_s": (False, "nullable_number"),
    "fleet/barrier_charged_host": (False, "nullable_number"),
    # skew-reactive input rebalancing (ISSUE 14; keys absent unless
    # FleetConfig.rebalance is ON — a rebalance-off fleet run's records
    # are byte-identical to pre-ISSUE-14 ones): share_self is this host's
    # current per-slice read share (rows), shift_rows/from/to describe the
    # actuation applied at THIS window close (null between actuations),
    # shifts the cumulative actuation count
    "fleet/rebalance_share_self": (False, "nullable_number"),
    "fleet/rebalance_shift_rows": (False, "nullable_number"),
    "fleet/rebalance_from_host": (False, "nullable_number"),
    "fleet/rebalance_to_host": (False, "nullable_number"),
    "fleet/rebalance_shifts": (False, "nullable_number"),
    # resilience (ISSUE 7; keys absent without a ResilienceConfig):
    # cumulative preemption notices honored, emergency checkpoints
    # written, corrupt tags quarantined at resume; restarts is the
    # supervisor attempt number this process is (0 = first run);
    # resumed_step the optimizer step this run restored from (null until
    # a resume happens), lost_steps the steps a newer-but-invalid tag
    # had recorded beyond the resumed one; elastic_resumes (ISSUE 14) the
    # resumes that re-sharded state saved on a DIFFERENT topology
    "resilience/preemptions": (False, "nullable_number"),
    "resilience/emergency_saves": (False, "nullable_number"),
    "resilience/quarantined": (False, "nullable_number"),
    "resilience/restarts": (False, "nullable_number"),
    "resilience/resumed_step": (False, "nullable_number"),
    "resilience/lost_steps": (False, "nullable_number"),
    "resilience/elastic_resumes": (False, "nullable_number"),
    # serving engine (ISSUE 9; keys absent without a ServingEngine emit —
    # training records NEVER carry them): cumulative request/token
    # counters, capacity gauges (queue depth, decode-slot fill, KV-block
    # occupancy), exact p50/p99 of the TTFT/TPOT reservoirs, the
    # queue/prefill/decode goodput split of the serve wall clock
    # (sums-to-wall, like the training goodput ledger), and the weight-
    # quantization compression ratio (param bytes fp / as-served)
    "serve/requests": (False, "nullable_number"),
    "serve/completed": (False, "nullable_number"),
    "serve/tokens_out": (False, "nullable_number"),
    "serve/queue_depth": (False, "nullable_number"),
    "serve/active_seqs": (False, "nullable_number"),
    "serve/batch_fill": (False, "nullable_number"),
    "serve/kv_blocks_used": (False, "nullable_number"),
    "serve/kv_block_occupancy": (False, "nullable_number"),
    "serve/ttft_p50_s": (False, "nullable_number"),
    "serve/ttft_p99_s": (False, "nullable_number"),
    "serve/tpot_p50_s": (False, "nullable_number"),
    "serve/tpot_p99_s": (False, "nullable_number"),
    "serve/goodput_queue_s": (False, "nullable_number"),
    "serve/goodput_prefill_s": (False, "nullable_number"),
    "serve/goodput_decode_s": (False, "nullable_number"),
    "serve/quant_compression": (False, "nullable_number"),
    # serve fast path (ISSUE 13): chunked-prefill dispatch count and
    # tokens drawn through the sampling path (both 0 for a greedy,
    # unchunked engine — the fields still ride every serve record)
    "serve/prefill_chunks": (False, "nullable_number"),
    "serve/sampled_tokens": (False, "nullable_number"),
    # speculative decoding (ISSUE 17; keys absent without a speculative
    # config — ServeMetrics omits them until enable_speculative(), so a
    # non-speculative engine's records are byte-identical to pre-ISSUE-17
    # ones): draft tokens scored by verify dispatches and draft tokens
    # accepted into the output stream (accepted/drafted = accept rate)
    "serve/spec_draft_tokens": (False, "nullable_number"),
    "serve/spec_accepted_tokens": (False, "nullable_number"),
    # SLO observatory (ISSUE 16; keys absent until a request carries a
    # RequestSLO — an SLO-free engine's records are byte-identical to
    # pre-ISSUE-16 ones): submitted/finished/violated counts over
    # SLO-tagged requests, TTFT/TPOT/overall attainment fractions (null
    # before the first SLO-tagged finish), goodput under SLO (tokens/s
    # from requests that met their deadline — the arXiv:2605.25645
    # measuring stick), the pooled queue-ETA forecast (median admission
    # wait), min TTFT deadline headroom over in-flight requests (null
    # when none is awaiting its first token; negative = busted), and the
    # count of attributions degraded by a truncated/inactive span ring
    "serve/slo_requests": (False, "nullable_number"),
    "serve/slo_finished": (False, "nullable_number"),
    "serve/slo_violations": (False, "nullable_number"),
    "serve/slo_ttft_attainment": (False, "nullable_number"),
    "serve/slo_tpot_attainment": (False, "nullable_number"),
    "serve/slo_attainment": (False, "nullable_number"),
    "serve/slo_goodput_tokens_per_s": (False, "nullable_number"),
    "serve/slo_queue_eta_s": (False, "nullable_number"),
    "serve/slo_headroom_min_s": (False, "nullable_number"),
    "serve/slo_partial_attributions": (False, "nullable_number"),
    # SLO-aware TFLOP goodput (ISSUE 18; key absent unless BOTH the SLO
    # observatory is active AND ServeConfig.cost_cards armed a per-token
    # cost — an SLO-only engine's records stay byte-identical to
    # pre-ISSUE-18 ones)
    "serve/slo_goodput_tflops_per_s": (False, "nullable_number"),
    # serve roofline / cost accounting (ISSUE 18; keys absent without
    # ServeConfig.cost_cards — an unconfigured engine's records are
    # byte-identical to pre-ISSUE-18 ones): cumulative analytic FLOPs /
    # bytes dispatched (XLA cost analysis per program signature, fed per
    # dispatch), model-FLOPs-per-emitted-token, MFU and HBM-bandwidth
    # utilization over dispatch-busy seconds, the decode roofline's
    # attainable per-dispatch TPOT (max of the compute- and bandwidth-
    # limited bounds at the AttributionConfig peaks) vs the achieved
    # decode wall per dispatch, arithmetic intensity of plain decode and
    # of the speculative verify program (the PR-17 k-token uplift,
    # measured), the decode-family program's analytic bound class
    # ("memory"/"compute"), and the count of distinct programs analyzed
    "serve/cost_flops": (False, "nullable_number"),
    "serve/cost_bytes": (False, "nullable_number"),
    "serve/cost_flops_per_token": (False, "nullable_number"),
    "serve/cost_mfu": (False, "nullable_number"),
    "serve/cost_hbm_bw_util": (False, "nullable_number"),
    "serve/cost_attainable_tpot_s": (False, "nullable_number"),
    "serve/cost_achieved_tpot_s": (False, "nullable_number"),
    "serve/cost_decode_intensity": (False, "nullable_number"),
    "serve/cost_verify_intensity": (False, "nullable_number"),
    "serve/cost_decode_bound": (False, "nullable_string"),
    "serve/cost_cards": (False, "nullable_number"),
    # serve KV-headroom forecast (ISSUE 19; key absent without a
    # MemoryConfig — a memory-free engine's records are byte-identical
    # to pre-ISSUE-19 ones): free KV-pool bytes minus the worst-case
    # blocks-to-completion of every in-flight request (negative =
    # admission has over-committed the pool)
    "serve/mem_headroom_bytes": (False, "nullable_number"),
    # per-layer numerics observatory (ISSUE 12; keys absent without a
    # NumericsConfig): groups is the fixed group count of the run's param
    # tree; per_group the nullable {group: {stat: value}} block (grad/
    # param/update rms, absmax, nonfinite element count, plus wire_err /
    # quant_err when those signal families observed anything) the offline
    # numerics_diff.py aligns between runs; provenance_* name the FIRST
    # module group a non-finite value was attributed to (null while the
    # run is clean); quant_err_* the serving-weight dequant error of the
    # worst-quantized module (null without int8-served weights)
    "numerics/groups": (False, "nullable_number"),
    "numerics/per_group": (False, "nullable_group_block"),
    "numerics/provenance_group": (False, "nullable_number"),
    "numerics/provenance_name": (False, "nullable_string"),
    "numerics/provenance_field": (False, "nullable_string"),
    "numerics/quant_err_max": (False, "nullable_number"),
    "numerics/quant_err_group": (False, "nullable_string"),
    # HBM capacity ledger (ISSUE 19; keys absent without a MemoryConfig
    # — an unconfigured run's records are byte-identical to pre-ISSUE-19
    # ones): the analytic per-subsystem resident ledger (per-device
    # bytes from shape/dtype/sharding trees — the five components
    # recombine EXACTLY into resident_bytes; unregistered subsystems are
    # null, empty ones 0), the max-over-programs memory_analysis temp
    # peak, the predicted peak (resident + temp), device capacity
    # (MemoryConfig.capacity_bytes override or live bytes_limit; null on
    # the CPU simulator), headroom = capacity - predicted peak, and the
    # reconciliation gauge: live bytes-in-use minus the analytic
    # resident total (fragmentation / unledgered subsystems; null
    # without memory_stats)
    "mem/params_bytes": (False, "nullable_number"),
    "mem/opt_state_bytes": (False, "nullable_number"),
    "mem/transport_bytes": (False, "nullable_number"),
    "mem/kv_cache_bytes": (False, "nullable_number"),
    "mem/snapshot_bytes": (False, "nullable_number"),
    "mem/resident_bytes": (False, "nullable_number"),
    "mem/temp_peak_bytes": (False, "nullable_number"),
    "mem/predicted_peak_bytes": (False, "nullable_number"),
    "mem/capacity_bytes": (False, "nullable_number"),
    "mem/headroom_bytes": (False, "nullable_number"),
    "mem/unattributed_bytes": (False, "nullable_number"),
    "hbm_bytes_in_use": (False, "nullable_number"),
    "hbm_peak_bytes": (False, "nullable_number"),
    "hbm_bytes_limit": (False, "nullable_number"),
}

#: the fleet-view subset of the schema (populated via ``build_step_event``'s
#: ``fleet=`` dict; stoke_tpu.telemetry.fleet.FLEET_EVENT_FIELDS must match)
FLEET_STEP_FIELDS = tuple(
    f for f in STEP_EVENT_FIELDS if f.startswith("fleet/")
)

#: the rebalance subset (ISSUE 14): emitted ONLY when
#: ``FleetConfig.rebalance`` is on — the monitor omits these keys from its
#: window dict otherwise, and ``build_step_event`` honors the omission, so
#: a rebalance-off run adds zero JSONL fields
FLEET_REBALANCE_FIELDS = tuple(
    f for f in FLEET_STEP_FIELDS if f.startswith("fleet/rebalance_")
)

#: the resilience subset of the schema (populated via ``build_step_event``'s
#: ``resilience=`` dict; ResilienceMonitor.event_fields must match)
RESILIENCE_STEP_FIELDS = tuple(
    f for f in STEP_EVENT_FIELDS if f.startswith("resilience/")
)

#: the serving subset of the schema (populated via ``build_step_event``'s
#: ``serve=`` dict; ServeMetrics.event_fields must match)
SERVE_STEP_FIELDS = tuple(
    f for f in STEP_EVENT_FIELDS if f.startswith("serve/")
)

#: the SLO subset (ISSUE 16): emitted ONLY once a request carries a
#: RequestSLO — the tracker omits these keys from its block otherwise,
#: and ``build_step_event`` honors the omission, so an SLO-free engine
#: adds zero JSONL fields (the FLEET_REBALANCE_FIELDS discipline)
SERVE_SLO_FIELDS = tuple(
    f for f in SERVE_STEP_FIELDS if f.startswith("serve/slo_")
)

#: the speculative-decoding subset (ISSUE 17): emitted ONLY by engines
#: with ``ServeConfig.speculative_k`` set — ServeMetrics omits these keys
#: until ``enable_speculative()``, and ``build_step_event`` honors the
#: omission (the SERVE_SLO_FIELDS discipline)
SERVE_SPEC_FIELDS = tuple(
    f for f in SERVE_STEP_FIELDS if f.startswith("serve/spec_")
)

#: the cost/roofline subset (ISSUE 18): emitted ONLY by engines with
#: ``ServeConfig.cost_cards`` on — the ServeCostObservatory's block is
#: merged into the serve dict only when it exists, and
#: ``build_step_event`` honors the omission (the SERVE_SLO_FIELDS
#: discipline)
SERVE_COST_FIELDS = tuple(
    f for f in SERVE_STEP_FIELDS if f.startswith("serve/cost_")
)

#: the serve memory-headroom subset (ISSUE 19): emitted ONLY by engines
#: with a MemoryConfig — the MemoryObservatory's field is merged into
#: the serve dict only when it exists, and ``build_step_event`` honors
#: the omission (the SERVE_SLO_FIELDS discipline)
SERVE_MEM_FIELDS = tuple(
    f for f in SERVE_STEP_FIELDS if f.startswith("serve/mem_")
)

#: the HBM capacity-ledger subset (ISSUE 19; populated via
#: ``build_step_event``'s ``memory=`` dict; MemoryObservatory
#: .event_fields must match)
MEM_STEP_FIELDS = tuple(
    f for f in STEP_EVENT_FIELDS if f.startswith("mem/")
)

#: the per-layer-numerics subset (populated via ``build_step_event``'s
#: ``numerics=`` dict; NumericsMonitor.event_fields must match)
NUMERICS_STEP_FIELDS = tuple(
    f for f in STEP_EVENT_FIELDS if f.startswith("numerics/")
)


def _kind_ok(value: Any, kind: str) -> bool:
    if kind == "string":
        return isinstance(value, str)
    if kind == "int":
        return isinstance(value, numbers.Integral) and not isinstance(value, bool)
    if kind == "number":
        return isinstance(value, numbers.Real) and not isinstance(value, bool)
    if kind == "nullable_number":
        return value is None or _kind_ok(value, "number")
    if kind == "nullable_string":
        return value is None or isinstance(value, str)
    if kind == "nullable_number_or_list":
        if value is None or _kind_ok(value, "number"):
            return True
        return isinstance(value, list) and all(
            _kind_ok(v, "number") for v in value
        )
    if kind == "nullable_group_block":
        # {group_name: {stat_name: number-or-null}} — the per-layer
        # numerics block (ISSUE 12); group/stat sets vary per model, so
        # only the SHAPE is schema-checked here (the stat names are the
        # numerics module's wire format, drift-guarded in its own tests)
        if value is None:
            return True
        return isinstance(value, dict) and all(
            isinstance(k, str)
            and isinstance(v, dict)
            and all(
                isinstance(sk, str) and _kind_ok(sv, "nullable_number")
                for sk, sv in v.items()
            )
            for k, v in value.items()
        )
    raise AssertionError(f"unknown schema kind {kind!r}")


def validate_step_event(record: Dict[str, Any]) -> None:
    """Raise ``ValueError`` when ``record`` violates the v1 step schema
    (missing required field, wrong type, unknown field, wrong version)."""
    if not isinstance(record, dict):
        raise ValueError(f"step event must be a dict, got {type(record).__name__}")
    if record.get("schema") != STEP_EVENT_SCHEMA:
        raise ValueError(
            f"unknown step-event schema {record.get('schema')!r} "
            f"(expected {STEP_EVENT_SCHEMA!r})"
        )
    for field, (required, kind) in STEP_EVENT_FIELDS.items():
        if field not in record:
            if required:
                raise ValueError(f"step event missing required field {field!r}")
            continue
        if not _kind_ok(record[field], kind):
            raise ValueError(
                f"step event field {field!r} has invalid value "
                f"{record[field]!r} (expected {kind})"
            )
    unknown = set(record) - set(STEP_EVENT_FIELDS)
    if unknown:
        raise ValueError(f"step event has unknown fields {sorted(unknown)}")


def read_step_events(path: str, validate: bool = True) -> List[Dict[str, Any]]:
    """Load a JSONL step-event file back into records (the consumer half of
    the schema contract; round-tripped in tests/test_telemetry.py)."""
    out = []
    with open(path) as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{line_no}: invalid JSON ({e})") from e
            if validate:
                try:
                    validate_step_event(rec)
                except ValueError as e:
                    raise ValueError(f"{path}:{line_no}: {e}") from e
            out.append(rec)
    return out


def _round(value: Optional[float], digits: int = 6):
    if value is None:
        return None
    return round(float(value), digits)


def build_step_event(
    *,
    ts: float,
    step: int,
    rank: int,
    window_steps: int,
    host_dispatch_s: float,
    loader_wait_s: float,
    samples_total: float,
    compiles_total: int,
    recompiles: int,
    compile_time_s: float,
    device_step_s: Optional[float] = None,
    samples_per_s: Optional[float] = None,
    tokens_per_s: Optional[float] = None,
    ema_loss: Optional[float] = None,
    step_loss: Optional[float] = None,
    grad_norm: Optional[float] = None,
    loss_scale=None,
    loss_scale_events: int = 0,
    skipped_steps: float = 0.0,
    comm_bytes_prequant: Optional[float] = None,
    comm_bytes_onwire: Optional[float] = None,
    comm_bytes_param_gather: Optional[float] = None,
    comm_compression: Optional[float] = None,
    comm_residual_norm: Optional[float] = None,
    param_norm: Optional[float] = None,
    update_ratio: Optional[float] = None,
    nonfinite_leaves: Optional[float] = None,
    health_anomalies: Optional[float] = None,
    achieved_tflops: Optional[float] = None,
    mfu: Optional[float] = None,
    hbm_bw_util: Optional[float] = None,
    bound: Optional[str] = None,
    goodput_productive_s: Optional[float] = None,
    goodput_compile_s: Optional[float] = None,
    goodput_compile_fresh_s: Optional[float] = None,
    goodput_compile_cached_s: Optional[float] = None,
    goodput_recompile_s: Optional[float] = None,
    goodput_loader_s: Optional[float] = None,
    goodput_checkpoint_s: Optional[float] = None,
    goodput_halt_s: Optional[float] = None,
    compile_cache_hits: Optional[int] = None,
    compile_cache_misses: Optional[int] = None,
    compile_cache_saved_s: Optional[float] = None,
    hbm_bytes_in_use: Optional[int] = None,
    hbm_peak_bytes: Optional[int] = None,
    hbm_bytes_limit: Optional[int] = None,
    fleet: Optional[Dict[str, Any]] = None,
    resilience: Optional[Dict[str, Any]] = None,
    serve: Optional[Dict[str, Any]] = None,
    numerics: Optional[Dict[str, Any]] = None,
    memory: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble + validate a v1 step event (single construction point so the
    schema cannot drift from the writer)."""
    record = {
        "schema": STEP_EVENT_SCHEMA,
        "ts": float(ts),
        "step": int(step),
        "rank": int(rank),
        "window_steps": int(window_steps),
        "host_dispatch_s": _round(host_dispatch_s),
        "device_step_s": _round(device_step_s),
        "loader_wait_s": _round(loader_wait_s),
        "samples_per_s": _round(samples_per_s, 3),
        "tokens_per_s": _round(tokens_per_s, 3),
        "samples_total": float(samples_total),
        "ema_loss": _round(ema_loss),
        "step_loss": _round(step_loss),
        "grad_norm": _round(grad_norm),
        "loss_scale": (
            [float(v) for v in loss_scale]
            if isinstance(loss_scale, (list, tuple))
            else (None if loss_scale is None else float(loss_scale))
        ),
        "loss_scale_events": int(loss_scale_events),
        "skipped_steps": float(skipped_steps),
        "compiles_total": int(compiles_total),
        "recompiles": int(recompiles),
        "compile_time_s": _round(compile_time_s),
        "comm_bytes_prequant": (
            None if comm_bytes_prequant is None else float(comm_bytes_prequant)
        ),
        "comm_bytes_onwire": (
            None if comm_bytes_onwire is None else float(comm_bytes_onwire)
        ),
        "comm_bytes_param_gather": (
            None
            if comm_bytes_param_gather is None
            else float(comm_bytes_param_gather)
        ),
        "comm_compression": _round(comm_compression, 4),
        "comm_residual_norm": _round(comm_residual_norm),
        "param_norm": _round(param_norm),
        "update_ratio": _round(update_ratio, 8),
        "nonfinite_leaves": (
            None if nonfinite_leaves is None else float(nonfinite_leaves)
        ),
        "health_anomalies": (
            None if health_anomalies is None else float(health_anomalies)
        ),
        # 9 digits: CPU-scale smoke runs produce sub-micro TFLOP/s values
        # that 4-digit rounding would collapse to a lying 0.0
        "achieved_tflops": _round(achieved_tflops, 9),
        "mfu": _round(mfu, 9),
        "hbm_bw_util": _round(hbm_bw_util, 9),
        "bound": bound,
        # goodput buckets are rounded uniformly so their sum stays within
        # rounding distance of the window wall clock (the acceptance
        # contract: buckets sum to wall time within 1%)
        "goodput_productive_s": _round(goodput_productive_s),
        "goodput_compile_s": _round(goodput_compile_s),
        "goodput_compile_fresh_s": _round(goodput_compile_fresh_s),
        "goodput_compile_cached_s": _round(goodput_compile_cached_s),
        "goodput_recompile_s": _round(goodput_recompile_s),
        "goodput_loader_s": _round(goodput_loader_s),
        "goodput_checkpoint_s": _round(goodput_checkpoint_s),
        "goodput_halt_s": _round(goodput_halt_s),
        "compile_cache_hits": (
            None if compile_cache_hits is None else int(compile_cache_hits)
        ),
        "compile_cache_misses": (
            None if compile_cache_misses is None
            else int(compile_cache_misses)
        ),
        "compile_cache_saved_s": _round(compile_cache_saved_s),
        "hbm_bytes_in_use": hbm_bytes_in_use,
        "hbm_peak_bytes": hbm_peak_bytes,
        "hbm_bytes_limit": hbm_bytes_limit,
    }
    if fleet is not None:
        # fleet view (ISSUE 5): keys appear only when a FleetMonitor is
        # attached; the slash-named fields cannot be python kwargs, so
        # they arrive as one dict — unknown keys fail validation below
        for key in FLEET_STEP_FIELDS:
            if key in FLEET_REBALANCE_FIELDS and key not in fleet:
                # rebalance keys ride only when the actuator is configured
                # (ISSUE 14 default-OFF contract: zero new JSONL fields)
                continue
            value = fleet.get(key)
            if key == "fleet/skew_class":
                record[key] = value
            elif key in ("fleet/hosts", "fleet/window",
                         "fleet/straggler_host",
                         "fleet/barrier_charged_host",
                         "fleet/rebalance_from_host",
                         "fleet/rebalance_to_host"):
                record[key] = None if value is None else int(value)
            else:
                record[key] = _round(value)
        unknown = set(fleet) - set(FLEET_STEP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown fleet step-event fields {sorted(unknown)}"
            )
    if resilience is not None:
        # resilience counters (ISSUE 7): keys appear only when a
        # ResilienceMonitor is attached; slash-named fields arrive as one
        # dict like the fleet view's — unknown keys fail validation
        for key in RESILIENCE_STEP_FIELDS:
            value = resilience.get(key)
            record[key] = None if value is None else float(value)
        unknown = set(resilience) - set(RESILIENCE_STEP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown resilience step-event fields {sorted(unknown)}"
            )
    if serve is not None:
        # serving fields (ISSUE 9): keys appear only when a ServingEngine
        # emits the record — a training run's JSONL never carries them
        for key in SERVE_STEP_FIELDS:
            if (
                key in SERVE_SLO_FIELDS
                or key in SERVE_COST_FIELDS
                or key in SERVE_MEM_FIELDS
            ) and key not in serve:
                # SLO keys ride only once a request carried a RequestSLO
                # (ISSUE 16 default-OFF contract: zero new JSONL fields);
                # cost keys only with ServeConfig.cost_cards (ISSUE 18),
                # memory headroom only with a MemoryConfig (ISSUE 19) —
                # same contract
                continue
            value = serve.get(key)
            if key == "serve/cost_decode_bound":
                # the one string-kind serve field ("memory"/"compute")
                record[key] = value
            else:
                record[key] = (
                    None if value is None else _round(float(value))
                )
        unknown = set(serve) - set(SERVE_STEP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown serve step-event fields {sorted(unknown)}"
            )
    if numerics is not None:
        # per-layer numerics (ISSUE 12): keys appear only when a
        # NumericsMonitor is attached; the per_group block and string
        # provenance fields pass through, numbers round like the rest
        for key in NUMERICS_STEP_FIELDS:
            value = numerics.get(key)
            if key == "numerics/per_group":
                # round the inner numbers when the block is well-formed;
                # anything else passes through untouched so the schema
                # validation below rejects it with a ValueError instead
                # of this builder crashing mid-comprehension
                if isinstance(value, dict) and all(
                    isinstance(stats, dict) for stats in value.values()
                ):
                    record[key] = {
                        g: {s: _round(v, 9) for s, v in stats.items()}
                        for g, stats in value.items()
                    }
                else:
                    record[key] = value
            elif key in (
                "numerics/provenance_name",
                "numerics/provenance_field",
                "numerics/quant_err_group",
            ):
                record[key] = value
            elif key in ("numerics/groups", "numerics/provenance_group"):
                record[key] = None if value is None else int(value)
            else:
                record[key] = _round(value, 9)
        unknown = set(numerics) - set(NUMERICS_STEP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown numerics step-event fields {sorted(unknown)}"
            )
    if memory is not None:
        # HBM capacity ledger (ISSUE 19): keys appear only when a
        # MemoryObservatory is attached; slash-named fields arrive as
        # one dict like the fleet view's — unknown keys fail validation
        for key in MEM_STEP_FIELDS:
            value = memory.get(key)
            record[key] = None if value is None else float(value)
        unknown = set(memory) - set(MEM_STEP_FIELDS)
        if unknown:
            raise ValueError(
                f"unknown memory step-event fields {sorted(unknown)}"
            )
    validate_step_event(record)
    return record

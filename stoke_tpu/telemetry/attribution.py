"""Step-time attribution & goodput accounting (ISSUE 4 tentpole).

The telemetry layer (ISSUE 1) answers "how fast is the step" and the
health monitor (ISSUE 3) answers "is the run still healthy"; this module
answers **"where is the time going, and how much of the hardware are we
actually using"** — live, per window, while training:

- :class:`CostCard` / :class:`CostCardCache` — one XLA cost-analysis per
  compiled step program (keyed by the engine's existing program+shape
  signature): analytic FLOPs, bytes accessed, and the roofline-optimal
  step time against a configured peak.  The cards generalize the old
  offline ``Stoke.estimate_step_flops`` probe (now a thin wrapper) and
  feed per-dispatch FLOP/byte counters, so achieved TFLOP/s works across
  all four step paths (apply / fused / window / multi) and any mix of
  them.
- :class:`AttributionMonitor` — per-window gauges derived from the
  registry deltas the telemetry pipeline already collects: achieved
  TFLOP/s, **MFU** against ``AttributionConfig.peak_tflops``, HBM
  bandwidth utilization, and a **bound classification** (compute /
  memory / comm / host) from step wall time + comm bytes-on-wire
  (ISSUE 2) + loader wait (ISSUE 1).
- **Goodput ledger** — buckets total wall clock into productive-compute
  vs compile vs recompile vs loader-stall vs checkpoint-IO vs halt time
  (MLPerf-scale TPU practice, arXiv:1909.09756: utilization and goodput
  are the primary scaling lens).  Emitted per window in the JSONL step
  events and Prometheus, summarized at end of run
  (:meth:`AttributionMonitor.goodput_summary`), and included in
  flight-recorder post-mortem bundles.
- **Anomaly-triggered profiler capture** — when MFU drops below a
  threshold or the step wall time z-score spikes, capture a bounded
  number of xprof trace windows into ``ProfilerConfig.trace_dir`` so the
  device timeline of the bad window is on disk before anyone asks.
  Registered as a health detector (:class:`AutoCaptureDetector`) when a
  ``HealthConfig`` is present, so captures surface in the anomaly stream
  and post-mortem ring.

Everything is host-side bookkeeping over programs the engine compiles
anyway: with ``AttributionConfig`` absent nothing here runs and the
compiled step programs are bit-identical to a build without the feature;
with it enabled the only extra device-adjacent work is one
``cost_analysis`` per program signature (on the already-traced lowering —
no second compile on runtimes that support unoptimized-HLO cost
analysis).
"""

from __future__ import annotations

import threading
import time
import warnings
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from stoke_tpu.telemetry.health import Anomaly, Detector, _RunningStats

#: goodput bucket names, in emission order.  ``productive`` is the
#: remainder after the measured overheads — Google-goodput convention:
#: productive time = total wall clock minus accounted losses.
GOODPUT_BUCKETS: Tuple[str, ...] = (
    "productive", "compile", "recompile", "loader", "checkpoint", "halt",
)

#: bound classifications the per-window attribution can emit
BOUND_CLASSES: Tuple[str, ...] = ("compute", "memory", "comm", "host")

#: backends that reported "no cost analysis" — warn once per backend and
#: remember the negative result so every later probe/estimate call is a
#: silent no-op instead of a fresh lower + warning (ISSUE 4 satellite:
#: estimate_step_flops used to warn on every call)
_COST_UNAVAILABLE_BACKENDS: set = set()
_cost_warn_lock = threading.Lock()


def _cost_dict(obj) -> Optional[Dict[str, float]]:
    """Normalize a jax cost-analysis return (dict, or a 1-list of dicts on
    older versions) to a plain dict, or None when empty."""
    if isinstance(obj, (list, tuple)):
        obj = obj[0] if obj else None
    if not obj:
        return None
    return dict(obj)


def cost_analysis_of(fn, *args, backend: Optional[str] = None):
    """XLA cost analysis of jitted ``fn`` at ``args``: the one shared
    funnel behind CostCards, ``Stoke.estimate_step_flops`` and
    ``scripts/flops_probe.py``.

    Prefers ``Lowered.cost_analysis()`` (no second compile); falls back
    to compiling when the lowering cannot answer.  Returns the raw cost
    dict (``flops`` / ``bytes accessed`` keys) or None when the backend
    reports no cost analysis — in which case it warns ONCE per backend
    and caches the negative result.
    """
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # pragma: no cover - jax-free analysis callers
            backend = "unknown"
    if backend in _COST_UNAVAILABLE_BACKENDS:
        return None
    # tracing errors are USER errors (bad loss structure, shape mismatch)
    # and propagate — only a backend declining to report cost analysis
    # lands in the warn-once negative cache
    lowered = fn.lower(*args)
    cost = None
    try:
        cost = _cost_dict(lowered.cost_analysis())
    except Exception:
        cost = None
    if cost is None:
        # unoptimized-HLO analysis unavailable: pay the compile once.
        # Real compile failures (bad shardings, OOM) raise — same
        # contract the pre-refactor estimate_step_flops documented.
        compiled = lowered.compile()
        try:
            cost = _cost_dict(compiled.cost_analysis())
        except Exception as e:
            _note_cost_unavailable(backend, e)
            return None
    if not cost:
        _note_cost_unavailable(backend, "empty cost analysis")
        return None
    # NOTE: a dict WITHOUT a "flops" key is a program property (XLA omits
    # zero-valued properties, so a zero-FLOP program reports none), not a
    # backend one — return it (callers treat missing flops as 0) instead
    # of blacklisting the whole backend for every later program
    return cost


def memory_analysis_stats(fn, *args) -> Optional[Dict[str, float]]:
    """Component breakdown of jitted ``fn``'s compiled
    ``memory_analysis()`` at ``args``: argument / output / temp /
    generated-code / alias bytes plus the derived ``peak_bytes``
    (argument + output + temp - aliased) — the ISSUE 19 per-program
    memory card.  REQUIRES a compile, so callers pay it only on explicit
    opt-in (``CostCardCache(memory_analysis=True)``); ``None`` whenever
    the backend or jax version cannot answer."""
    try:
        stats = fn.lower(*args).compile().memory_analysis()
        if stats is None:
            return None
        out = {
            "argument_bytes": float(stats.argument_size_in_bytes),
            "output_bytes": float(stats.output_size_in_bytes),
            "temp_bytes": float(stats.temp_size_in_bytes),
            "alias_bytes": float(stats.alias_size_in_bytes),
            "generated_code_bytes": float(
                getattr(stats, "generated_code_size_in_bytes", 0.0)
            ),
        }
        out["peak_bytes"] = (
            out["argument_bytes"]
            + out["output_bytes"]
            + out["temp_bytes"]
            - out["alias_bytes"]
        )
        return out
    except Exception:
        return None


def memory_analysis_bytes(fn, *args) -> Optional[float]:
    """Best-effort peak-HBM estimate of jitted ``fn`` at ``args`` from
    the compiled executable's ``memory_analysis()`` (argument + output +
    temp, minus donated aliases).  Unlike :func:`cost_analysis_of` this
    REQUIRES a compile, so callers pay it only on explicit opt-in (the
    serve roofline observatory's per-program cards); ``None`` whenever
    the backend or jax version cannot answer."""
    stats = memory_analysis_stats(fn, *args)
    if stats is None:
        return None
    total = stats["peak_bytes"]
    return total if total > 0 else None


def _note_cost_unavailable(backend: str, reason) -> None:
    with _cost_warn_lock:
        if backend in _COST_UNAVAILABLE_BACKENDS:
            return
        _COST_UNAVAILABLE_BACKENDS.add(backend)
    warnings.warn(
        f"Stoke -- cost_analysis unavailable on backend {backend!r}: "
        f"{reason!r}; FLOPs/MFU attribution disabled for this backend"
    )


@dataclass
class CostCard:
    """Analytic cost of ONE compiled step program (one dispatch).

    ``steps`` is how many optimizer steps a single dispatch of this
    program advances (n for a ``train_steps`` segment, 1 for apply /
    boundary ``train_step``, 0 for non-boundary micro-steps — their
    FLOPs still count toward achieved-TFLOP/s, they just do not complete
    a step on their own).
    """

    program: str                    # "apply" | "fused" | "accum" | ...
    flops: float                    # per dispatch
    bytes_accessed: Optional[float] # per dispatch (None when unreported)
    steps: int                      # optimizer steps per dispatch
    optimal_time_s: Optional[float] = None  # roofline bound per dispatch
    #: compiled peak-HBM estimate (memory_analysis; None unless a caller
    #: opted into the extra AOT compile — see memory_analysis_bytes)
    peak_hbm_bytes: Optional[float] = None
    #: memory_analysis component breakdown (argument/output/temp/alias/
    #: generated-code/peak bytes; same opt-in — the ISSUE 19 memory
    #: observatory's per-program card)
    mem_stats: Optional[Dict[str, float]] = None

    @property
    def intensity(self) -> Optional[float]:
        """Arithmetic intensity (FLOPs per byte accessed) — the roofline
        x-axis; None when XLA did not report bytes."""
        if not self.bytes_accessed or self.flops <= 0:
            return None
        return self.flops / self.bytes_accessed

    def to_dict(self) -> Dict[str, Any]:
        return {
            "program": self.program,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "steps_per_dispatch": self.steps,
            "optimal_time_s": self.optimal_time_s,
            "intensity": self.intensity,
            "peak_hbm_bytes": self.peak_hbm_bytes,
        }

    @classmethod
    def from_cost(cls, cost: Dict[str, Any], program: str, steps: int,
                  peak_tflops: float = 0.0,
                  peak_hbm_gbps: float = 0.0) -> "CostCard":
        """The one cost-dict → CostCard conversion (XLA omits zero-valued
        properties, so a missing "flops" key means 0) — shared by the
        live cache and ``Stoke.estimate_step_cost`` so the offline
        estimate can never diverge from the live gauges."""
        flops = float(cost.get("flops") or 0.0)
        bytes_acc = cost.get("bytes accessed")
        bytes_acc = float(bytes_acc) if bytes_acc else None
        return cls(
            program,
            flops,
            bytes_acc,
            steps,
            optimal_time_s=roofline_time_s(
                flops, bytes_acc, peak_tflops, peak_hbm_gbps
            ),
        )


def roofline_time_s(
    flops: float,
    bytes_accessed: Optional[float],
    peak_tflops: float,
    peak_hbm_gbps: float = 0.0,
) -> Optional[float]:
    """Roofline-optimal execution time: max of the compute-limited and
    (when a bandwidth peak is configured) the memory-limited bound."""
    if peak_tflops <= 0:
        return None
    t = flops / (peak_tflops * 1e12)
    if bytes_accessed and peak_hbm_gbps > 0:
        t = max(t, bytes_accessed / (peak_hbm_gbps * 1e9))
    return t


def roofline_summary(
    flops: Optional[float], step_seconds: float, peak_tflops: float
) -> Dict[str, Optional[float]]:
    """Achieved TFLOP/s + fraction-of-peak from a per-step FLOPs count
    and a measured step time — the shared arithmetic behind the live MFU
    gauge and ``scripts/flops_probe.py`` (which used to re-derive it
    inline per arm)."""
    if not flops or step_seconds <= 0:
        return {"achieved_tflops": None, "mfu": None}
    achieved = flops / step_seconds / 1e12
    return {
        "achieved_tflops": achieved,
        "mfu": achieved / peak_tflops if peak_tflops > 0 else None,
    }


def classify_bound(
    *,
    wall_s: float,
    compute_optimal_s: Optional[float],
    memory_optimal_s: Optional[float],
    comm_s: Optional[float],
    host_s: float,
    host_fraction: float = 0.5,
    dominant_fraction: float = 0.4,
) -> Optional[str]:
    """Classify one window as compute/memory/comm/host-bound from its
    wall time and the per-resource time estimates (pure function —
    unit-tested on synthetic timings).

    Host time (loader wait + non-overlapped dispatch) wins when it alone
    covers ``host_fraction`` of the wall clock — the device is starving,
    nothing else matters.  Otherwise the resource whose optimal/estimated
    time is largest wins, provided it explains at least
    ``dominant_fraction`` of the wall clock; below that nothing dominates
    and the window is host/overhead-bound by elimination.
    """
    if wall_s <= 0:
        return None
    if host_s / wall_s >= host_fraction:
        return "host"
    candidates = {
        "compute": compute_optimal_s or 0.0,
        "memory": memory_optimal_s or 0.0,
        "comm": comm_s or 0.0,
    }
    bound = max(candidates, key=lambda k: candidates[k])
    if candidates[bound] <= 0 or candidates[bound] / wall_s < dominant_fraction:
        return "host"
    return bound


class CostCardCache:
    """One cost-analysis per (program, shape-signature): the engine calls
    :meth:`note_dispatch` on every compiled-program invocation; the first
    call per key runs the analysis (on the engine's own jitted function
    with the live args) and every call adds the card's analytic FLOPs /
    bytes to the registry counters the per-window attribution deltas.
    """

    #: cap on cached cards, mirroring the engine's _MAX_SHAPE_SIGS bound:
    #: pathological shape churn must not retrace/cost-analyze per new
    #: signature forever nor grow host memory without bound.  Beyond the
    #: cap, unseen signatures reuse the program's most recent card (shape
    #: churn rarely changes per-dispatch cost much) without analysis.
    _MAX_CARDS = 1024

    def __init__(self, registry, peak_tflops: float = 0.0,
                 peak_hbm_gbps: float = 0.0, counter_prefix: str = "attr",
                 memory_analysis: bool = False):
        self.registry = registry
        self.peak_tflops = float(peak_tflops)
        self.peak_hbm_gbps = float(peak_hbm_gbps)
        #: registry namespace for the per-dispatch counters — "attr" for
        #: the training monitor (wire-stable names), "serve/cost" for the
        #: ISSUE 18 serve roofline observatory riding the same machinery
        self.counter_prefix = counter_prefix
        #: opt-in compiled peak-HBM attachment (one extra AOT compile per
        #: distinct program signature — never on by default: training
        #: attribution stays lowering-only)
        self.memory_analysis = bool(memory_analysis)
        self.cards: Dict[Any, CostCard] = {}
        self.cost_analysis_runs = 0  # test hook: one per distinct key
        self._program_fallback: Dict[str, CostCard] = {}
        self._lock = threading.Lock()
        registry.counter(
            f"{counter_prefix}/flops_total",
            help="analytic FLOPs dispatched",
        )
        registry.counter(
            f"{counter_prefix}/bytes_total",
            help="analytic bytes accessed by dispatches",
        )
        registry.counter(
            f"{counter_prefix}/optimal_s_total",
            help="roofline-optimal seconds of dispatched programs",
        )
        registry.counter(
            f"{counter_prefix}/cost_cards_total",
            help="distinct programs analyzed",
        )

    def note_dispatch(self, key, program: str, fn, args: tuple,
                      steps: int) -> Optional[CostCard]:
        """Called by the engine per dispatch.  ``key`` is the engine's
        program cache key + input-shape signature; ``fn`` the jitted
        function about to run; ``args`` its positional arguments."""
        card = self.cards.get(key)
        if card is None:
            if (
                len(self.cards) >= self._MAX_CARDS
                and program in self._program_fallback
            ):
                # bounded under shape churn: no retrace, no new entry —
                # account the program's last known cost instead.  A
                # program kind never analyzed before the cap filled still
                # gets its one analysis (a handful of kinds exist), so
                # its FLOPs are never silently dropped.
                card = self._program_fallback[program]
            else:
                card = self._analyze(key, program, fn, args, steps)
        if card is None:
            return None
        prefix = self.counter_prefix
        self.registry.counter(f"{prefix}/flops_total").inc(card.flops)
        if card.bytes_accessed:
            self.registry.counter(f"{prefix}/bytes_total").inc(
                card.bytes_accessed
            )
        if card.optimal_time_s:
            self.registry.counter(f"{prefix}/optimal_s_total").inc(
                card.optimal_time_s
            )
        return card

    def _analyze(self, key, program, fn, args, steps) -> Optional[CostCard]:
        with self._lock:
            card = self.cards.get(key)
            if card is not None:
                return card
            self.cost_analysis_runs += 1
            try:
                cost = cost_analysis_of(fn, *args)
            except Exception as e:
                # the REAL dispatch of the same program/args is about to
                # run and will surface any genuine error; attribution
                # bookkeeping must never be what kills a training step
                warnings.warn(
                    f"Stoke -- cost analysis of program {program!r} "
                    f"failed: {e!r}; attribution skips it"
                )
                cost = None
            if cost is None:
                # negative result IS the cached result: a backend without
                # cost analysis must not re-lower on every dispatch
                card = CostCard(program, 0.0, None, steps)
                # the zero card is also the program's fallback — without
                # one, the _MAX_CARDS bound would never engage for this
                # program and shape churn would grow the dict forever
                self._program_fallback.setdefault(program, card)
            else:
                card = CostCard.from_cost(
                    cost, program, steps, self.peak_tflops,
                    self.peak_hbm_gbps,
                )
                if self.memory_analysis:
                    card.mem_stats = memory_analysis_stats(fn, *args)
                    if card.mem_stats is not None:
                        peak = card.mem_stats["peak_bytes"]
                        card.peak_hbm_bytes = peak if peak > 0 else None
                self.registry.counter(
                    f"{self.counter_prefix}/cost_cards_total"
                ).inc()
                self._program_fallback[program] = card
            self.cards[key] = card
            return card

    def last_cards(self, n: int = 8) -> List[Dict[str, Any]]:
        """Most recently analyzed cards (insertion-ordered dict), for the
        post-mortem bundle: utilization context at time of death."""
        return [c.to_dict() for c in list(self.cards.values())[-n:] if c.flops]


class AutoCaptureDetector(Detector):
    """Health-registry adapter for the profiler auto-capture (ISSUE 4):
    when the attribution monitor triggered a capture since the last
    health observation, surface it as an anomaly (action from
    ``AttributionConfig.capture_action``) so captures land in the anomaly
    counters, the flight-recorder ring, and post-mortem bundles."""

    name = "attribution_capture"

    def __init__(self, monitor: "AttributionMonitor", action: str = "record"):
        super().__init__(action)
        self.monitor = monitor

    def check(self, step, sentinels, ctx) -> Optional[Anomaly]:
        trigger = self.monitor.consume_trigger()
        if trigger is None:
            return None
        return self._fire(
            step,
            f"profiler auto-capture #{trigger['capture']} triggered "
            f"({trigger['reason']}) -> {trigger['trace_dir']}",
            value=trigger.get("value"),
        )


class AttributionMonitor:
    """Owns the cost-card cache, the per-window gauges, the goodput
    ledger, and the auto-capture state.  The facade constructs one per
    run when an ``AttributionConfig`` is supplied, attaches the cache to
    the engine and itself to the telemetry pipeline; ``record_step``
    calls :meth:`window_stats` with the window wall time and the already-
    collected registry deltas."""

    def __init__(self, cfg, registry, *, compile_tracker=None,
                 trace_dir: Optional[str] = None):
        self.cfg = cfg
        self.registry = registry
        self.compile_tracker = compile_tracker
        self.trace_dir = trace_dir
        self.cost_cards = CostCardCache(
            registry, cfg.peak_tflops, cfg.peak_hbm_gbps
        )
        self._last: Dict[str, float] = {}
        self._goodput_totals: Dict[str, float] = {
            b: 0.0 for b in GOODPUT_BUCKETS
        }
        # compile split (ISSUE 6 satellite): fresh backend compiles vs
        # AOT-cache warm-start loads, summing to the compile+recompile
        # bucket totals
        self._compile_fresh_total = 0.0
        self._compile_cached_total = 0.0
        self._wall_total = 0.0
        # FLOPs covered by RECORDED windows only — the aggregate-MFU
        # numerator.  The raw attr/flops_total counter also carries
        # dispatches after the last record, whose wall time is not in
        # _wall_total; dividing it by recorded wall would inflate MFU.
        self._flops_recorded = 0.0
        self._windows = 0
        self._step_stats = _RunningStats(cfg.ema_alpha)
        # auto-capture state
        self.captures = 0
        self._capturing = False
        self._capture_stop_at: Optional[int] = None
        self._pending_trigger: Optional[Dict[str, Any]] = None
        self._capture_dirs: List[str] = []
        # manual (ops-plane) captures run on scraper threads while the
        # step path runs on_step: the lock orders start/stop transitions
        # and the flag keeps on_step from closing a wall-clock-bounded
        # manual window at its step-count boundary
        self._capture_lock = threading.Lock()
        self._manual_capture = False
        for b in GOODPUT_BUCKETS:
            registry.counter(
                f"goodput/{b}_s_total", help=f"wall seconds: {b}"
            )
        registry.counter(
            "goodput/compile_fresh_s_total",
            help="compile-bucket seconds from fresh XLA backend compiles",
        )
        registry.counter(
            "goodput/compile_cached_s_total",
            help="compile-bucket seconds from AOT-cache warm-start loads",
        )

    # ------------------------------------------------------------------ #
    # per-window attribution
    # ------------------------------------------------------------------ #

    def _delta(self, name: str) -> float:
        inst = self.registry.get(name)
        now = inst.value if inst is not None else 0.0
        prev = self._last.get(name, 0.0)
        self._last[name] = now
        return max(0.0, now - prev)

    def window_stats(
        self,
        *,
        step: int,
        wall_s: Optional[float],
        host_dispatch_s: float,
        loader_wait_s: float,
        ckpt_io_s: float,
        comm_bytes_onwire: Optional[float],
    ) -> Dict[str, Any]:
        """Compute one window's attribution record from the registry
        deltas.  Returns the JSONL-field dict (achieved_tflops / mfu /
        hbm_bw_util / bound / goodput_* — all nullable)."""
        flops = self._delta("attr/flops_total")
        bytes_acc = self._delta("attr/bytes_total")
        # compile split (ISSUE 6 satellite): the compile bucket carries
        # fresh backend-compile seconds (jax/compile_time_s — full XLA
        # codegen; on non-CPU backends a cache-served load also lands
        # here as a small "fresh" duration, a documented imprecision)
        # plus the warm-start overhead cache hits actually paid
        # (compile_cache/hit_s_total: lowering + ledger lookup, measured
        # strictly before dispatch so step execution can never inflate
        # the bucket).  A warm start therefore shows a small cached
        # share where the cold run showed seconds of fresh codegen.
        compile_fresh_dt = self._delta("jax/compile_time_s")
        compile_cached_dt = self._delta("compile_cache/hit_s_total")
        compile_dt = compile_fresh_dt + compile_cached_dt
        recompiles_dt = self._delta("jax/recompiles_total")
        halt_dt = self._delta("health/halt_s")
        out: Dict[str, Any] = {
            "achieved_tflops": None, "mfu": None, "hbm_bw_util": None,
            "bound": None,
            "goodput_compile_fresh_s": None,
            "goodput_compile_cached_s": None,
        }
        for b in GOODPUT_BUCKETS:
            out[f"goodput_{b}_s"] = None
        if wall_s is None or wall_s <= 0:
            return out

        # --- utilization gauges ---
        rl = roofline_summary(flops, wall_s, self.cfg.peak_tflops)
        out["achieved_tflops"] = rl["achieved_tflops"]
        out["mfu"] = rl["mfu"]
        if bytes_acc and self.cfg.peak_hbm_gbps > 0:
            out["hbm_bw_util"] = (
                bytes_acc / wall_s / (self.cfg.peak_hbm_gbps * 1e9)
            )

        # --- bound classification ---
        comm_s = None
        if comm_bytes_onwire and self.cfg.ici_gbps > 0:
            comm_s = comm_bytes_onwire / (self.cfg.ici_gbps * 1e9)
        compute_s = (
            flops / (self.cfg.peak_tflops * 1e12)
            if self.cfg.peak_tflops > 0 else None
        )
        memory_s = (
            bytes_acc / (self.cfg.peak_hbm_gbps * 1e9)
            if bytes_acc and self.cfg.peak_hbm_gbps > 0 else None
        )
        # host leg = loader wait + host dispatch time (classify_bound's
        # documented contract).  NOTE: on synchronous backends (the CPU
        # simulator) the facade phase timers contain the device execution
        # itself, so host_s ~ wall and the classification reads "host" —
        # honest there; on TPU, dispatch is async and host_s only grows
        # when the host genuinely cannot keep the device fed.
        out["bound"] = classify_bound(
            wall_s=wall_s,
            compute_optimal_s=compute_s,
            memory_optimal_s=memory_s,
            comm_s=comm_s,
            host_s=loader_wait_s + host_dispatch_s,
        )

        # --- goodput ledger ---
        overheads = {
            "compile": compile_dt if recompiles_dt == 0 else 0.0,
            "recompile": compile_dt if recompiles_dt > 0 else 0.0,
            "loader": loader_wait_s,
            "checkpoint": ckpt_io_s,
            "halt": halt_dt,
        }
        total_over = sum(overheads.values())
        if total_over > wall_s > 0:
            # concurrent losses (e.g. a compile overlapping a loader
            # stall) cannot exceed the window: scale proportionally so
            # the buckets remain a partition of wall clock
            scale = wall_s / total_over
            overheads = {k: v * scale for k, v in overheads.items()}
            total_over = wall_s
        buckets = {"productive": max(0.0, wall_s - total_over), **overheads}
        for b, v in buckets.items():
            out[f"goodput_{b}_s"] = v
            self._goodput_totals[b] += v
            self.registry.counter(f"goodput/{b}_s_total").inc(v)
        # fresh/cached split of the compile seconds this window accounted
        # (whether they landed in the compile or the recompile bucket):
        # scaled by the same factor the buckets were, so the split sums to
        # the bucketed compile time
        accounted = overheads["compile"] + overheads["recompile"]
        frac = accounted / compile_dt if compile_dt > 0 else 0.0
        fresh = compile_fresh_dt * frac
        cached = compile_cached_dt * frac
        out["goodput_compile_fresh_s"] = fresh
        out["goodput_compile_cached_s"] = cached
        self._compile_fresh_total += fresh
        self._compile_cached_total += cached
        self.registry.counter("goodput/compile_fresh_s_total").inc(fresh)
        self.registry.counter("goodput/compile_cached_s_total").inc(cached)
        self._wall_total += wall_s
        self._flops_recorded += flops
        self._windows += 1
        self.registry.gauge("attr/mfu").set(out["mfu"] or 0.0)
        self.registry.gauge("attr/achieved_tflops").set(
            out["achieved_tflops"] or 0.0
        )

        # --- capture triggers ---
        self._maybe_trigger_capture(step, out["mfu"], wall_s)
        return out

    def goodput_summary(self) -> Dict[str, Any]:
        """End-of-run (or any-time) cumulative goodput accounting:
        seconds and fraction per bucket, plus the utilization aggregate.
        ``Stoke.wall_clock_breakdown`` merges this in as ``goodput/*``
        entries when attribution is on."""
        wall = self._wall_total
        out: Dict[str, Any] = {
            "wall_s": wall,
            "windows": self._windows,
            "goodput_fraction": (
                self._goodput_totals["productive"] / wall if wall > 0 else None
            ),
        }
        for b in GOODPUT_BUCKETS:
            out[f"{b}_s"] = self._goodput_totals[b]
        # compile split + reclaimed seconds (ISSUE 6): cached warm-start
        # loads vs fresh compiles, and the original compile seconds the
        # AOT cache's hits avoided paying at all
        out["compile_fresh_s"] = self._compile_fresh_total
        out["compile_cached_s"] = self._compile_cached_total
        saved = self.registry.get("compile_cache/saved_s_total")
        out["compile_saved_s"] = saved.value if saved is not None else 0.0
        if wall > 0:
            out.update(roofline_summary(
                self._flops_recorded, wall, self.cfg.peak_tflops
            ))
        out["captures"] = self.captures
        out["capture_dirs"] = list(self._capture_dirs)
        return out

    # ------------------------------------------------------------------ #
    # anomaly-triggered profiler capture
    # ------------------------------------------------------------------ #

    def _maybe_trigger_capture(self, step: int, mfu: Optional[float],
                               wall_s: float) -> None:
        cfg = self.cfg
        z = self._step_stats.zscore(wall_s)
        warm = self._step_stats.count >= cfg.capture_warmup_windows
        self._step_stats.update(wall_s)
        if not cfg.auto_capture or self._capturing:
            return
        if self.captures >= cfg.max_captures:
            return
        reason = value = None
        if (
            warm
            and cfg.capture_step_zscore > 0
            and z is not None
            and z > cfg.capture_step_zscore
        ):
            reason, value = f"step-time z={z:.1f}", wall_s
        elif (
            warm
            and cfg.capture_mfu_below > 0
            and mfu is not None
            and mfu < cfg.capture_mfu_below
        ):
            reason, value = f"mfu {mfu:.4f} < {cfg.capture_mfu_below}", mfu
        if reason is None:
            return
        self._start_capture(step, reason, value)

    def _start_capture(self, step: int, reason: str, value) -> None:
        import os

        if self.trace_dir is None:  # status-validated, but stay safe
            return
        safe = "".join(
            c if (c.isalnum() or c in "-_=.") else "-" for c in reason
        )[:48]
        with self._capture_lock:
            if self._capturing:  # a manual capture raced in; defer
                return
            target = os.path.join(
                self.trace_dir,
                f"auto-capture-{self.captures + 1}-step{step}-{safe}",
            )
            try:
                import jax

                jax.profiler.start_trace(target)
            except Exception as e:  # unavailable profiler can't kill a run
                warnings.warn(
                    f"Stoke -- attribution auto-capture failed to start: "
                    f"{e!r}"
                )
                return
            # count only traces that actually started: a failing profiler
            # must neither burn the max_captures budget nor report
            # phantom captures
            self.captures += 1
            self._capturing = True
            self._capture_stop_at = step + max(1, self.cfg.capture_steps)
            self._capture_dirs.append(target)
        self.registry.counter(
            "attr/captures_total", help="anomaly-triggered xprof captures"
        ).inc()
        self._pending_trigger = {
            "capture": self.captures,
            "reason": reason,
            "value": None if value is None else float(value),
            "trace_dir": target,
            "step": step,
        }

    def on_step(self, optimizer_steps: int) -> None:
        """Per-optimizer-step hook (the facade calls this from every step
        boundary): closes an in-flight capture window once it covered
        ``capture_steps`` steps.  A MANUAL capture (ops-plane /profile)
        is wall-clock-bounded by its own thread, never by step count —
        the flag keeps this hook's step boundary from truncating it."""
        with self._capture_lock:
            if self._manual_capture:
                return
            if self._capturing and (
                self._capture_stop_at is None
                or optimizer_steps >= self._capture_stop_at
            ):
                self._stop_capture()

    def manual_capture(
        self, seconds: float, reason: str = "manual"
    ) -> Dict[str, Any]:
        """One bounded on-demand xprof capture (the ops plane's
        ``/profile`` executor, ISSUE 20): starts the profiler, sleeps
        ``seconds`` on the CALLER's thread (the step path keeps running
        — the capture observes it), then stops.  Shares the
        ``max_captures`` budget and the in-flight exclusivity with the
        anomaly-triggered captures, so a scraper can never DoS the run
        with profiler sessions.  Returns ``{"ok": True, "trace_dir",
        "seconds", "captures"}`` or ``{"ok": False, "error"}``."""
        import os

        if self.trace_dir is None:
            return {
                "ok": False,
                "error": "no trace_dir — set ProfilerConfig.trace_dir "
                "to enable on-demand capture",
            }
        safe = "".join(
            c if (c.isalnum() or c in "-_=.") else "-" for c in reason
        )[:48]
        with self._capture_lock:
            if self._capturing:
                return {"ok": False, "error": "capture already in flight"}
            if self.captures >= self.cfg.max_captures:
                return {
                    "ok": False,
                    "error": f"capture budget exhausted "
                    f"({self.captures}/{self.cfg.max_captures})",
                }
            target = os.path.join(
                self.trace_dir,
                f"manual-capture-{self.captures + 1}-{safe}",
            )
            try:
                import jax

                jax.profiler.start_trace(target)
            except Exception as e:
                return {
                    "ok": False,
                    "error": f"profiler failed to start: {e!r}",
                }
            # same budget discipline as _start_capture: only a trace
            # that actually started burns a capture slot
            self.captures += 1
            self._capturing = True
            self._manual_capture = True
            self._capture_stop_at = None
            self._capture_dirs.append(target)
            self.registry.counter(
                "attr/captures_total",
                help="anomaly-triggered xprof captures",
            ).inc()
        time.sleep(max(0.0, float(seconds)))
        with self._capture_lock:
            self._stop_capture()
            self._manual_capture = False
        return {
            "ok": True,
            "trace_dir": target,
            "seconds": float(seconds),
            "captures": self.captures,
            "max_captures": self.cfg.max_captures,
        }

    def _stop_capture(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._capturing = False
        self._capture_stop_at = None

    def consume_trigger(self) -> Optional[Dict[str, Any]]:
        """One-shot read of the latest capture trigger (the health
        detector adapter drains this)."""
        t, self._pending_trigger = self._pending_trigger, None
        return t

    def close(self) -> None:
        with self._capture_lock:
            if self._capturing:
                self._stop_capture()
                self._manual_capture = False

"""Per-layer numerics observatory (ISSUE 12 tentpole): module sentinels,
NaN provenance, and quantization-error attribution.

Every observability layer so far reports whole-model aggregates — one
global grad norm, one nonfinite-leaf count — so when a run diverges or an
int8 path distorts quality the framework can say *that* something broke
but never *where*.  EQuARX (arXiv:2506.17615) shows quantized-collective
error is strongly layer-dependent, and the Gemma-on-TPU comparison
(arXiv:2605.25645) treats per-layer quality attribution as table stakes
for serving quantized checkpoints.  Three signal families, one shared
grouping:

1. **Per-layer gradient/param/update stats** — the grads pytree is
   already layer-structured; :func:`module_groups` prefix-groups the
   flattened leaves by top-level module and :func:`compute_group_stats`
   packs raw sums (grad sum-of-squares / absmax / nonfinite-element
   count, param and update sum-of-squares) into one fixed-layout
   ``[n_groups, n_stats]`` f32 array *inside* the already-compiled step
   program (the PR-3 sentinel discipline: zero extra dispatches; the
   matrix is fetched with the existing sentinel row).  Raw sums — not
   rms — ride the wire so the per-group rows recombine EXACTLY to the
   global grad-norm sentinel (``norm² = Σ_g grad_sumsq_g``), which the
   acceptance test pins against silent leaf drops.
2. **NaN/Inf provenance** — the first offending group index + field is
   derived host-side from the fetched matrix and surfaced through the
   health detector registry (``numerics_provenance``:
   record/warn/dump/halt), the JSONL block, and flight-recorder bundles
   (``numerics.json``).
3. **Quantization-error attribution** — per-layer wire error for the
   PR-8 sharded transport (per-bucket error-feedback residual norms
   mapped back to module groups through the bucket layout) and per-layer
   dequant error for PR-9 ``QuantizedTensor`` serving weights (int8 vs
   source absmax / relative rms, computed once at quantize time).

Everything is default-OFF behind ``NumericsConfig``; without it the
compiled step programs are bit-identical and no ``numerics/*`` field or
gauge exists anywhere.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from stoke_tpu.telemetry.health import Detector, _RunningStats

#: group-stats matrix column layout: stat name -> index.  This is a wire
#: format (the packed [n_groups, n_stats] array the compiled step
#: returns); never reorder, only append.  Raw sums ride the wire — the
#: host derives rms from them (``rms = sqrt(sumsq / n)``) so per-group
#: rows recombine exactly to the global norms.
NUMERICS_STATS = (
    "grad_sumsq",      # Σ g² over the group's gradient elements (f32)
    "grad_absmax",     # max |g| over the group
    "grad_nonfinite",  # count of non-finite gradient ELEMENTS in the group
    "param_sumsq",     # Σ p² over the group's UPDATED parameters
    "update_sumsq",    # Σ (p_new - p_old)² over the group
)
NUMERICS_INDEX = {name: i for i, name in enumerate(NUMERICS_STATS)}
N_NUMERICS_STATS = len(NUMERICS_STATS)

#: per-group stats the JSONL block / gauges / summary expose (host-derived
#: from the wire sums; ``wire_err`` joins when the transport residual is
#: observed, ``quant_err`` when serving weights were quantized)
GROUP_REPORT_FIELDS = (
    "grad_rms", "grad_absmax", "nonfinite", "param_rms", "update_rms",
)

#: warnings the monitor emits itself (no HealthConfig to route through)
#: before degrading to record-only — the fleet-monitor discipline
_MAX_PROVENANCE_WARNINGS = 5

#: provenance events retained for the summary / numerics.json
_RECENT_PROVENANCE_MAX = 64


class ModuleGroup(NamedTuple):
    """One top-level module of the param tree: its name, the indices of
    its leaves in ``jax.tree_util.tree_flatten`` order, and each leaf's
    element count.  The leaf-index list against the FLATTENED tree is the
    contract that keeps the traced packing (:func:`compute_group_stats`)
    and every host-side consumer grouping identically."""

    name: str
    leaf_indices: Tuple[int, ...]
    leaf_elems: Tuple[int, ...]

    @property
    def n_elems(self) -> int:
        return int(sum(self.leaf_elems))


def _key_str(entry) -> str:
    """Render one tree-path entry (DictKey/SequenceKey/GetAttrKey/...) to
    a stable string."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _sanitize(name: str) -> str:
    """Group names become gauge-name segments and JSONL keys — keep them
    to a conservative charset."""
    return "".join(c if (c.isalnum() or c in "_-.") else "_" for c in name)


def module_groups(tree: Any) -> List[ModuleGroup]:
    """Prefix-group a param-shaped pytree's leaves by top-level module.

    The group of a leaf is the FIRST entry of its tree path (flax:
    the top-level module name, e.g. ``layer_0`` / ``conv_init`` /
    ``lm_head``); a bare-leaf tree groups as ``params``.  Groups are
    ordered by first appearance in flatten order, so the resulting
    index ↔ name mapping is deterministic for a given tree structure —
    the wire-format stability the drift-guard tests pin across
    GPT/ResNet/MoE trees.
    """
    import jax

    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    order: List[str] = []
    members: Dict[str, List[int]] = {}
    elems: Dict[str, List[int]] = {}
    for i, (path, leaf) in enumerate(leaves_with_path):
        name = _sanitize(_key_str(path[0])) if path else "params"
        if name not in members:
            order.append(name)
            members[name] = []
            elems[name] = []
        members[name].append(i)
        n = int(np.prod(leaf.shape)) if getattr(leaf, "shape", ()) else 1
        elems[name].append(n)
    return [
        ModuleGroup(name, tuple(members[name]), tuple(elems[name]))
        for name in order
    ]


def leaf_path_names(tree: Any) -> List[str]:
    """``"a/b/c"``-style path string per flattened leaf — the lookup the
    :class:`~stoke_tpu.telemetry.health.NonFiniteDetector` uses to name
    the first offending gradient leaf in its anomaly."""
    import jax

    leaves_with_path = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        "/".join(_key_str(e) for e in path) if path else "params"
        for path, _ in leaves_with_path
    ]


# --------------------------------------------------------------------------- #
# traced packing (called inside the engine's compiled apply)
# --------------------------------------------------------------------------- #


def compute_group_stats(grads: Any, new_params: Any, old_params: Any):
    """Pack the per-group diagnostics matrix — TRACED inside the engine's
    apply core, so every value is a fused reduction in the existing XLA
    program (zero extra dispatches; the tiny ``[n_groups, n_stats]``
    output is fetched alongside the sentinel row).

    ``grads`` are the unscaled post-transport, pre-clip gradients (same
    tap point as the sentinel grad norm, so the recombination identity
    ``grad_norm² == Σ_g grad_sumsq_g`` holds exactly); ``new_params`` /
    ``old_params`` the parameter trees after/before the update.  All
    three share the params treedef, so one :func:`module_groups` plan
    (static, host-side) indexes all of them.
    """
    import jax
    import jax.numpy as jnp

    groups = module_groups(grads)
    g_leaves = jax.tree_util.tree_leaves(grads)
    new_leaves = jax.tree_util.tree_leaves(new_params)
    old_leaves = jax.tree_util.tree_leaves(old_params)

    def _f32(leaf):
        return jnp.asarray(leaf, jnp.float32)

    rows = []
    for group in groups:
        gs = [_f32(g_leaves[i]) for i in group.leaf_indices]
        grad_sumsq = sum(jnp.sum(jnp.square(g)) for g in gs)
        grad_absmax = jnp.max(
            jnp.stack([jnp.max(jnp.abs(g)) for g in gs])
        )
        grad_nonfinite = sum(
            jnp.sum((~jnp.isfinite(g)).astype(jnp.float32)) for g in gs
        )
        param_sumsq = sum(
            jnp.sum(jnp.square(_f32(new_leaves[i])))
            for i in group.leaf_indices
        )
        update_sumsq = sum(
            jnp.sum(jnp.square(_f32(new_leaves[i]) - _f32(old_leaves[i])))
            for i in group.leaf_indices
        )
        rows.append(jnp.stack([
            grad_sumsq, jnp.asarray(grad_absmax, jnp.float32),
            grad_nonfinite, param_sumsq, update_sumsq,
        ]))
    return jnp.stack(rows)


def unpack_group_stats(
    row: np.ndarray, groups: List[ModuleGroup]
) -> Dict[str, Dict[str, float]]:
    """Host-side view of one ``[n_groups, n_stats]`` matrix as
    ``{group_name: {report_field: value}}`` (rms derived from the wire
    sums)."""
    m = np.asarray(row, np.float64).reshape(len(groups), N_NUMERICS_STATS)
    out: Dict[str, Dict[str, float]] = {}
    for g, group in enumerate(groups):
        n = max(group.n_elems, 1)
        out[group.name] = {
            "grad_rms": float(np.sqrt(m[g, NUMERICS_INDEX["grad_sumsq"]] / n)),
            "grad_absmax": float(m[g, NUMERICS_INDEX["grad_absmax"]]),
            "nonfinite": float(m[g, NUMERICS_INDEX["grad_nonfinite"]]),
            "param_rms": float(
                np.sqrt(m[g, NUMERICS_INDEX["param_sumsq"]] / n)
            ),
            "update_rms": float(
                np.sqrt(m[g, NUMERICS_INDEX["update_sumsq"]] / n)
            ),
        }
    return out


def provenance_of(
    row: np.ndarray, groups: List[ModuleGroup]
) -> Optional[Dict[str, Any]]:
    """First offending (group, field) of one stats matrix, or None when
    every value is finite.  Field precedence per group: ``grad`` (any
    non-finite gradient element, or a non-finite grad sum), then
    ``param``, then ``update`` — gradients go bad first in practice, and
    a NaN param implies the grad NaN already fired a step earlier."""
    m = np.asarray(row, np.float64).reshape(len(groups), N_NUMERICS_STATS)
    for g, group in enumerate(groups):
        if (
            m[g, NUMERICS_INDEX["grad_nonfinite"]] > 0
            or not np.isfinite(m[g, NUMERICS_INDEX["grad_sumsq"]])
        ):
            field = "grad"
        elif not np.isfinite(m[g, NUMERICS_INDEX["param_sumsq"]]):
            field = "param"
        elif not np.isfinite(m[g, NUMERICS_INDEX["update_sumsq"]]):
            field = "update"
        else:
            continue
        return {
            "group": g,
            "name": group.name,
            "field": field,
            "nonfinite_elems": float(
                m[g, NUMERICS_INDEX["grad_nonfinite"]]
            ),
        }
    return None


# --------------------------------------------------------------------------- #
# quantization-error attribution (wire + serving weights)
# --------------------------------------------------------------------------- #


def wire_residual_group_norms(
    transport: Any, comm_state: Optional[Dict[str, Any]], params: Any,
    groups: Optional[List[ModuleGroup]] = None,
) -> Optional[Dict[str, float]]:
    """Per-module-group norm of the error-feedback residual — the
    "quantization error currently being carried per layer" view of the
    PR-2/PR-8 transports.

    Replicated transport: the residual is a per-leaf pytree, so the
    grouping is exact (``group_norm² = Σ leaf_norm²``).  Sharded
    transport (PR 8): the residual is one flat buffer per BUCKET; each
    bucket's norm² is attributed to groups proportionally to the element
    share its member leaves contribute (buckets hold whole leaves, so
    the only approximation is within-bucket mixing).  Returns None when
    no residual is carried (no transport / no error feedback) — and on
    multi-host meshes where the sharded residual's non-addressable
    shards cannot be fetched, callers should catch and skip.
    """
    import jax

    residual = (comm_state or {}).get("residual")
    if residual is None:
        return None
    if groups is None:
        groups = module_groups(params)
    group_sq = {g.name: 0.0 for g in groups}
    if isinstance(residual, tuple):
        # sharded path: per-bucket flat buffers, mapped through the layout
        norms = [
            float(n)
            for n in jax.device_get(
                [jax.numpy.linalg.norm(r.astype(jax.numpy.float32))
                 for r in residual]
            )
        ]
        bucket_members = transport.bucket_leaf_elems(params)
        leaf_group = {}
        for g in groups:
            for i in g.leaf_indices:
                leaf_group[i] = g.name
        for b, members in enumerate(bucket_members):
            if b >= len(norms):
                break
            total = float(sum(n for _, n in members)) or 1.0
            for leaf_idx, n_elems in members:
                group_sq[leaf_group[leaf_idx]] += (
                    norms[b] ** 2 * (n_elems / total)
                )
    else:
        # replicated path: per-leaf residual pytree — exact grouping
        leaves = jax.tree_util.tree_leaves(residual)
        leaf_sq = [
            float(v) ** 2
            for v in jax.device_get(
                [jax.numpy.linalg.norm(l.astype(jax.numpy.float32))
                 for l in leaves]
            )
        ]
        for g in groups:
            for i in g.leaf_indices:
                if i < len(leaf_sq):
                    group_sq[g.name] += leaf_sq[i]
    return {name: float(np.sqrt(sq)) for name, sq in group_sq.items()}


def quant_error_by_group(
    errors_by_path: Dict[str, Dict[str, float]],
    groups: List[ModuleGroup],
    paths: List[str],
) -> Dict[str, Dict[str, float]]:
    """Fold per-leaf dequant errors (``serving.quant.quantization_error``)
    into per-module-group worst-case numbers: max relative rms and max
    absolute error over the group's quantized leaves.  Groups with no
    quantized leaf are omitted (nothing to attribute)."""
    path_group: Dict[str, str] = {}
    for g in groups:
        for i in g.leaf_indices:
            if i < len(paths):
                path_group[paths[i]] = g.name
    out: Dict[str, Dict[str, float]] = {}
    for path, err in errors_by_path.items():
        name = path_group.get(path)
        if name is None:
            # a path outside the grouping plan (shouldn't happen; be loud
            # in the value rather than dropping the error silently)
            name = path.split("/", 1)[0]
        slot = out.setdefault(
            name, {"rel_rms": 0.0, "abs_err_max": 0.0, "leaves": 0}
        )
        slot["rel_rms"] = max(slot["rel_rms"], float(err["rel_rms"]))
        slot["abs_err_max"] = max(
            slot["abs_err_max"], float(err["abs_err_max"])
        )
        slot["leaves"] += 1
    return out


def max_quant_error(
    by_group: Dict[str, Dict[str, float]],
) -> Tuple[Optional[str], Optional[float]]:
    """``(group_name, rel_rms)`` of the worst-quantized module — the
    layer that bounds int8 quality (the ``quant_err_layer`` /
    ``quant_err_max`` bench columns)."""
    if not by_group:
        return None, None
    name = max(by_group, key=lambda k: by_group[k]["rel_rms"])
    return name, by_group[name]["rel_rms"]


# --------------------------------------------------------------------------- #
# the monitor
# --------------------------------------------------------------------------- #


class NumericsMonitor:
    """Owns the host side of the observatory: unpacks fetched group-stats
    matrices, derives provenance, publishes ``numerics/*`` gauges,
    assembles the per-group JSONL block, and ranks groups for the
    end-of-run summary.

    The facade constructs one per run when a ``NumericsConfig`` is
    supplied, feeds it every fetched matrix window
    (:meth:`observe_window`), and attaches it to the telemetry pipeline
    (``Telemetry.numerics``) so ``record_step`` pulls
    :meth:`event_fields` at the logging cadence.  NaN provenance reaches
    the health anomaly pipeline through
    :class:`NumericsProvenanceDetector` when a ``HealthConfig`` is
    present; otherwise the monitor warns (bounded) itself.
    """

    def __init__(
        self,
        cfg,
        registry,
        groups: List[ModuleGroup],
        *,
        leaf_paths: Optional[List[str]] = None,
        rank: int = 0,
    ):
        self.cfg = cfg
        self.registry = registry
        self.groups = list(groups)
        self.leaf_paths = list(leaf_paths or [])
        self.rank = int(rank)
        self.windows = 0
        self.last_step: Optional[int] = None
        self.last_per_group: Optional[Dict[str, Dict[str, float]]] = None
        self.last_provenance: Optional[Dict[str, Any]] = None
        self.wire_err: Optional[Dict[str, float]] = None
        self.quant_err: Optional[Dict[str, Dict[str, float]]] = None
        # FIFO of provenance events awaiting the health pipeline: a
        # train_steps window can surface SEVERAL events (one per bad
        # step), and the facade runs one health observation per covered
        # step — each drains one event, so none is lost or re-stamped
        self._pending_provenance: List[Dict[str, Any]] = []
        self._provenance_events: List[Dict[str, Any]] = []
        self._warnings = 0
        # grad-noise ranking state: running mean/variance of each group's
        # grad rms (EW stats — the health z-score machinery reused); the
        # summary ranks groups by the coefficient of variation std/mean,
        # the "which layer's gradients are the noisiest" lens
        self._grad_stats: Dict[str, _RunningStats] = {
            g.name: _RunningStats(alpha=0.1) for g in self.groups
        }
        registry.counter(
            "numerics/windows_total",
            help="group-stats matrices observed",
        )
        registry.counter(
            "numerics/provenance_total",
            help="non-finite per-layer provenance events",
        )

    # ------------------------------ observe ---------------------------- #

    def observe_window(self, first_step: int, rows: np.ndarray) -> None:
        """Consume the fetched group-stats matrices of one dispatch
        (``rows`` is ``[window, n_groups, n_stats]``; a single step passes
        window=1).  Derives provenance per row (so a NaN mid-segment is
        attributed to its own step), updates the noise stats, and caches
        the latest per-group view for gauges/JSONL/summary."""
        rows = np.asarray(rows, np.float64)
        if rows.ndim == 2:
            rows = rows[None]
        for i in range(rows.shape[0]):
            step = int(first_step + i)
            self.windows += 1
            self.registry.counter("numerics/windows_total").inc()
            prov = provenance_of(rows[i], self.groups)
            if prov is not None:
                prov = {**prov, "step": step}
                self.registry.counter("numerics/provenance_total").inc()
                self._provenance_events.append(prov)
                del self._provenance_events[:-_RECENT_PROVENANCE_MAX]
                self.last_provenance = prov
                self._pending_provenance.append(prov)
                del self._pending_provenance[:-_RECENT_PROVENANCE_MAX]
                self._self_apply(prov)
            per_group = unpack_group_stats(rows[i], self.groups)
            for name, stats in per_group.items():
                rms = stats["grad_rms"]
                if np.isfinite(rms):
                    self._grad_stats[name].update(rms)
            self.last_step = step
            self.last_per_group = per_group
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        if self.last_per_group is None:
            return
        g = self.registry.gauge
        for name, stats in self.last_per_group.items():
            g(f"numerics/{name}/grad_rms").set(stats["grad_rms"])
            g(f"numerics/{name}/update_rms").set(stats["update_rms"])
            g(f"numerics/{name}/nonfinite").set(stats["nonfinite"])
        if self.wire_err is not None:
            for name, v in self.wire_err.items():
                g(f"numerics/{name}/wire_residual_norm").set(v)
        prov = self.last_provenance
        g("numerics/provenance_group").set(
            float(prov["group"]) if prov is not None else -1.0
        )

    def _self_apply(self, prov: Dict[str, Any]) -> None:
        """Warn-path fallback when no health registry will consume the
        pending provenance (the facade drains it through
        :class:`NumericsProvenanceDetector` when a ``HealthConfig`` is
        present)."""
        if self.cfg.provenance_action == "record":
            return
        if self._warnings >= _MAX_PROVENANCE_WARNINGS:
            return
        self._warnings += 1
        warnings.warn(f"Stoke -- numerics: {describe_provenance(prov)}")

    def consume_provenance(self) -> Optional[Dict[str, Any]]:
        """Pop the OLDEST pending provenance event (the detector adapter
        drains this into the health anomaly pipeline — FIFO, one per
        health observation, so a multi-step window's events each fire
        with their own step)."""
        if not self._pending_provenance:
            return None
        return self._pending_provenance.pop(0)

    # -------------------- quantization-error inputs -------------------- #

    def observe_wire(
        self, group_norms: Optional[Dict[str, float]]
    ) -> None:
        """Install the latest per-group wire (error-feedback residual)
        norms — computed by the facade at the logging cadence via
        :func:`wire_residual_group_norms`."""
        if group_norms is None:
            return
        self.wire_err = dict(group_norms)

    def set_quant_errors(
        self, by_group: Dict[str, Dict[str, float]]
    ) -> None:
        """Install per-group serving-weight dequant errors (computed once
        at quantize time — :func:`quant_error_by_group`) and publish the
        matching gauges."""
        self.quant_err = dict(by_group)
        g = self.registry.gauge
        for name, err in by_group.items():
            g(f"numerics/{name}/quant_err_rel_rms").set(err["rel_rms"])

    # ------------------------------ outputs ----------------------------- #

    def event_fields(self) -> Dict[str, Any]:
        """The ``numerics/*`` JSONL step-event block (keys present only
        when a monitor is attached; the per-group block is nullable and
        omitted between observations or when ``per_group_jsonl`` is
        off)."""
        per_group = None
        if self.cfg.per_group_jsonl:
            # the block merges whatever signal families have data — a
            # grad_stats=False (wire/quant-only) config still emits it,
            # so numerics_diff.py --stat wire_err can align such runs
            per_group = {
                name: dict(stats)
                for name, stats in (self.last_per_group or {}).items()
            }
            if self.wire_err is not None:
                for name, v in self.wire_err.items():
                    per_group.setdefault(name, {})["wire_err"] = v
            if self.quant_err is not None:
                for name, err in self.quant_err.items():
                    per_group.setdefault(name, {})["quant_err"] = (
                        err["rel_rms"]
                    )
            per_group = per_group or None
        prov = self.last_provenance
        q_layer, q_max = (
            max_quant_error(self.quant_err)
            if self.quant_err is not None
            else (None, None)
        )
        return {
            "numerics/groups": len(self.groups),
            "numerics/per_group": per_group,
            "numerics/provenance_group": (
                None if prov is None else prov["group"]
            ),
            "numerics/provenance_name": (
                None if prov is None else prov["name"]
            ),
            "numerics/provenance_field": (
                None if prov is None else prov["field"]
            ),
            "numerics/quant_err_max": q_max,
            "numerics/quant_err_group": q_layer,
        }

    def grad_noise(self) -> Dict[str, float]:
        """Per-group gradient-noise score: the running coefficient of
        variation (std/mean) of the group's grad rms — scale-free, so a
        tiny layernorm and a huge matmul rank comparably."""
        out = {}
        for name, stats in self._grad_stats.items():
            if stats.mean is None or stats.mean <= 0:
                out[name] = 0.0
            else:
                out[name] = float((stats.var ** 0.5) / stats.mean)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Bundle payload (``numerics.json``): the latest per-group view,
        provenance history, and quantization-error attribution — "which
        layer was bad at time of death"."""
        return {
            "rank": self.rank,
            "step": self.last_step,
            "windows": self.windows,
            "groups": [g.name for g in self.groups],
            "group_elems": {g.name: g.n_elems for g in self.groups},
            "per_group": self.last_per_group,
            "grad_noise": self.grad_noise(),
            "wire_err": self.wire_err,
            "quant_err": self.quant_err,
            "provenance": self.last_provenance,
            "provenance_events": list(self._provenance_events),
        }

    def summary(self) -> Dict[str, Any]:
        """End-of-run ranking (the ``Stoke.numerics_summary`` surface):
        groups ordered by grad-noise and by quant error, plus the latest
        per-group stats and every provenance event."""
        noise = self.grad_noise()
        top_k = max(int(self.cfg.top_k), 1)
        by_noise = sorted(
            noise.items(), key=lambda kv: kv[1], reverse=True
        )[:top_k]
        by_quant: List[Tuple[str, float]] = []
        if self.quant_err:
            by_quant = sorted(
                ((n, e["rel_rms"]) for n, e in self.quant_err.items()),
                key=lambda kv: kv[1], reverse=True,
            )[:top_k]
        by_wire: List[Tuple[str, float]] = []
        if self.wire_err:
            by_wire = sorted(
                self.wire_err.items(), key=lambda kv: kv[1], reverse=True
            )[:top_k]
        out = self.snapshot()
        out["top_grad_noise"] = [
            {"group": n, "noise": v} for n, v in by_noise
        ]
        out["top_quant_err"] = [
            {"group": n, "rel_rms": v} for n, v in by_quant
        ]
        out["top_wire_err"] = [
            {"group": n, "residual_norm": v} for n, v in by_wire
        ]
        out["provenance_total"] = int(
            self.registry.counter("numerics/provenance_total").value
        )
        return out


def describe_provenance(prov: Dict[str, Any]) -> str:
    n = prov.get("nonfinite_elems") or 0
    detail = (
        f" ({int(n)} non-finite gradient elements)" if n else ""
    )
    return (
        f"non-finite {prov['field']} values first appear in module group "
        f"{prov['name']!r} (index {prov['group']}) at step "
        f"{prov.get('step', '?')}{detail}"
    )


class NumericsProvenanceDetector(Detector):
    """Health-registry adapter (PR 3 registry contract): when the
    numerics monitor derived a fresh non-finite provenance since the last
    health observation, surface it as a ``numerics_provenance`` anomaly
    (action from ``NumericsConfig.provenance_action``) so the culprit
    layer lands in the anomaly counters, the flight-recorder ring, and
    post-mortem bundles — and a ``halt`` action stops the run AT the
    facade boundary with the layer named."""

    name = "numerics_provenance"

    def __init__(self, monitor: NumericsMonitor, action: str = "warn"):
        super().__init__(action)
        self.monitor = monitor
        # the monitor's own warn fallback would double-report next to the
        # health pipeline's warning
        monitor._warnings = _MAX_PROVENANCE_WARNINGS

    def check(self, step, sentinels, ctx):
        event = self.monitor.consume_provenance()
        if event is None:
            return None
        # stamp the anomaly with the EVENT's step, not the observation's:
        # a train_steps window drains its events across the per-step
        # health observations, and the ring/bundle must key each firing
        # to the step the NaN actually appeared at
        anomaly = self._fire(
            int(event.get("step", step)),
            f"numerics provenance: {describe_provenance(event)}",
            value=float(event["group"]),
        )
        anomaly.context = dict(event)
        return anomaly

"""Unified telemetry subsystem (ISSUE 1 tentpole).

One pipeline replaces the facade's disconnected one-off probes
(``profile_trace`` / ``estimate_step_flops`` / the wall-clock dict):

    registry (counters/gauges/histograms)
        <- facade phase timers, data-loader wait/starvation, compile
           tracking, HBM watermarks, user scalars
    -> sinks at the logging cadence:
         JSONL structured step events (events.py schema, one line/window)
         Prometheus text exposition (atomic scrape file)
         native TensorBoard writer (utils/tb_writer.py format)

Enable by passing ``TelemetryConfig`` to ``Stoke(configs=[...])``; the
:class:`Telemetry` object is also usable standalone (scripts, tests):

    from stoke_tpu.telemetry import Telemetry
    from stoke_tpu import TelemetryConfig

    t = Telemetry(TelemetryConfig(output_dir="/tmp/run1"), rank=0)
    with t.phase("step"):
        ...
    t.record_step(step=1, window_steps=1, ema_loss=2.3)

See docs/observability.md for the full tour.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from stoke_tpu.telemetry.collectors import (
    CompileTracker,
    hbm_stats,
    set_xprof_enabled,
    update_hbm_gauges,
    xprof_span,
)
from stoke_tpu.telemetry.events import (
    STEP_EVENT_SCHEMA,
    build_step_event,
    read_step_events,
    validate_step_event,
)
from stoke_tpu.telemetry.health import (
    SENTINEL_FIELDS,
    WATCHDOG_EXIT_CODE,
    Anomaly,
    HangWatchdog,
    HealthHaltError,
    HealthMonitor,
    compute_sentinels,
    unpack_sentinels,
)
from stoke_tpu.telemetry.attribution import (
    BOUND_CLASSES,
    GOODPUT_BUCKETS,
    AttributionMonitor,
    AutoCaptureDetector,
    CostCard,
    CostCardCache,
    classify_bound,
    cost_analysis_of,
    roofline_summary,
    roofline_time_s,
)
from stoke_tpu.telemetry.fleet import (
    FLEET_EVENT_FIELDS,
    FLEET_SIGNALS,
    FleetMonitor,
    FleetStragglerDetector,
    fleet_aggregates,
    observe_sync_wait,
    pack_fleet_vector,
    register_sync_registry,
    straggler_verdict,
    timed_sync,
    unpack_fleet_vector,
    unregister_sync_registry,
)
from stoke_tpu.telemetry.numerics import (
    GROUP_REPORT_FIELDS,
    N_NUMERICS_STATS,
    NUMERICS_STATS,
    ModuleGroup,
    NumericsMonitor,
    NumericsProvenanceDetector,
    compute_group_stats,
    leaf_path_names,
    max_quant_error,
    module_groups,
    provenance_of,
    quant_error_by_group,
    unpack_group_stats,
    wire_residual_group_norms,
)
from stoke_tpu.telemetry.memory import (
    MEM_FIELDS,
    MemoryObservatory,
    transport_resident_bytes,
    tree_resident_bytes,
)
from stoke_tpu.telemetry.recorder import FlightRecorder
from stoke_tpu.telemetry.tracing import (
    TRACE_EVENT_KEYS,
    ComposedContext,
    Span,
    TraceRecorder,
    register_recorder,
    trace_add,
    trace_point,
    trace_span,
    tracing_active,
    unregister_recorder,
)
from stoke_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from stoke_tpu.telemetry.sinks import (
    JsonlSink,
    PrometheusSink,
    Sink,
    TensorBoardSink,
    render_prometheus,
)

__all__ = [
    "Telemetry",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "Sink",
    "JsonlSink",
    "PrometheusSink",
    "TensorBoardSink",
    "render_prometheus",
    "CompileTracker",
    "hbm_stats",
    "update_hbm_gauges",
    "xprof_span",
    "set_xprof_enabled",
    "STEP_EVENT_SCHEMA",
    "build_step_event",
    "validate_step_event",
    "read_step_events",
    # health monitor (ISSUE 3)
    "SENTINEL_FIELDS",
    "WATCHDOG_EXIT_CODE",
    "Anomaly",
    "HangWatchdog",
    "HealthHaltError",
    "HealthMonitor",
    "FlightRecorder",
    "compute_sentinels",
    "unpack_sentinels",
    # step-time attribution & goodput (ISSUE 4)
    "AttributionMonitor",
    "AutoCaptureDetector",
    "CostCard",
    "CostCardCache",
    "BOUND_CLASSES",
    "GOODPUT_BUCKETS",
    "classify_bound",
    "cost_analysis_of",
    "roofline_summary",
    "roofline_time_s",
    # fleet observability (ISSUE 5)
    "FLEET_SIGNALS",
    "FLEET_EVENT_FIELDS",
    "FleetMonitor",
    "FleetStragglerDetector",
    "fleet_aggregates",
    "straggler_verdict",
    "pack_fleet_vector",
    "unpack_fleet_vector",
    "register_sync_registry",
    "unregister_sync_registry",
    "observe_sync_wait",
    "timed_sync",
    # per-layer numerics observatory (ISSUE 12)
    "NUMERICS_STATS",
    "N_NUMERICS_STATS",
    "GROUP_REPORT_FIELDS",
    "ModuleGroup",
    "NumericsMonitor",
    "NumericsProvenanceDetector",
    "compute_group_stats",
    "leaf_path_names",
    "max_quant_error",
    "module_groups",
    "provenance_of",
    "quant_error_by_group",
    "unpack_group_stats",
    "wire_residual_group_norms",
    # HBM capacity observatory (ISSUE 19)
    "MEM_FIELDS",
    "MemoryObservatory",
    "transport_resident_bytes",
    "tree_resident_bytes",
    # structured tracing (ISSUE 10)
    "TRACE_EVENT_KEYS",
    "ComposedContext",
    "Span",
    "TraceRecorder",
    "register_recorder",
    "unregister_recorder",
    "trace_span",
    "trace_point",
    "trace_add",
    "tracing_active",
]


class Telemetry:
    """Orchestrator: owns the registry, collectors, and sinks.

    Constructed with ``config=None`` it is a *disabled* pipeline: the
    registry still works (the facade's wall-clock breakdown and xprof spans
    ride on it unconditionally) but no collectors attach and ``record_step``
    is a no-op — zero IO, zero listeners, zero device touches.

    Multi-host: sinks default to rank 0 only; ``jsonl_all_ranks=True`` adds
    a per-rank JSONL stream (``steps.rank<N>.jsonl``).
    """

    def __init__(
        self,
        config=None,
        rank: int = 0,
        extra_sinks: Optional[List[Sink]] = None,
    ):
        self.config = config
        self.rank = int(rank)
        self.registry = MetricsRegistry()
        self.sinks: List[Sink] = list(extra_sinks or [])
        self.compile_tracker: Optional[CompileTracker] = None
        # step-time attribution monitor (ISSUE 4) — assigned by the
        # facade when an AttributionConfig is supplied; None keeps
        # record_step free of MFU/goodput computation entirely
        self.attribution = None
        # fleet-view monitor (ISSUE 5) — assigned by the facade when a
        # FleetConfig is supplied; None keeps record_step free of any
        # cross-host exchange entirely
        self.fleet = None
        # resilience monitor (ISSUE 7) — assigned by the facade when a
        # ResilienceConfig is supplied; None keeps the resilience/* keys
        # out of every step event entirely
        self.resilience = None
        # per-layer numerics monitor (ISSUE 12) — assigned by the facade
        # when a NumericsConfig is supplied; None keeps the numerics/*
        # keys out of every step event entirely
        self.numerics = None
        # HBM capacity observatory (ISSUE 19) — assigned by the facade
        # when a MemoryConfig is supplied; None keeps the mem/* keys out
        # of every step event entirely
        self.memory = None
        # cross-process sync timings (Stoke.barrier / checkpoint
        # sync_global_devices) land in this registry even when no
        # TelemetryConfig drives sinks — the wall-clock breakdown and
        # the fleet barrier-wait attribution both read them
        register_sync_registry(self.registry)
        self._last_record: Dict[str, float] = {}
        # seeded now so the FIRST record's rates cover init->record wall
        # time (includes warm-up compiles — honest, if conservative)
        self._last_record_ts: Optional[float] = time.time()
        self._last_loss_scale = None
        self._closed = False
        if config is None:
            return
        import os

        # xprof annotation gating is process-global; only ever *disable*
        # from a config (never re-enable) so a later default-config
        # instance cannot clobber an earlier instance's explicit opt-out.
        # Re-enable explicitly via set_xprof_enabled(True) if needed.
        if not config.xprof_annotations:
            set_xprof_enabled(False)
        if config.track_compiles:
            self.compile_tracker = CompileTracker(self.registry)
        is_rank0 = self.rank == 0
        out = config.output_dir
        if config.jsonl and (is_rank0 or config.jsonl_all_ranks):
            name = (
                "steps.jsonl"
                if is_rank0 and not config.jsonl_all_ranks
                else f"steps.rank{self.rank}.jsonl"
            )
            self.sinks.append(JsonlSink(os.path.join(out, name)))
        if config.prometheus and (is_rank0 or config.prometheus_all_ranks):
            from stoke_tpu.telemetry.sinks import host_labels

            prom_name = (
                "metrics.prom"
                if is_rank0 and not config.prometheus_all_ranks
                else f"metrics.rank{self.rank}.prom"
            )
            self.sinks.append(
                PrometheusSink(
                    os.path.join(out, prom_name),
                    # host/process_index labels (ISSUE 5 satellite): a
                    # multi-host job's per-host expositions scraped into
                    # one Prometheus must not collide into one series
                    labels={
                        "rank": str(self.rank),
                        "run": config.run_name,
                        **host_labels(self.rank),
                    },
                )
            )
        if config.tensorboard and is_rank0:
            self.sinks.append(TensorBoardSink(os.path.join(out, "tb")))

    # ------------------------------------------------------------------ #
    # emit surface (facade / data / user)
    # ------------------------------------------------------------------ #

    @property
    def enabled(self) -> bool:
        """True when a ``TelemetryConfig`` drives sinks (the registry works
        regardless)."""
        return self.config is not None

    def phase(self, name: str, annotate: bool = True):
        """Timer for a facade/engine phase: seconds accumulate into
        ``facade/<name>_s`` (the wall-clock breakdown), the span is
        labeled in xprof timelines, AND — with a trace recorder
        registered (ISSUE 10) — the same section lands in the host span
        ring, so every timed section is also a trace span (one composed
        helper instead of the hand-rolled span+timer pairing)."""
        timer = self.registry.timer(f"facade/{name}_s")
        if not annotate:
            return timer
        return trace_span(f"stoke/{name}", track="facade", timer=timer)

    def log_scalar(self, tag: str, value: float) -> None:
        """User scalar -> gauge ``user/<tag>`` (mirrored to sinks at the
        next cadence; the facade additionally writes it to its TB stream
        immediately for parity with the legacy ``log_scalar``)."""
        self.registry.gauge(f"user/{tag}").set(float(value))

    def add_samples(self, n: int) -> None:
        self.registry.counter("data/samples_total").inc(n)

    def add_tokens(self, n: int) -> None:
        self.registry.counter("data/tokens_total").inc(n)

    def observe_device_step(self, seconds: float) -> None:
        """Record one sampled device-step time (block_until_ready bracketed
        dispatch, see facade)."""
        self.registry.histogram("device/step_s").observe(seconds)

    def will_sample_device(self) -> bool:
        return self.enabled and self.config.sample_device_time

    def wall_clock_breakdown(self) -> Dict[str, float]:
        """``{phase: cumulative host seconds}`` from the registry-backed
        facade timers (the legacy ``Stoke.wall_clock_breakdown`` surface).
        With attribution on (ISSUE 4), the cumulative goodput buckets are
        merged in as ``goodput/<bucket>`` entries — one call answers both
        "where did host dispatch go" and "where did wall clock go"."""
        out = {}
        for name in self.registry.names():
            if name.startswith("facade/") and name.endswith("_s"):
                out[name[len("facade/"):-2]] = self.registry.get(name).value
        # cross-process sync time (ISSUE 5 satellite): barrier waits are
        # host wall clock like the facade phases, and invisible anywhere
        # else a wall-clock reader looks — surface once any accrued
        sync = self.registry.get("sync/barrier_wait_s")
        if sync is not None and sync.value > 0:
            out["sync/barrier_wait"] = sync.value
        if self.attribution is not None:
            summary = self.attribution.goodput_summary()
            for b in GOODPUT_BUCKETS:
                out[f"goodput/{b}"] = summary[f"{b}_s"]
        return out

    def goodput_summary(self) -> Optional[dict]:
        """End-of-run goodput accounting (cumulative bucket seconds,
        goodput fraction, aggregate achieved TFLOP/s + MFU, capture
        paths); None without an ``AttributionConfig``."""
        if self.attribution is None:
            return None
        return self.attribution.goodput_summary()

    def fleet_summary(self) -> Optional[dict]:
        """End-of-run fleet accounting (windows, latest per-host matrix +
        aggregates + straggler verdict, straggler counts); None without a
        ``FleetConfig``."""
        if self.fleet is None:
            return None
        return self.fleet.summary()

    # ------------------------------------------------------------------ #
    # step records
    # ------------------------------------------------------------------ #

    def _counter_value(self, name: str) -> float:
        inst = self.registry.get(name)
        return inst.value if inst is not None else 0.0

    def _counter_or_none(self, name: str) -> Optional[float]:
        """Counter value, or None when nothing ever registered it (the
        "feature absent -> field null" contract)."""
        inst = self.registry.get(name)
        return inst.value if inst is not None else None

    def _delta(self, name: str) -> float:
        """Per-window delta of a cumulative counter (vs the last record)."""
        now = self._counter_value(name)
        prev = self._last_record.get(name, 0.0)
        self._last_record[name] = now
        return max(0.0, now - prev)

    def note_loss_scale(self, scale) -> int:
        """Track dynamic-loss-scale transitions; returns the cumulative
        transition (backoff+growth) count."""
        events = self.registry.counter("precision/loss_scale_events_total")
        if scale is not None and self._last_loss_scale is not None:
            prev, cur = self._last_loss_scale, scale
            prev_l = prev if isinstance(prev, list) else [prev]
            cur_l = cur if isinstance(cur, list) else [cur]
            changed = len(prev_l) != len(cur_l) or any(
                a != b for a, b in zip(prev_l, cur_l)
            )
            if changed:
                events.inc()
        if scale is not None:
            self._last_loss_scale = scale
        return int(events.value)

    def record_step(
        self,
        step: int,
        window_steps: int = 1,
        *,
        ema_loss: Optional[float] = None,
        step_loss: Optional[float] = None,
        grad_norm: Optional[float] = None,
        loss_scale=None,
        skipped_steps: float = 0.0,
        comm_residual_norm: Optional[float] = None,
        param_norm: Optional[float] = None,
        update_ratio: Optional[float] = None,
        nonfinite_leaves: Optional[float] = None,
        health_anomalies: Optional[float] = None,
        tokens_hint: Optional[float] = None,
        ts: Optional[float] = None,
        serve: Optional[Dict[str, Any]] = None,
        memory=None,
    ) -> Optional[dict]:
        """Assemble one structured step event from the registry state and
        fan it to every sink.  Called by the facade at the logging cadence;
        safe to call directly from scripts.  Returns the record (None when
        telemetry is disabled)."""
        if not self.enabled or self._closed:
            return None
        now = time.time() if ts is None else ts
        wall_dt = (
            None
            if self._last_record_ts is None
            else max(now - self._last_record_ts, 1e-9)
        )
        self._last_record_ts = now

        if self.config.track_hbm:
            update_hbm_gauges(self.registry)

        # host dispatch seconds this window: sum of facade phase deltas
        # (checkpoint IO tracked separately — it feeds the goodput ledger)
        host_dispatch = 0.0
        ckpt_io = 0.0
        for name in self.registry.names():
            if name.startswith("facade/") and name.endswith("_s"):
                d = self._delta(name)
                host_dispatch += d
                if name in ("facade/save_s", "facade/load_s"):
                    ckpt_io += d
        loader_wait = self._delta("data/loader_wait_s")
        samples_delta = self._delta("data/samples_total")
        tokens_delta = self._delta("data/tokens_total")
        samples_total = self._counter_value("data/samples_total")

        samples_per_s = (
            samples_delta / wall_dt if wall_dt and samples_delta else None
        )
        tokens = tokens_delta if tokens_delta else (tokens_hint or 0.0)
        tokens_per_s = tokens / wall_dt if wall_dt and tokens else None

        dev_hist = self.registry.get("device/step_s")
        device_step_s = (
            dev_hist.ema if isinstance(dev_hist, Histogram) else None
        )

        # gradient-transport bytes (ISSUE 2): per-window deltas of the
        # analytic bytes-on-wire counters the facade increments per
        # optimizer step; null when no transport is configured
        if self.registry.get("comm/grad_bytes_prequant_total") is not None:
            comm_pre = self._delta("comm/grad_bytes_prequant_total")
            comm_wire = self._delta("comm/grad_bytes_onwire_total")
            comm_ratio = comm_pre / comm_wire if comm_wire else None
        else:
            comm_pre = comm_wire = comm_ratio = None
        # second wire leg (ISSUE 8): the updated-parameter all-gather of
        # the weight-update-sharded path; the counter exists only when the
        # facade runs a sharded transport — absent, the field rides null
        if self.registry.get("comm/param_gather_bytes_total") is not None:
            comm_gather = self._delta("comm/param_gather_bytes_total")
        else:
            comm_gather = None

        if self.compile_tracker is not None:
            compiles = self.compile_tracker.compiles
            recompiles = self.compile_tracker.recompiles
            compile_time = self.compile_tracker.compile_time_s
        else:
            compiles = recompiles = 0
            compile_time = 0.0

        # persistent compile cache (ISSUE 6): cumulative AOT hit/miss
        # counts + reclaimed compile seconds.  The counters exist only
        # when a CompileCache registered them (a CompileConfig run) —
        # absent, the fields ride as nulls.
        cc_hits = self._counter_or_none("compile_cache/hits_total")
        cc_misses = self._counter_or_none("compile_cache/misses_total")
        cc_saved = self._counter_or_none("compile_cache/saved_s_total")

        # step-time attribution (ISSUE 4): per-window MFU/roofline gauges
        # + goodput buckets, derived from the deltas computed above — one
        # code path for all four facade step APIs
        attr_fields: dict = {}
        if self.attribution is not None:
            attr_fields = self.attribution.window_stats(
                step=step,
                wall_s=wall_dt,
                host_dispatch_s=host_dispatch,
                loader_wait_s=loader_wait,
                ckpt_io_s=ckpt_io,
                comm_bytes_onwire=comm_wire,
            )

        # fleet view (ISSUE 5): accumulate this record's deltas into the
        # current fleet window; at a window boundary ONE in-band
        # process_allgather yields the per-host matrix and the fleet/*
        # fields below — between boundaries the fields ride as nulls
        fleet_fields: Optional[dict] = None
        if self.fleet is not None:
            fleet_fields = self.fleet.window_stats(
                step=step,
                wall_s=wall_dt,
                loader_wait_s=loader_wait,
                comm_bytes_onwire=comm_wire,
            )

        # resilience counters (ISSUE 7): cumulative preemption/restart/
        # quarantine accounting rides every record when a monitor is
        # attached — pure registry reads, no device or IO work
        resilience_fields: Optional[dict] = None
        if self.resilience is not None:
            resilience_fields = self.resilience.event_fields()

        # per-layer numerics (ISSUE 12): the latest per-group block +
        # provenance / quant-error attribution rides every record when a
        # monitor is attached — pure host reads of already-fetched state
        numerics_fields: Optional[dict] = None
        if self.numerics is not None:
            numerics_fields = self.numerics.event_fields()

        # HBM capacity ledger (ISSUE 19): the analytic per-subsystem
        # resident ledger + OOM forecast rides every record when an
        # observatory is attached — pure host arithmetic over
        # shape/dtype trees, no device touches
        # (a ServingEngine passes its OWN observatory via ``memory=`` so
        # serve records ledger the serving subsystems, not the train ones)
        memory_obs = memory if memory is not None else self.memory
        memory_fields: Optional[dict] = None
        if memory_obs is not None:
            memory_obs.refresh_gauges()
            memory_fields = memory_obs.event_fields()

        hbm = hbm_stats() if self.config.track_hbm else None
        record = build_step_event(
            ts=now,
            step=step,
            rank=self.rank,
            window_steps=window_steps,
            host_dispatch_s=host_dispatch,
            device_step_s=device_step_s,
            loader_wait_s=loader_wait,
            samples_per_s=samples_per_s,
            tokens_per_s=tokens_per_s,
            samples_total=samples_total,
            ema_loss=ema_loss,
            step_loss=step_loss,
            grad_norm=grad_norm,
            loss_scale=loss_scale,
            loss_scale_events=self.note_loss_scale(loss_scale),
            skipped_steps=skipped_steps,
            comm_bytes_prequant=comm_pre,
            comm_bytes_onwire=comm_wire,
            comm_bytes_param_gather=comm_gather,
            comm_compression=comm_ratio,
            comm_residual_norm=comm_residual_norm,
            param_norm=param_norm,
            update_ratio=update_ratio,
            nonfinite_leaves=nonfinite_leaves,
            health_anomalies=health_anomalies,
            compiles_total=compiles,
            recompiles=recompiles,
            compile_time_s=compile_time,
            compile_cache_hits=cc_hits,
            compile_cache_misses=cc_misses,
            compile_cache_saved_s=cc_saved,
            hbm_bytes_in_use=(hbm or {}).get("bytes_in_use"),
            hbm_peak_bytes=(hbm or {}).get("peak_bytes_in_use"),
            hbm_bytes_limit=(hbm or {}).get("bytes_limit"),
            fleet=fleet_fields,
            resilience=resilience_fields,
            # serving fields (ISSUE 9): only a ServingEngine emit passes
            # them — training records stay free of every serve/* key
            serve=serve,
            numerics=numerics_fields,
            memory=memory_fields,
            **attr_fields,
        )
        snapshot = self.registry.snapshot()
        for sink in self.sinks:
            sink.emit(record, snapshot)
        return record

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # stop receiving other runs' barrier waits: a closed pipeline's
        # counters are a finished run's record, not a live subscriber
        unregister_sync_registry(self.registry)
        if self.attribution is not None:
            try:
                self.attribution.close()  # stop an in-flight auto-capture
            except Exception:
                pass
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:
                pass

"""Metrics registry: counters, gauges, histograms with pluggable sinks.

The observability core the facade/engine/data layers emit into (ISSUE 1
tentpole).  Design goals, in order:

1. **Hot-path cheap.**  Instrument creation is cached by name; recording is
   one lock-guarded float op.  Nothing here ever touches a device or blocks
   on IO — sinks drain a *snapshot* at the logging cadence.
2. **One namespace.**  Every metric lives under a ``/``-separated name
   (``facade/step_s``, ``data/loader_wait_s``, ``jax/compiles_total``) so
   sinks can render it per-format (Prometheus sanitizes, TensorBoard keeps
   the slashes as tag groups).
3. **Deterministic & test-friendly.**  All state is readable back
   (``value``/``snapshot()``); no wall-clock dependence except the explicit
   ``timer`` helper.

The reference has no equivalent — metrics were DeepSpeed-passthrough only
(reference configs.py:392-405); VERDICT round 5 flagged the resulting
"disconnected one-off" profiling surface as Weak #1.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple


class Counter:
    """Monotonically increasing value (``_total`` convention in sinks)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"Counter {self.name!r} cannot decrease (inc({amount}))"
            )
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value, settable up or down."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._set = False
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._set = True

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._set = True

    @property
    def value(self) -> float:
        return self._value

    @property
    def has_value(self) -> bool:
        """False until the first ``set``/``inc`` — sinks skip unset gauges
        (a 0.0 HBM gauge on a backend without memory_stats would be a lie)."""
        return self._set


#: default histogram buckets: exponential seconds ladder covering sub-ms
#: dispatch times up to minute-scale compiles
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Cumulative-bucket histogram plus an EMA of observations.

    The buckets serve Prometheus exposition; the EMA serves the step-event
    JSONL (a smoothed "current" step time without retaining samples).
    """

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
        ema_weight: float = 0.1,
    ):
        self.name = name
        self.help = help
        bs = tuple(sorted(buckets if buckets is not None else DEFAULT_BUCKETS))
        # finite positive bounds only: +Inf is implicit (the overflow
        # bucket), and a non-positive or -Inf bound can never be a
        # meaningful "le" for the durations/sizes recorded here
        if not bs or any(b <= 0 or math.isinf(b) for b in bs):
            raise ValueError(
                f"Histogram {name!r}: buckets must be finite and positive"
            )
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1 for +Inf
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._ema = 0.0
        self._ema_init = False
        self._ema_weight = float(ema_weight)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)
            if self._ema_init:
                w = self._ema_weight
                self._ema = (1.0 - w) * self._ema + w * value
            else:
                self._ema = value
                self._ema_init = True
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def min(self) -> Optional[float]:
        return self._min if self._count else None

    @property
    def max(self) -> Optional[float]:
        return self._max if self._count else None

    @property
    def ema(self) -> Optional[float]:
        return self._ema if self._ema_init else None

    @property
    def mean(self) -> Optional[float]:
        return self._sum / self._count if self._count else None

    def cumulative_buckets(self) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` ending with ``(inf, count)`` —
        the Prometheus ``_bucket`` series."""
        out = []
        cum = 0
        for b, c in zip(self.buckets, self._counts):
            cum += c
            out.append((b, cum))
        out.append((math.inf, self._count))
        return out


class _Timer:
    """Context manager accumulating elapsed seconds into a Counter and
    (optionally) observing into a Histogram."""

    __slots__ = ("_counter", "_hist", "_t0")

    def __init__(self, counter: Counter, hist: Optional[Histogram] = None):
        self._counter = counter
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._counter.inc(dt)
        if self._hist is not None:
            self._hist.observe(dt)
        return False


class MetricsRegistry:
    """Named instrument factory + snapshot source for sinks.

    ``counter``/``gauge``/``histogram`` are get-or-create (idempotent by
    name); asking for an existing name with a different kind raises — two
    subsystems silently sharing a name under different semantics is the
    classic metrics bug.
    """

    def __init__(self):
        self._instruments: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, **kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._get_or_create(name, Histogram, help=help, buckets=buckets)

    def timer(self, name: str, histogram: Optional[str] = None) -> _Timer:
        """Accumulating wall-clock timer: seconds land in counter ``name``;
        with ``histogram=<name>`` each timing is also observed there."""
        hist = self.histogram(histogram) if histogram else None
        return _Timer(self.counter(name), hist)

    def get(self, name: str):
        return self._instruments.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    def snapshot(self) -> Dict[str, dict]:
        """Point-in-time dump every sink renders from:
        ``{name: {kind, value|count/sum/ema/min/max/buckets, help}}``.

        The lock acquire is bounded: the flight recorder calls this from a
        signal handler running ON the main thread, which may have been
        interrupted while holding the lock — blocking would deadlock the
        crash dump.  On timeout, fall back to a lockless list() of the
        instrument dict (atomic enough under the GIL; instruments are
        never removed)."""
        if self._lock.acquire(timeout=1.0):
            try:
                instruments = list(self._instruments.values())
            finally:
                self._lock.release()
        else:  # pragma: no cover - signal-context fallback
            instruments = list(self._instruments.values())
        out: Dict[str, dict] = {}
        for inst in instruments:
            if isinstance(inst, Counter):
                out[inst.name] = {
                    "kind": "counter", "value": inst.value, "help": inst.help,
                }
            elif isinstance(inst, Gauge):
                if not inst.has_value:
                    continue
                out[inst.name] = {
                    "kind": "gauge", "value": inst.value, "help": inst.help,
                }
            elif isinstance(inst, Histogram):
                out[inst.name] = {
                    "kind": "histogram",
                    "count": inst.count,
                    "sum": inst.sum,
                    "ema": inst.ema,
                    "mean": inst.mean,
                    "buckets": inst.cumulative_buckets(),
                    "help": inst.help,
                }
        return out

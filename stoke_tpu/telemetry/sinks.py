"""Telemetry sinks: JSONL step events, Prometheus text exposition, and the
native TensorBoard event writer.

Every sink implements the same two-method contract:

- ``emit(record, snapshot)`` — called at the logging cadence with the
  structured step event (``events.py`` schema) and the registry snapshot.
- ``close()`` — flush + release file handles (idempotent).

Sinks never raise into the training loop: IO errors are warned once and the
sink disables itself (a full disk must not kill a 3-day run at step 40k).
"""

from __future__ import annotations

import json
import math
import os
import warnings
from typing import Dict, Optional

from stoke_tpu.telemetry.events import validate_step_event


class Sink:
    """Base: subclasses override ``_emit``; failure handling is shared."""

    def __init__(self):
        self._dead = False
        self._warned_invalid = False

    def emit(self, record: dict, snapshot: Dict[str, dict]) -> None:
        if self._dead:
            return
        try:
            self._emit(record, snapshot)
        except OSError as e:  # disk full / perms / unmounted — disable, warn
            self._dead = True
            warnings.warn(
                f"Stoke -- telemetry sink {type(self).__name__} disabled "
                f"after IO error: {e}"
            )
        except ValueError as e:
            # a record failing schema validation (validate_step_event names
            # the offending key in its message) must not raise into the
            # training loop: drop the record, warn ONCE, and keep the sink
            # alive — later valid records still flow
            if not self._warned_invalid:
                self._warned_invalid = True
                warnings.warn(
                    f"Stoke -- telemetry sink {type(self).__name__} dropped "
                    f"an invalid step event (further drops are silent): {e}"
                )

    def _emit(self, record: dict, snapshot: Dict[str, dict]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


# --------------------------------------------------------------------------- #
# JSONL structured step events
# --------------------------------------------------------------------------- #


class JsonlSink(Sink):
    """One schema-validated JSON line per step window, append-only.

    Multi-host: rank 0 writes by default; ``TelemetryConfig.
    jsonl_all_ranks`` gives every process its own ``steps.rank<N>.jsonl``
    (records carry the rank, so files concatenate cleanly)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)  # line-buffered: crash-safe

    def _emit(self, record: dict, snapshot: Dict[str, dict]) -> None:
        validate_step_event(record)
        self._f.write(json.dumps(record, sort_keys=True) + "\n")

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()


# --------------------------------------------------------------------------- #
# Prometheus text exposition (scrape file)
# --------------------------------------------------------------------------- #


def host_labels(process_index: int = 0) -> Dict[str, str]:
    """Identity labels for multi-host expositions (ISSUE 5 satellite):
    ``host`` (this machine's hostname) and ``process_index`` (the JAX
    process rank).  Without them, per-host scrape files of the same job
    aggregated into one Prometheus collide into a single series and the
    per-host skew the fleet view exists to expose is unplottable."""
    import socket

    try:
        host = socket.gethostname() or "unknown"
    except OSError:  # pragma: no cover - exotic resolver failures
        host = "unknown"
    return {"host": host, "process_index": str(int(process_index))}


def _prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name: slashes become underscores,
    invalid chars collapse, and everything gets the ``stoke_`` namespace."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    sanitized = "".join(out).strip("_")
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return f"stoke_{sanitized}"


def _prom_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def render_prometheus(snapshot: Dict[str, dict], labels: Optional[Dict[str, str]] = None) -> str:
    """Registry snapshot -> Prometheus text exposition format 0.0.4
    (HELP/TYPE headers, ``_total`` counters, cumulative ``_bucket`` series).
    Pure function — unit-tested against the format grammar."""
    label_str = ""
    if labels:

        def esc(v):
            # exposition-format label escaping: backslash FIRST (or the
            # escapes it introduces get re-escaped), then quote, then
            # newline — a raw newline in a label value truncates the
            # sample line and poisons every scrape of the file
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        inner = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(labels.items()))
        label_str = "{" + inner + "}"
    lines = []
    for name in sorted(snapshot):
        meta = snapshot[name]
        pname = _prom_name(name)
        kind = meta["kind"]
        # the _total suffix is part of the exposed family name: HELP/TYPE
        # and the sample line must all use it or strict OpenMetrics parsers
        # see an orphan HELP family
        if kind == "counter" and not pname.endswith("_total"):
            pname += "_total"
        if meta.get("help"):
            lines.append(f"# HELP {pname} {meta['help']}")
        if kind == "counter":
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname}{label_str} {_prom_value(meta['value'])}")
        elif kind == "gauge":
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname}{label_str} {_prom_value(meta['value'])}")
        elif kind == "histogram":
            lines.append(f"# TYPE {pname} histogram")
            for le, cum in meta["buckets"]:
                le_s = "+Inf" if math.isinf(le) else _prom_value(le)
                if labels:
                    bucket_labels = label_str[:-1] + f',le="{le_s}"}}'
                else:
                    bucket_labels = f'{{le="{le_s}"}}'
                lines.append(f"{pname}_bucket{bucket_labels} {cum}")
            lines.append(f"{pname}_sum{label_str} {_prom_value(meta['sum'])}")
            lines.append(f"{pname}_count{label_str} {meta['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusSink(Sink):
    """Atomic-rename text-exposition file for node-exporter-style scraping
    (``textfile`` collector / sidecar cat).  Rewritten whole at each cadence
    — scrapers never observe a half-written file."""

    def __init__(self, path: str, labels: Optional[Dict[str, str]] = None):
        super().__init__()
        self.path = path
        self.labels = dict(labels or {})
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _emit(self, record: dict, snapshot: Dict[str, dict]) -> None:
        text = render_prometheus(snapshot, self.labels)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, self.path)


# --------------------------------------------------------------------------- #
# TensorBoard (native event writer, utils/tb_writer.py)
# --------------------------------------------------------------------------- #

#: step-event fields mirrored to TB as scalars (null fields skipped)
_TB_RECORD_FIELDS = (
    "host_dispatch_s", "device_step_s", "loader_wait_s", "samples_per_s",
    "tokens_per_s", "ema_loss", "step_loss", "grad_norm", "skipped_steps",
    "recompiles", "compile_time_s", "hbm_bytes_in_use", "hbm_peak_bytes",
)


class TensorBoardSink(Sink):
    """Scalar mirror of the step events into the native TB event writer
    (``utils/tb_writer.py`` — same file format the frame parser in
    tests/test_utils.py pins), tags under ``telemetry/``."""

    def __init__(self, logdir: Optional[str] = None, writer=None):
        super().__init__()
        if writer is None:
            from stoke_tpu.utils.tb_writer import TBEventWriter

            writer = TBEventWriter(logdir)
        self.writer = writer

    def _emit(self, record: dict, snapshot: Dict[str, dict]) -> None:
        step = record["step"]
        for field in _TB_RECORD_FIELDS:
            v = record.get(field)
            if v is None:
                continue
            self.writer.add_scalar(f"telemetry/{field}", float(v), step)
        ls = record.get("loss_scale")
        if isinstance(ls, list):
            for i, v in enumerate(ls):
                self.writer.add_scalar(f"telemetry/loss_scale_{i}", float(v), step)
        elif ls is not None:
            self.writer.add_scalar("telemetry/loss_scale", float(ls), step)
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()

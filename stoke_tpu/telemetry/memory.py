"""HBM capacity observatory (ISSUE 19): per-subsystem memory ledger,
OOM pre-flight, and per-program peak capture.

The observability stack explains *time* (PR-10 attribution, PR-16 SLOs,
PR-18 roofline) but not *bytes*: HBM was two coarse watermark gauges
with no attribution.  This module closes that gap:

- **Analytic resident ledger** — per-device bytes of each subsystem,
  computed from shape/dtype/sharding trees alone (no device probes):
  params, optimizer state, grad-transport buckets + error-feedback
  residual (per-shard, from :meth:`GradTransport.layout_descriptor` —
  the ISSUE 8 sharded transport holds 1/world of the residual each
  device, the ISSUE 2 replicated one a full copy), the serving KV block
  pool, and in-flight staged-snapshot buffers.  The components recombine
  EXACTLY into the reported resident total (the PR-18 recombination
  discipline: a ledger whose parts do not sum is a lying ledger).
- **Per-program memory cards** — the compiled executable's
  ``memory_analysis()`` component breakdown (argument / output / temp /
  generated-code bytes) per (program, shape signature), captured through
  the already-``memory_analysis``-parameterized
  :class:`~stoke_tpu.telemetry.attribution.CostCardCache` at both
  dispatch funnels (``StepEngine._aot_call`` /
  ``ServingEngine._dispatch``).
- **OOM pre-flight** — predicted peak = resident + max-over-programs
  temp peak, compared against device capacity at ``build()`` /
  ``serve()``: a predicted squeeze warns BEFORE the first dispatch, with
  the largest contributors ranked and remedies named.
- **Reconciliation** — ``mem/unattributed_bytes`` = live
  ``memory_stats()`` bytes-in-use minus the analytic resident total, on
  backends that report stats (None on the CPU simulator): a growing gap
  is allocator fragmentation or an unledgered subsystem.
- **Serve headroom forecast** — ``serve/mem_headroom_bytes``: the KV
  pool's free bytes minus the worst-case blocks-to-completion of every
  in-flight request (the engine computes the block demand; this module
  carries the gauge/JSONL field), feeding the admission story.

Everything is host-side arithmetic over trees the run already holds:
with ``MemoryConfig`` absent nothing here is constructed, records carry
zero new fields, and the dispatched programs are HLO bit-identical; with
it on, the only extra device-adjacent work is one ``memory_analysis``
compile per distinct program signature (the PR-18 opt-in price).

The ``mem/*`` JSONL block is conditional — absent, not null, without
the config — and its field list is pinned append-only in
``analysis/manifests/wire_formats.json``.
"""

from __future__ import annotations

import math
import warnings
from typing import Any, Callable, Dict, Optional, Tuple

from stoke_tpu.telemetry.attribution import CostCardCache
from stoke_tpu.telemetry.collectors import hbm_stats

#: the ``mem/*`` JSONL field block (ISSUE 19) — emitted only by runs
#: with a ``MemoryConfig`` (the default-OFF contract: unconfigured
#: records carry zero new fields).  Pinned append-only by the
#: ``analysis/manifests/wire_formats.json`` manifest.
MEM_FIELDS = (
    "mem/params_bytes",
    "mem/opt_state_bytes",
    "mem/transport_bytes",
    "mem/kv_cache_bytes",
    "mem/snapshot_bytes",
    "mem/resident_bytes",
    "mem/temp_peak_bytes",
    "mem/predicted_peak_bytes",
    "mem/capacity_bytes",
    "mem/headroom_bytes",
    "mem/unattributed_bytes",
)

#: the ledger's subsystem components, in emission order — the five
#: ``mem/<name>_bytes`` JSONL fields above.  ``resident`` is their exact
#: sum (unregistered components count 0), never an independent number.
LEDGER_COMPONENTS: Tuple[str, ...] = (
    "params", "opt_state", "transport", "kv_cache", "snapshot",
)

#: per-component remedy named by the OOM pre-flight (the status.py
#: discipline: every warning says what to do about it)
_COMPONENT_REMEDIES = {
    "params": "shard parameters across the mesh (partition rules) or "
              "serve quantized weights (ServeConfig.quantization)",
    "opt_state": "shard the optimizer state (CommConfig "
                 "shard_updates / ZeRO path) or offload it to disk "
                 "(OffloadConfig)",
    "transport": "use the sharded transport (CommConfig shard_updates: "
                 "buckets and EF residual drop to 1/world per device)",
    "kv_cache": "lower ServeConfig.kv_blocks / max_seqs / max_seq_len, "
                "or quantize the KV cache",
    "snapshot": "lower the staged-snapshot overlap (offload.MAX_STAGED) "
                "or checkpoint less often",
}


def tree_resident_bytes(tree) -> int:
    """Analytic per-device resident bytes of a pytree: each array leaf
    contributes its LOCAL shard (``sharding.shard_shape`` when the leaf
    carries a mesh placement, the full shape otherwise) times its dtype
    width.  Pure host arithmetic — no device touches, safe pre-dispatch.
    """
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                shape = sharding.shard_shape(tuple(shape))
            except Exception:
                pass  # non-mesh placements fall back to the full shape
        try:
            itemsize = dtype.itemsize
        except AttributeError:
            import numpy as np

            itemsize = np.dtype(dtype).itemsize
        total += math.prod(shape) * itemsize
    return int(total)


def transport_resident_bytes(
    descriptor: Optional[Dict[str, Any]],
) -> int:
    """Per-device resident bytes of the gradient transport, from its
    :meth:`~stoke_tpu.parallel.collectives.GradTransport
    .layout_descriptor`: the padded fp32 bucket buffers plus (with error
    feedback) the carried residual.  The replicated transport (ISSUE 2)
    holds full buckets and a full per-leaf residual on every device; the
    sharded one (ISSUE 8) holds 1/world of each — the topology-dependent
    resident set the analytic ledger exists to pin."""
    if not descriptor:
        return 0
    world = max(1, int(descriptor.get("world", 1)))
    sharded = descriptor.get("kind") == "sharded"
    padded_elems = sum(
        int(padded) for _, padded in descriptor.get("buckets", [])
    )
    bucket_bytes = padded_elems * 4
    if sharded:
        bucket_bytes //= world
    residual_bytes = 0
    if descriptor.get("error_feedback"):
        if sharded:
            # the sharded residual lives in bucket layout: 1/world of the
            # padded flat buffer per device
            residual_bytes = padded_elems * 4 // world
        else:
            # replicated: one full fp32 residual per leaf on every device
            residual_bytes = sum(
                int(n) for n in descriptor.get("leaf_sizes", [])
            ) * 4
    return int(bucket_bytes + residual_bytes)


class MemoryObservatory:
    """The HBM capacity ledger of one run (train facade or serving
    engine).  Owners register subsystem components as zero-arg byte
    callables (:meth:`set_component`) and feed the dispatch funnels
    through :meth:`note_program`; the telemetry pipeline reads
    :meth:`event_fields` / :meth:`refresh_gauges`, and
    ``Stoke.memory_summary`` / ``ServingEngine.summary()`` read
    :meth:`summary`."""

    def __init__(self, cfg, registry):
        self.cfg = cfg
        self.registry = registry
        #: component name -> zero-arg callable returning live bytes
        self._components: Dict[str, Callable[[], int]] = {}
        #: per-program memory_analysis component stats (program -> dict)
        self.program_mem: Dict[str, Dict[str, float]] = {}
        #: serve KV headroom forecast, set by the owning ServingEngine
        self._serve_headroom: Optional[float] = None
        self.cache: Optional[CostCardCache] = None
        if cfg.program_peaks:
            # the PR-18 cost-card machinery with the memory_analysis leg
            # armed: one compile per distinct program signature attaches
            # the executable's argument/output/temp/generated-code bytes
            self.cache = CostCardCache(
                registry, counter_prefix="mem/cost", memory_analysis=True
            )
        #: pre-flight verdicts, by context ("build"/"serve") — test hook
        #: and post-mortem record of what the forecast said before the
        #: first dispatch
        self.preflights: Dict[str, Dict[str, Any]] = {}

    # ------------------------------ ledger ------------------------------ #

    def set_component(self, name: str, fn: Callable[[], int]) -> None:
        """Register one subsystem's live-bytes callable.  ``name`` must
        be a :data:`LEDGER_COMPONENTS` member — the JSONL field set is a
        wire format, not an open namespace."""
        if name not in LEDGER_COMPONENTS:
            raise ValueError(
                f"unknown memory-ledger component {name!r} "
                f"(known: {LEDGER_COMPONENTS})"
            )
        self._components[name] = fn

    def ledger(self) -> Dict[str, Optional[int]]:
        """The per-subsystem resident ledger: bytes per registered
        component (None for unregistered ones — absent subsystems are
        distinguishable from empty ones) plus ``resident`` = the EXACT
        sum of the registered components."""
        out: Dict[str, Optional[int]] = {}
        resident = 0
        for name in LEDGER_COMPONENTS:
            fn = self._components.get(name)
            if fn is None:
                out[name] = None
                continue
            try:
                nbytes = int(fn())
            except Exception:
                # a racing subsystem (e.g. a snapshot resolving mid-read)
                # must never kill telemetry; 0 this window, live next
                nbytes = 0
            out[name] = nbytes
            resident += nbytes
        out["resident"] = resident
        return out

    def resident_bytes(self) -> int:
        return self.ledger()["resident"]

    # ------------------------- program peaks ---------------------------- #

    def note_program(self, program: str, fn, args: tuple, sig) -> None:
        """Per-dispatch hook (both engines' funnels): first call per
        (program, signature) pays the ``memory_analysis`` compile; every
        call keeps the program's latest component stats."""
        if self.cache is None:
            return
        card = self.cache.note_dispatch(
            (program, sig), program, fn, args, steps=0
        )
        if card is not None and card.mem_stats:
            self.program_mem[program] = card.mem_stats

    def temp_peak_bytes(self) -> Optional[float]:
        """Max temp-buffer bytes over every analyzed program — the
        transient the OOM pre-flight stacks on top of the resident set
        (programs never run concurrently per device; max, not sum)."""
        temps = [
            m.get("temp_bytes")
            for m in self.program_mem.values()
            if m.get("temp_bytes") is not None
        ]
        return max(temps) if temps else None

    # --------------------------- capacity ------------------------------- #

    def capacity_bytes(self) -> Optional[int]:
        """Device HBM capacity: the ``MemoryConfig.capacity_bytes``
        override when set (planning/test runs on capacity-blind
        backends), else the live ``memory_stats()`` bytes_limit, else
        None (the CPU simulator reports nothing)."""
        if self.cfg.capacity_bytes is not None:
            return int(self.cfg.capacity_bytes)
        stats = hbm_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
        return None

    def predicted_peak_bytes(self) -> int:
        return int(self.resident_bytes() + (self.temp_peak_bytes() or 0))

    def headroom_bytes(self) -> Optional[int]:
        cap = self.capacity_bytes()
        if cap is None:
            return None
        return int(cap - self.predicted_peak_bytes())

    def unattributed_bytes(self) -> Optional[int]:
        """Live ``memory_stats()`` bytes-in-use minus the analytic
        resident total — the reconciliation gauge (None on backends
        without stats).  A growing positive gap is fragmentation or an
        unledgered subsystem; a negative one means something ledgered
        was freed."""
        stats = hbm_stats()
        if not stats or stats.get("bytes_in_use") is None:
            return None
        return int(stats["bytes_in_use"] - self.resident_bytes())

    # --------------------------- pre-flight ----------------------------- #

    def preflight(self, context: str = "build") -> Dict[str, Any]:
        """The OOM pre-flight: predicted peak vs capacity, run once at
        ``build()``/``serve()`` BEFORE the first dispatch.  Fires a
        warning naming the largest contributors and their remedies when
        the prediction crosses ``oom_margin_frac`` of capacity; silent
        (and recorded as such) otherwise or when no capacity is known."""
        ledger = self.ledger()
        resident = ledger["resident"]
        temp = self.temp_peak_bytes()
        predicted = int(resident + (temp or 0))
        capacity = self.capacity_bytes()
        contributors = sorted(
            (
                (name, nbytes)
                for name, nbytes in ledger.items()
                if name in LEDGER_COMPONENTS and nbytes
            ),
            key=lambda kv: kv[1],
            reverse=True,
        )
        verdict: Dict[str, Any] = {
            "context": context,
            "fired": False,
            "resident_bytes": resident,
            "temp_peak_bytes": temp,
            "predicted_peak_bytes": predicted,
            "capacity_bytes": capacity,
            "contributors": contributors,
        }
        if (
            self.cfg.preflight
            and capacity is not None
            and predicted > self.cfg.oom_margin_frac * capacity
        ):
            verdict["fired"] = True
            top = "; ".join(
                f"{name}={nbytes / 2**20:.1f} MiB "
                f"(remedy: {_COMPONENT_REMEDIES[name]})"
                for name, nbytes in contributors[:3]
            ) or "no ledgered components"
            warnings.warn(
                f"Stoke -- OOM pre-flight at {context}: predicted peak "
                f"{predicted / 2**20:.1f} MiB "
                f"(resident {resident / 2**20:.1f} MiB + program temp "
                f"{(temp or 0) / 2**20:.1f} MiB) exceeds "
                f"{self.cfg.oom_margin_frac:.0%} of the "
                f"{capacity / 2**20:.1f} MiB device capacity.  "
                f"Largest contributors: {top}"
            )
        self.preflights[context] = verdict
        return verdict

    # ------------------------- serve headroom --------------------------- #

    def note_serve_headroom(self, headroom_bytes: Optional[float]) -> None:
        """The owning ServingEngine's KV headroom forecast (free-pool
        bytes minus worst-case blocks-to-completion of every in-flight
        request); refreshed at the engine's gauge cadence."""
        self._serve_headroom = headroom_bytes

    def serve_event_fields(self) -> Dict[str, Any]:
        """The conditional serve-record field this observatory adds
        (merged into the engine's serve dict beside the SLO/cost
        blocks)."""
        return {"serve/mem_headroom_bytes": self._serve_headroom}

    # ----------------------------- gauges ------------------------------- #

    def refresh_gauges(self) -> None:
        """Publish the ledger + forecast gauges (telemetry cadence)."""
        reg = self.registry
        for name, v in self.event_fields().items():
            if v is not None:
                reg.gauge(name).set(v)
        if self._serve_headroom is not None:
            reg.gauge("serve/mem_headroom_bytes").set(self._serve_headroom)

    # --------------------------- JSONL fields --------------------------- #

    def event_fields(self) -> Dict[str, Any]:
        """The conditional ``mem/*`` block of one JSONL record — only
        runs constructed with a ``MemoryConfig`` carry an observatory at
        all, so unconfigured records stay byte-identical to pre-ISSUE-19
        ones (``build_step_event`` honors the omission)."""
        ledger = self.ledger()
        out: Dict[str, Any] = {}
        out["mem/params_bytes"] = ledger["params"]
        out["mem/opt_state_bytes"] = ledger["opt_state"]
        out["mem/transport_bytes"] = ledger["transport"]
        out["mem/kv_cache_bytes"] = ledger["kv_cache"]
        out["mem/snapshot_bytes"] = ledger["snapshot"]
        out["mem/resident_bytes"] = ledger["resident"]
        out["mem/temp_peak_bytes"] = self.temp_peak_bytes()
        out["mem/predicted_peak_bytes"] = self.predicted_peak_bytes()
        out["mem/capacity_bytes"] = self.capacity_bytes()
        out["mem/headroom_bytes"] = self.headroom_bytes()
        out["mem/unattributed_bytes"] = self.unattributed_bytes()
        return out

    # ----------------------------- summary ------------------------------ #

    def summary(self) -> Dict[str, Any]:
        """The memory block of ``Stoke.memory_summary()`` /
        ``ServingEngine.summary()``: subsystems ranked by bytes, the
        recombining resident total, per-program memory cards, and the
        pre-flight verdicts."""
        ledger = self.ledger()
        ranked = sorted(
            (
                (name, nbytes)
                for name, nbytes in ledger.items()
                if name in LEDGER_COMPONENTS and nbytes is not None
            ),
            key=lambda kv: kv[1],
            reverse=True,
        )
        return {
            "active": True,
            "components": {name: nbytes for name, nbytes in ranked},
            "resident_bytes": ledger["resident"],
            "temp_peak_bytes": self.temp_peak_bytes(),
            "predicted_peak_bytes": self.predicted_peak_bytes(),
            "capacity_bytes": self.capacity_bytes(),
            "headroom_bytes": self.headroom_bytes(),
            "unattributed_bytes": self.unattributed_bytes(),
            "serve_headroom_bytes": self._serve_headroom,
            "programs": {
                program: dict(stats)
                for program, stats in sorted(self.program_mem.items())
            },
            "preflights": dict(self.preflights),
        }

"""Live ops plane: a scrapeable read-only HTTP observatory (ISSUE 20).

Every observatory before this one is file-and-offline — Prometheus is an
atomic-rename textfile, traces and summaries only exist after someone
calls a Python method, and a load balancer has no way to ask a serving
rank "are you healthy, and how much SLO headroom do you have?".  The ops
plane turns those surfaces into live endpoints the fleet practice of the
serving-economics literature assumes (scrape, drain, capture-on-
incident) — stdlib-only (``http.server.ThreadingHTTPServer``, zero new
dependencies), read-only (GET only), and default OFF: without an
``OpsPlaneConfig`` no thread starts and no socket binds, and with one the
plane emits ZERO new JSONL fields and leaves dispatch counts untouched —
it only reads state other subsystems already keep.

Endpoints (all JSON unless noted):

- ``/metrics`` — Prometheus text exposition rendered by the SAME
  :func:`~stoke_tpu.telemetry.sinks.render_prometheus` the
  ``PrometheusSink`` uses, with the sink's own labels — one renderer, so
  the scrape file and the HTTP surface can never drift (byte-equality is
  pinned in tests).
- ``/healthz`` — 200 while serviceable, 503 once the health monitor has
  halted (``HealthMonitor.halted``): the drain signal for load
  balancers, flipped by the same injected-NaN halt the health tests use.
- ``/statusz`` — one JSON object whose top-level key set is pinned
  append-only as :data:`STATUSZ_FIELDS` (registered in
  ``analysis/manifests/wire_formats.json``): identity, health, the
  training goodput/memory/trace summaries, and the serving engine's
  ``summary()`` (SLO/cost/memory blocks included).
- ``/requests`` — the in-flight serve table: rid, priority class, state
  (queued/prefilling/decoding), tokens emitted, KV blocks held, and the
  TTFT-deadline headroom the PR-16 tracker prices admissions with.
- ``/trace`` — Chrome/Perfetto trace-event snapshot of the span ring via
  ``TraceRecorder.to_trace_events`` (load in ui.perfetto.dev).
- ``/profile?seconds=N`` — bounded on-demand ``jax.profiler`` capture
  into ``ProfilerConfig.trace_dir``, riding the PR-10 auto-capture
  budget (``AttributionConfig.max_captures``) so a scraper cannot DoS
  the run: budget exhausted → 429, capture already in flight → 409.

Multi-host: every rank binds ``cfg.port + process_index`` (loopback by
default), so one host's ranks never collide and a fleet scraper can
enumerate them; ``port=0`` binds an ephemeral port (tests, colocated
benches) and :attr:`OpsPlane.port` reports the bound one.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from stoke_tpu.telemetry.sinks import (
    PrometheusSink,
    host_labels,
    render_prometheus,
)

#: Pinned top-level key set of the ``/statusz`` JSON object — appended to,
#: never reordered or removed (``analysis/manifests/wire_formats.json``
#: carries the reviewed copy and scripts/stoke_lint.py enforces the
#: prefix rule).  Every key is ALWAYS present; absent subsystems render
#: as null, so a fleet dashboard can rely on the shape.
STATUSZ_FIELDS = (
    "rank",
    "host",
    "port",
    "run",
    "uptime_s",
    "healthy",
    "halted",
    "anomalies",
    "training",
    "serving",
)

#: states a row in the ``/requests`` table can report
REQUEST_STATES = ("queued", "prefilling", "decoding")


def _safe(fn: Optional[Callable[[], Any]]) -> Any:
    """Best-effort provider call: the plane reads live state mutated by
    the run's own threads, and a torn read must degrade to null — never
    to a 500 that pages an operator about the observatory itself."""
    if fn is None:
        return None
    try:
        return fn()
    except Exception:
        return None


class OpsPlane:
    """The live HTTP observatory one rank exposes (see module docstring).

    Construction is cheap and binds nothing; :meth:`start` binds the
    socket and launches the daemon serving thread, :meth:`close` shuts
    both down (idempotent).  Attach points mirror the facade's optional
    subsystems — every one of them may stay ``None`` and the affected
    endpoint degrades to null fields or an informative error status.
    """

    def __init__(
        self,
        cfg,
        telemetry=None,
        *,
        registry=None,
        labels: Optional[Dict[str, str]] = None,
        rank: int = 0,
    ):
        self.cfg = cfg
        self.telemetry = telemetry
        self.rank = int(rank)
        self.host = cfg.host
        # multihost contract: rank r binds port + r so colocated ranks
        # never collide; port 0 asks the OS for an ephemeral port (the
        # offset would be meaningless there)
        self.port = cfg.port + self.rank if cfg.port else 0
        self._registry = registry
        self._labels = labels
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._started_at: Optional[float] = None
        # /profile serialization: one capture at a time per plane, on top
        # of the attribution monitor's own in-flight/budget gates
        self._profile_lock = threading.Lock()
        # attach points (all optional)
        self._health = None
        self._tracer = None
        self._attribution = None
        self._engine = None
        self._goodput_fn: Optional[Callable[[], Any]] = None
        self._memory_fn: Optional[Callable[[], Any]] = None
        self._trace_summary_fn: Optional[Callable[[], Any]] = None

    # ----------------------------- attach ------------------------------ #

    def attach_health(self, monitor) -> None:
        """The /healthz flip source (``HealthMonitor.halted``)."""
        self._health = monitor

    def attach_tracer(self, tracer) -> None:
        """The /trace snapshot source (``TraceRecorder``)."""
        self._tracer = tracer

    def attach_attribution(self, monitor) -> None:
        """The /profile capture executor (``AttributionMonitor`` — its
        ``max_captures`` budget bounds scraper-triggered captures)."""
        self._attribution = monitor

    def attach_engine(self, engine) -> None:
        """The /requests table + /statusz serving-block source; a plane
        outliving one engine re-attaches to the next (latest wins)."""
        self._engine = engine

    def attach_training(
        self,
        *,
        goodput: Optional[Callable[[], Any]] = None,
        memory: Optional[Callable[[], Any]] = None,
        trace_summary: Optional[Callable[[], Any]] = None,
    ) -> None:
        """Facade-side summary providers for the /statusz training block
        (each a zero-arg callable returning a JSON-friendly dict or
        None)."""
        self._goodput_fn = goodput
        self._memory_fn = memory
        self._trace_summary_fn = trace_summary

    # ---------------------------- lifecycle ---------------------------- #

    def start(self) -> None:
        """Bind the socket and launch the daemon serving thread.  With
        ``port=0`` the OS assigns an ephemeral port and :attr:`port` is
        updated to the bound one."""
        if self._server is not None:
            return
        from http.server import ThreadingHTTPServer

        handler = _make_handler(self)
        self._server = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._server.server_address[1]
        self._started_at = time.monotonic()
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"stoke-opsplane-{self.port}",
            daemon=True,
        )
        self._thread.start()

    @property
    def running(self) -> bool:
        return self._server is not None

    def close(self) -> None:
        """Shut down the server and join the serving thread (idempotent;
        in-flight handlers finish first)."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ----------------------------- views ------------------------------- #

    def registry(self):
        """The metrics registry /metrics renders: the explicit override,
        else the run telemetry's, else the attached engine's."""
        if self._registry is not None:
            return self._registry
        if self.telemetry is not None:
            return self.telemetry.registry
        if self._engine is not None:
            return self._engine.metrics.registry
        return None

    def scrape_labels(self) -> Dict[str, str]:
        """The exact labels the run's ``PrometheusSink`` stamps on every
        series — taken FROM the live sink when one exists, so the scrape
        file and /metrics byte-match for the same snapshot; reconstructed
        from the telemetry identity otherwise."""
        if self._labels is not None:
            return dict(self._labels)
        if self.telemetry is not None:
            for sink in getattr(self.telemetry, "sinks", []):
                if isinstance(sink, PrometheusSink):
                    return dict(sink.labels)
            cfg = self.telemetry.config
            if cfg is not None:
                return {
                    "rank": str(self.telemetry.rank),
                    "run": cfg.run_name,
                    **host_labels(self.telemetry.rank),
                }
        return {"rank": str(self.rank), **host_labels(self.rank)}

    def render_metrics(self) -> Optional[str]:
        """The /metrics body: the shared renderer over the live registry
        snapshot with the sink's labels (None when no registry exists)."""
        registry = self.registry()
        if registry is None:
            return None
        return render_prometheus(registry.snapshot(), self.scrape_labels())

    def healthz(self):
        """``(http_status, body)`` for /healthz: 503 once the health
        monitor halted (the load-balancer drain signal), 200 otherwise."""
        halted = getattr(self._health, "halted", None)
        body = {
            "ok": halted is None,
            "halted": halted,
            "anomalies": (
                self._health.anomaly_count
                if self._health is not None
                else None
            ),
        }
        return (503 if halted is not None else 200), body

    def statusz(self) -> Dict[str, Any]:
        """The /statusz object — top-level keys exactly
        :data:`STATUSZ_FIELDS` (pinned; absent subsystems are null)."""
        _, health = self.healthz()
        run = None
        if self.telemetry is not None and self.telemetry.config is not None:
            run = self.telemetry.config.run_name
        training = {
            "goodput": _safe(self._goodput_fn),
            "memory": _safe(self._memory_fn),
            "trace": _safe(self._trace_summary_fn),
        }
        engine = self._engine
        out = {
            "rank": self.rank,
            "host": self.host,
            "port": self.port,
            "run": run,
            "uptime_s": (
                time.monotonic() - self._started_at
                if self._started_at is not None
                else None
            ),
            "healthy": health["ok"],
            "halted": health["halted"],
            "anomalies": health["anomalies"],
            "training": (
                training if any(v is not None for v in training.values())
                else None
            ),
            "serving": _safe(engine.summary) if engine is not None else None,
        }
        assert tuple(out) == STATUSZ_FIELDS  # the wire pin, locally honest
        return out

    def requests_table(self) -> Dict[str, Any]:
        """The /requests body: one row per in-flight request (queued +
        slotted), capped at ``cfg.requests_limit`` rows (``truncated``
        says so).  Rows snapshot live scheduler state mutated by the
        engine thread — each field is read once, best-effort."""
        engine = self._engine
        if engine is None:
            return {"requests": [], "truncated": False}
        now = time.perf_counter()
        rows = []

        def row(req, state: str, blocks: int) -> Dict[str, Any]:
            slo = req.slo
            headroom = None
            if (
                slo is not None
                and slo.ttft_target_s is not None
                and req.first_token_ts is None
            ):
                # the PR-16 admission signal, per request: seconds left
                # until the TTFT deadline busts (negative = already has)
                headroom = slo.ttft_target_s - (now - req.arrival_ts)
            return {
                "rid": req.rid,
                "priority": slo.priority if slo is not None else None,
                "state": state,
                "tokens_out": len(req.tokens),
                "kv_blocks": blocks,
                "slo_headroom_s": headroom,
                "age_s": now - req.arrival_ts,
            }

        try:
            sched = engine.scheduler
            for req in list(sched.queue):
                rows.append(row(req, "queued", 0))
            for slot in list(sched.slots):
                req = slot.request
                if req is None:
                    continue
                state = (
                    "prefilling" if slot.prefill_pos is not None
                    else "decoding"
                )
                rows.append(row(req, state, len(slot.blocks)))
        except Exception:
            pass  # a torn snapshot degrades to the rows gathered so far
        limit = max(1, int(self.cfg.requests_limit))
        truncated = len(rows) > limit
        return {"requests": rows[:limit], "truncated": truncated}

    def trace_events(self):
        """The /trace body (Chrome trace-event list) or None without a
        tracer."""
        if self._tracer is None:
            return None
        return self._tracer.to_trace_events()

    def profile(self, seconds: Optional[float]):
        """``(http_status, body)`` for /profile: run one bounded manual
        xprof capture through the attribution monitor's budget.  409 when
        a capture is already in flight (auto or scraped), 429 when the
        ``max_captures`` budget is spent, 400 on a bad duration."""
        if self._attribution is None:
            return 404, {
                "ok": False,
                "error": "no attribution monitor attached — on-demand "
                "capture requires an AttributionConfig and a "
                "ProfilerConfig.trace_dir",
            }
        if seconds is None:
            seconds = self.cfg.profile_default_seconds
        try:
            seconds = float(seconds)
        except (TypeError, ValueError):
            return 400, {"ok": False, "error": "seconds must be a number"}
        if seconds <= 0:
            return 400, {"ok": False, "error": "seconds must be > 0"}
        # a scraper asking for an hour gets the configured ceiling — the
        # budget bounds HOW MANY captures, the clamp bounds how long each
        # one can pin the profiler
        seconds = min(seconds, self.cfg.profile_max_seconds)
        if not self._profile_lock.acquire(blocking=False):
            return 409, {"ok": False, "error": "capture already in flight"}
        try:
            result = self._attribution.manual_capture(
                seconds, reason="opsplane"
            )
        finally:
            self._profile_lock.release()
        if result.get("ok"):
            return 200, result
        error = result.get("error", "")
        status = (
            429 if "budget" in error else 409 if "in flight" in error
            else 503
        )
        return status, result


def _make_handler(plane: OpsPlane):
    """The per-plane request handler class (BaseHTTPRequestHandler binds
    behavior at the class level, so each plane gets its own subclass
    closing over it)."""
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        # the plane is an observatory, not an access log generator
        def log_message(self, fmt, *args):  # noqa: D102 - stdlib override
            pass

        def _send(self, status: int, body: str, ctype: str) -> None:
            data = body.encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            try:
                self.wfile.write(data)
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper hung up mid-write; nothing to salvage

        def _send_json(self, status: int, obj) -> None:
            self._send(
                status, json.dumps(obj, default=str), "application/json"
            )

        def do_GET(self) -> None:  # noqa: N802 - stdlib naming
            parsed = urlparse(self.path)
            route = parsed.path.rstrip("/") or "/"
            try:
                if route == "/metrics":
                    text = plane.render_metrics()
                    if text is None:
                        self._send_json(
                            404, {"error": "no metrics registry attached"}
                        )
                    else:
                        # version=0.0.4 is the text exposition the
                        # renderer targets; Prometheus requires it echoed
                        self._send(
                            200, text,
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                elif route == "/healthz":
                    status, body = plane.healthz()
                    self._send_json(status, body)
                elif route == "/statusz":
                    self._send_json(200, plane.statusz())
                elif route == "/requests":
                    self._send_json(200, plane.requests_table())
                elif route == "/trace":
                    events = plane.trace_events()
                    if events is None:
                        self._send_json(
                            404,
                            {"error": "no trace recorder attached — add a "
                             "TraceConfig"},
                        )
                    else:
                        self._send_json(200, events)
                elif route == "/profile":
                    qs = parse_qs(parsed.query)
                    seconds = qs.get("seconds", [None])[0]
                    status, body = plane.profile(seconds)
                    self._send_json(status, body)
                else:
                    self._send_json(
                        404,
                        {
                            "error": f"unknown endpoint {route!r}",
                            "endpoints": [
                                "/metrics", "/healthz", "/statusz",
                                "/requests", "/trace", "/profile",
                            ],
                        },
                    )
            except Exception as e:  # read-only surface: never crash a run
                self._send_json(500, {"error": repr(e)})

        # a read-only plane: every mutating verb is refused uniformly
        def _refuse(self) -> None:
            self._send_json(
                405, {"error": "the ops plane is read-only (GET only)"}
            )

        do_POST = do_PUT = do_DELETE = do_PATCH = _refuse

    return Handler

"""Training health monitor (ISSUE 3 tentpole): on-device numerics
sentinels, host-side anomaly detectors, and the hang watchdog.

The telemetry layer answers "how fast is the step"; this module answers
"is this run still healthy".  Three cooperating pieces (the fourth, the
flight recorder, lives in :mod:`stoke_tpu.telemetry.recorder`):

- **Sentinels** — :func:`compute_sentinels` packs per-step diagnostics
  (loss, global grad/param norms, update ratio, nonfinite-leaf count,
  scaler-skip flag, comm residual norm) into one tiny f32 vector *inside*
  the engine's existing compiled apply, so surfacing them costs zero extra
  device dispatches (acceptance-checked against the engine dispatch
  counter).  This subsumes the host-side ``facade._sample_grad_norm``
  extra reduction.
- **Detectors** — small host-side checks over the sentinel stream and the
  telemetry registry (loss/grad-norm spike z-score vs a running EMA,
  nonfinite gradients, fp16 scaler-skip streaks, recompile storms, loader
  starvation streaks, error-feedback residual runaway), each firing one of
  four actions: ``record`` / ``warn`` / ``dump`` / ``halt``.
- **Watchdog** — :class:`HangWatchdog`, a daemon thread armed per dispatch
  that fires when no step completes within the timeout (wedged collective
  / dead tunnel), dumping all-thread stacks + a post-mortem bundle and
  optionally hard-exiting with :data:`WATCHDOG_EXIT_CODE`.

Everything is default-OFF; with no ``HealthConfig`` the compiled step
programs are bit-identical to before this module existed.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: exit code of a watchdog-killed process — distinct from generic failures
#: so supervisors (scripts/_supervise.py keeps a synced copy: it must not
#: import jax) can report "hung and self-terminated" instead of "timed out"
WATCHDOG_EXIT_CODE = 113

#: sentinel vector layout: field name -> index.  The order is the wire
#: format of the packed vector the compiled step returns; never reorder,
#: only append.
SENTINEL_FIELDS = (
    "step_loss",          # undivided micro loss at the boundary
    "grad_norm",          # global grad norm, unscaled, post-transport, pre-clip
    "param_norm",         # global norm of the updated parameters
    "update_ratio",       # ||param_new - param_old|| / (||param_new|| + eps)
    "nonfinite_leaves",   # gradient leaves containing any non-finite value
    "scaler_skip",        # 1.0 when the fp16 scaler skipped this step
    "comm_residual_norm", # error-feedback residual norm (0 without EF)
    # appended by ISSUE 12 (append-only wire format): flat index of the
    # FIRST gradient leaf carrying a non-finite value, -1 when all finite
    # — the NonFiniteDetector maps it to a leaf path so bundles name the
    # culprit even when only a HealthConfig is on
    "first_nonfinite_leaf",
)
SENTINEL_INDEX = {name: i for i, name in enumerate(SENTINEL_FIELDS)}
N_SENTINELS = len(SENTINEL_FIELDS)


class HealthHaltError(RuntimeError):
    """Raised at the facade boundary when a detector with action ``halt``
    fires.  Carries the anomalies that tripped it and the post-mortem
    bundle path (a halt always dumps first — leave a corpse)."""

    def __init__(self, anomalies: List["Anomaly"], bundle: Optional[str]):
        self.anomalies = list(anomalies)
        self.bundle = bundle
        names = ", ".join(a.detector for a in self.anomalies) or "?"
        msg = f"Stoke -- health halt: {names}"
        if bundle:
            msg += f" (post-mortem bundle: {bundle})"
        super().__init__(msg)


# --------------------------------------------------------------------------- #
# on-device sentinels (called inside the engine's compiled apply)
# --------------------------------------------------------------------------- #


def compute_sentinels(loss_val, grads, new_params, old_params, finite,
                      comm_state):
    """Pack the per-step diagnostics vector — TRACED inside the engine's
    apply core, so every value is one fused reduction in the existing XLA
    program (zero extra dispatches, zero extra host syncs beyond fetching
    the tiny output).

    Args mirror what the apply core already has in hand: the boundary loss
    scalar (or None), the unscaled post-transport gradients, the parameter
    trees before/after the update, the scaler finite flag, and the
    gradient-transport state (``residual`` key when error feedback is on).
    Returns a ``[N_SENTINELS]`` float32 array.
    """
    import jax
    import jax.numpy as jnp
    import optax

    eps = jnp.float32(1e-12)
    grad_norm = optax.global_norm(grads).astype(jnp.float32)
    param_norm = optax.global_norm(new_params).astype(jnp.float32)
    update_norm = optax.global_norm(
        jax.tree_util.tree_map(
            lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
            new_params, old_params,
        )
    )
    update_ratio = update_norm / (param_norm + eps)
    leaves = jax.tree_util.tree_leaves(grads)
    if leaves:
        flags = jnp.stack(
            [jnp.any(~jnp.isfinite(l)) for l in leaves]
        )
        nonfinite = jnp.sum(flags.astype(jnp.float32))
        # first offending leaf's flat index (argmax of the flag vector is
        # the first True), -1 when every leaf is finite — NaN provenance
        # at leaf granularity for one extra O(n_leaves) reduction
        first_bad = jnp.where(
            jnp.any(flags),
            jnp.argmax(flags).astype(jnp.float32),
            jnp.float32(-1.0),
        )
    else:
        nonfinite = jnp.float32(0.0)
        first_bad = jnp.float32(-1.0)
    skip = 1.0 - jnp.asarray(finite).astype(jnp.float32)
    residual = None
    if isinstance(comm_state, dict):
        residual = comm_state.get("residual")
    res_norm = (
        optax.global_norm(residual).astype(jnp.float32)
        if residual is not None
        else jnp.float32(0.0)
    )
    loss = (
        jnp.asarray(loss_val, jnp.float32).reshape(())
        if loss_val is not None
        else jnp.float32(jnp.nan)
    )
    return jnp.stack([
        loss, grad_norm, param_norm, update_ratio,
        jnp.asarray(nonfinite, jnp.float32), skip, res_norm, first_bad,
    ])


def unpack_sentinels(vec) -> Dict[str, float]:
    """Host-side view of one sentinel row as ``{field: float}``."""
    arr = np.asarray(vec, np.float64).reshape(-1)
    return {name: float(arr[i]) for i, name in enumerate(SENTINEL_FIELDS)}


# --------------------------------------------------------------------------- #
# detectors
# --------------------------------------------------------------------------- #


@dataclass
class Anomaly:
    """One detector firing.  ``context`` carries structured provenance
    (e.g. the first offending leaf path / module group, ISSUE 12) so
    bundles name the culprit machine-readably, not only in the
    message."""

    detector: str
    step: int
    action: str
    message: str
    value: Optional[float] = None
    context: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "detector": self.detector,
            "step": self.step,
            "action": self.action,
            "message": self.message,
            "value": self.value,
        }
        if self.context is not None:
            out["context"] = dict(self.context)
        return out


class _RunningStats:
    """EMA mean/variance for the z-score spike detectors (an exponentially
    weighted analogue of Welford's update — deterministic, O(1) state)."""

    def __init__(self, alpha: float):
        self.alpha = float(alpha)
        self.mean: Optional[float] = None
        self.var = 0.0
        self.count = 0

    def zscore(self, x: float) -> Optional[float]:
        """Z-score of ``x`` against the CURRENT stats (before updating with
        it); None until the first observation."""
        if self.mean is None:
            return None
        std = self.var ** 0.5
        if std <= 0.0:
            return 0.0 if x == self.mean else float("inf")
        return (x - self.mean) / std

    def update(self, x: float) -> None:
        self.count += 1
        if self.mean is None:
            self.mean = float(x)
            self.var = 0.0
            return
        a = self.alpha
        delta = float(x) - self.mean
        self.mean += a * delta
        # EW variance: blends the squared innovation (West 1979 lineage)
        self.var = (1.0 - a) * (self.var + a * delta * delta)


class Detector:
    """Base: ``check(step, sentinels, ctx)`` returns an :class:`Anomaly`
    or None.  ``sentinels`` is the unpacked dict (or None when the
    on-device vector is off); ``ctx`` is the owning monitor (registry /
    compile-tracker access)."""

    name = "detector"

    def __init__(self, action: str):
        self.action = action

    def check(self, step: int, sentinels: Optional[Dict[str, float]],
              ctx: "HealthMonitor") -> Optional[Anomaly]:
        raise NotImplementedError

    def _fire(self, step: int, message: str,
              value: Optional[float] = None) -> Anomaly:
        return Anomaly(self.name, step, self.action, message, value)


class SpikeDetector(Detector):
    """Shared z-score-vs-EMA spike logic for loss / grad-norm."""

    field_name = ""

    def __init__(self, action: str, zscore: float, warmup: int, alpha: float):
        super().__init__(action)
        self.threshold = float(zscore)
        self.warmup = int(warmup)
        self.stats = _RunningStats(alpha)

    def check(self, step, sentinels, ctx):
        if sentinels is None:
            return None
        x = sentinels.get(self.field_name)
        if x is None or not np.isfinite(x):
            # non-finite values are the NonFiniteDetector's job; feeding
            # them into the EMA would poison the baseline forever
            return None
        z = self.stats.zscore(x)
        fired = None
        if (
            z is not None
            and self.stats.count >= self.warmup
            and z > self.threshold
        ):
            fired = self._fire(
                step,
                f"{self.field_name} {x:.6g} is {z:.1f} sigma above its "
                f"running mean {self.stats.mean:.6g} "
                f"(threshold {self.threshold})",
                value=x,
            )
            # a spike must not drag the baseline up to itself: clamp the
            # update to the detection threshold so repeated spikes keep
            # firing instead of normalizing.  With ZERO running variance
            # the clamp would collapse to the mean and a permanent regime
            # shift would fire forever — feed the raw value there so the
            # baseline adapts.
            std = self.stats.var ** 0.5
            if std > 0:
                x = self.stats.mean + self.threshold * std
        self.stats.update(x)
        return fired


class LossSpikeDetector(SpikeDetector):
    name = "loss_spike"
    field_name = "step_loss"


class GradNormSpikeDetector(SpikeDetector):
    name = "grad_norm_spike"
    field_name = "grad_norm"


class NonFiniteDetector(Detector):
    name = "nonfinite_grads"

    def check(self, step, sentinels, ctx):
        if sentinels is None:
            return None
        n = sentinels.get("nonfinite_leaves", 0.0)
        if n and n > 0:
            # leaf-level provenance (ISSUE 12 satellite): the sentinel row
            # carries the FIRST offending leaf's flat index; the monitor's
            # leaf-path table (facade-installed) names it, so the anomaly
            # and its bundle say WHERE even when only HealthConfig is on
            idx = int(sentinels.get("first_nonfinite_leaf", -1.0))
            context = None
            where = ""
            if idx >= 0:
                context = {"first_leaf_index": idx}
                paths = getattr(ctx, "leaf_paths", None)
                if paths and idx < len(paths):
                    context["first_leaf_path"] = paths[idx]
                    where = f" (first offending leaf: {paths[idx]})"
            anomaly = self._fire(
                step,
                f"{int(n)} gradient leaves contain non-finite values at "
                f"step {step}{where}",
                value=n,
            )
            anomaly.context = context
            return anomaly
        return None


class ScalerSkipStreakDetector(Detector):
    name = "scaler_skip_streak"

    def __init__(self, action: str, streak: int):
        super().__init__(action)
        self.streak = int(streak)
        self._run = 0

    def check(self, step, sentinels, ctx):
        if sentinels is None:
            return None
        if sentinels.get("scaler_skip", 0.0) > 0:
            self._run += 1
        else:
            self._run = 0
            return None
        if self._run >= self.streak:
            fired = self._fire(
                step,
                f"{self._run} consecutive fp16 scaler-skipped steps "
                f"(scale collapse?)",
                value=float(self._run),
            )
            self._run = 0  # re-arm: fire once per streak, not per step
            return fired
        return None


class RecompileStormDetector(Detector):
    """Structural recompiles (engine shape-signature collector) growing by
    >= threshold within a sliding step window: shape-polymorphic inputs
    eating the run in silent multi-second compiles."""

    name = "recompile_storm"

    def __init__(self, action: str, threshold: int, window: int):
        super().__init__(action)
        self.threshold = int(threshold)
        self.window = int(window)
        self._history: List[tuple] = []  # (step, cumulative recompiles)

    def check(self, step, sentinels, ctx):
        tracker = ctx.compile_tracker
        if tracker is None:
            return None
        total = tracker.recompiles
        self._history.append((step, total))
        cutoff = step - self.window
        while self._history and self._history[0][0] < cutoff:
            self._history.pop(0)
        delta = total - self._history[0][1]
        if delta >= self.threshold:
            self._history = [(step, total)]  # re-arm
            return self._fire(
                step,
                f"{delta} structural recompiles within the last "
                f"{self.window} steps (shape-polymorphic inputs?)",
                value=float(delta),
            )
        return None


class LoaderStarvationDetector(Detector):
    """Consecutive steps accruing post-warmup loader starvation time: the
    device is waiting on the input pipeline."""

    name = "loader_starvation"

    def __init__(self, action: str, streak: int):
        super().__init__(action)
        self.streak = int(streak)
        self._last = 0.0
        self._run = 0

    def check(self, step, sentinels, ctx):
        counter = ctx.registry.get("data/starvation_s")
        if counter is None:
            return None
        now = counter.value
        grew = now > self._last
        self._last = now
        if grew:
            self._run += 1
        else:
            self._run = 0
            return None
        if self._run >= self.streak:
            fired = self._fire(
                step,
                f"loader starvation accrued on {self._run} consecutive "
                f"steps ({now:.3f}s total; input-pipeline-bound)",
                value=now,
            )
            self._run = 0
            return fired
        return None


class CommResidualRunawayDetector(Detector):
    """Error-feedback residual norm outrunning its own EMA (or going
    non-finite): the int8 transport's quantization error is no longer being
    re-absorbed — the standing correctness monitor PR 2's lossy wire format
    requires."""

    name = "comm_residual_runaway"

    def __init__(self, action: str, factor: float, warmup: int, alpha: float):
        super().__init__(action)
        self.factor = float(factor)
        self.warmup = int(warmup)
        self.stats = _RunningStats(alpha)

    def check(self, step, sentinels, ctx):
        if sentinels is None:
            return None
        x = sentinels.get("comm_residual_norm", 0.0)
        if x == 0.0:
            return None  # no transport / no error feedback
        if not np.isfinite(x):
            return self._fire(
                step, "error-feedback residual went non-finite", value=x
            )
        fired = None
        if (
            self.stats.mean is not None
            and self.stats.count >= self.warmup
            and self.stats.mean > 0
            and x > self.factor * self.stats.mean
        ):
            fired = self._fire(
                step,
                f"error-feedback residual norm {x:.6g} exceeds "
                f"{self.factor}x its running mean {self.stats.mean:.6g} "
                f"(quantization error outrunning re-injection)",
                value=x,
            )
        self.stats.update(x)
        return fired


def build_detectors(cfg) -> List[Detector]:
    """Instantiate the detector registry from a ``HealthConfig``."""
    return [
        LossSpikeDetector(
            cfg.loss_spike_action, cfg.loss_spike_zscore,
            cfg.detector_warmup_steps, cfg.ema_alpha,
        ),
        GradNormSpikeDetector(
            cfg.grad_spike_action, cfg.grad_spike_zscore,
            cfg.detector_warmup_steps, cfg.ema_alpha,
        ),
        NonFiniteDetector(cfg.nonfinite_action),
        ScalerSkipStreakDetector(
            cfg.scaler_skip_action, cfg.scaler_skip_streak
        ),
        RecompileStormDetector(
            cfg.recompile_storm_action, cfg.recompile_storm_threshold,
            cfg.recompile_storm_window,
        ),
        LoaderStarvationDetector(
            cfg.starvation_action, cfg.starvation_streak
        ),
        CommResidualRunawayDetector(
            cfg.comm_residual_action, cfg.comm_residual_factor,
            cfg.detector_warmup_steps, cfg.ema_alpha,
        ),
    ]


# --------------------------------------------------------------------------- #
# hang watchdog
# --------------------------------------------------------------------------- #


class HangWatchdog:
    """Daemon thread firing when an armed dispatch does not complete in
    time (the wedged-collective / dead-tunnel case: the training thread is
    stuck inside a device call and can never report the hang itself).

    ``arm()`` before a dispatch, ``disarm()`` once the step (and its
    sentinel fetch) completed.  On trip: ``on_trip()`` runs on the watchdog
    thread (dump stacks + bundle), then — with ``kill=True`` — the process
    hard-exits with :data:`WATCHDOG_EXIT_CODE` so a supervisor can tell
    "hung and self-terminated" from a generic timeout.  Fires once per arm.
    """

    def __init__(
        self,
        timeout_s: float,
        on_trip: Callable[[], None],
        *,
        kill: bool = False,
        exit_code: int = WATCHDOG_EXIT_CODE,
    ):
        self.timeout_s = float(timeout_s)
        self.on_trip = on_trip
        self.kill = bool(kill)
        self.exit_code = int(exit_code)
        self.trips = 0
        self._deadline: Optional[float] = None
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="stoke-health-watchdog", daemon=True
        )
        self._thread.start()

    def arm(self, timeout_s: Optional[float] = None) -> None:
        """Arm (or re-arm, extending the deadline) for one dispatch;
        ``timeout_s`` overrides the default — callers scale it by the
        steps a dispatch covers and by warm-up compile grace."""
        with self._lock:
            self._deadline = time.monotonic() + (
                self.timeout_s if timeout_s is None else float(timeout_s)
            )
        self._wake.set()

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop:
            with self._lock:
                deadline = self._deadline
            if deadline is None:
                self._wake.wait(timeout=self.timeout_s)
                self._wake.clear()
                continue
            wait = deadline - time.monotonic()
            if wait > 0:
                # short slices so a disarm/stop is honored promptly
                self._wake.wait(timeout=min(wait, 0.05))
                self._wake.clear()
                continue
            with self._lock:
                # re-check under the lock: the step may have completed (or
                # re-armed) while we were deciding to fire
                if self._deadline is None or self._deadline > time.monotonic():
                    continue
                self._deadline = None  # fire once per arm
            self.trips += 1
            try:
                self.on_trip()
            except Exception:
                pass
            if self.kill:
                import os

                os._exit(self.exit_code)


# --------------------------------------------------------------------------- #
# the monitor
# --------------------------------------------------------------------------- #

#: warnings per detector before the "warn" action degrades to "record"
#: (a detector firing every step must not drown the log)
MAX_WARNINGS_PER_DETECTOR = 5

#: Anomaly OBJECTS retained for inspection (counters are unbounded; the
#: object list must not grow without bound over a multi-day run with a
#: permanently-firing detector)
RECENT_ANOMALIES_MAX = 1024


class HealthMonitor:
    """Owns the detector registry, the flight recorder, and the watchdog;
    the facade calls :meth:`observe` once per completed optimizer step.

    Anomaly counters land in the telemetry registry
    (``health/anomalies_total``, ``health/anomaly_<detector>_total``,
    ``health/bundles_total``, ``health/watchdog_trips_total``) and are
    therefore exposed through the Prometheus/JSONL sinks for free.
    """

    def __init__(self, cfg, registry, recorder, *,
                 compile_tracker=None):
        self.cfg = cfg
        self.registry = registry
        self.recorder = recorder
        self.compile_tracker = compile_tracker
        self.detectors = build_detectors(cfg)
        # bounded recent-anomaly window; totals live in the int counters
        # below (and the registry), never in list length
        self.anomalies: "deque[Anomaly]" = deque(maxlen=RECENT_ANOMALIES_MAX)
        self._anomaly_total = 0
        self._by_detector: Dict[str, int] = {}
        self._anomaly_dumps = 0
        self._exception_dumps = 0
        self._warned: Dict[str, int] = {}
        self._steps_completed = False
        # the name of the detector that halted the run, set just before
        # HealthHaltError leaves observe() and never cleared: the ops
        # plane's /healthz (ISSUE 20) reads it as the load-balancer
        # drain signal, which must survive the exception unwinding
        self.halted: Optional[str] = None
        # flat-leaf-index -> path-string table for the param/grad tree
        # (facade-installed; telemetry.numerics.leaf_path_names) — the
        # NonFiniteDetector's leaf-level provenance lookup
        self.leaf_paths: Optional[List[str]] = None
        self.watchdog: Optional[HangWatchdog] = None
        if cfg.watchdog:
            self.watchdog = HangWatchdog(
                cfg.watchdog_timeout_s,
                self._on_watchdog_trip,
                kill=cfg.watchdog_kill,
            )
        # pre-register so scrapes carry zeros before the first anomaly
        registry.counter(
            "health/anomalies_total", help="health detector firings"
        )
        registry.counter(
            "health/bundles_total", help="post-mortem bundles written"
        )
        registry.counter(
            "health/watchdog_trips_total", help="hang-watchdog firings"
        )
        registry.counter(
            "health/halt_s",
            help="wall seconds spent writing health dumps / halting "
            "(the goodput ledger's halt bucket, ISSUE 4)",
        )

    # ------------------------------ hooks ------------------------------ #

    def arm_watchdog(self, steps: int = 1) -> None:
        """Arm the hang watchdog for one upcoming dispatch.  The deadline
        scales with the optimizer steps the dispatch covers (a
        ``train_steps(n)`` segment legitimately runs n steps in one
        program) and, until the FIRST step has ever completed, by the
        compile-grace allowance (warm-up XLA compilation can dwarf a
        steady-state step).  No-op without a watchdog."""
        if self.watchdog is None:
            return
        timeout = self.cfg.watchdog_timeout_s * max(1, int(steps))
        if not self._steps_completed:
            timeout += max(0.0, self.cfg.watchdog_compile_grace_s)
        self.watchdog.arm(timeout)

    def disarm_watchdog(self) -> None:
        if self.watchdog is not None:
            self.watchdog.disarm()

    def _on_watchdog_trip(self) -> None:
        self.registry.counter("health/watchdog_trips_total").inc()
        self.recorder.record("note", {
            "note": "watchdog trip",
            "timeout_s": self.cfg.watchdog_timeout_s,
        })
        self.dump(
            "watchdog",
            extra={
                "timeout_s": self.cfg.watchdog_timeout_s,
                "exit_code": (
                    WATCHDOG_EXIT_CODE if self.cfg.watchdog_kill else None
                ),
            },
        )

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None) -> str:
        """The single bundle-writing funnel (anomaly/halt/watchdog/
        exception/manual): counts into ``health/bundles_total`` and
        delegates to the recorder.  Uncapped — only the anomaly ``dump``
        action applies the ``max_dumps`` budget, in ``observe``.  (Signal
        dumps go straight through the recorder's handler and skip the
        counter: the handler must stay registry-free to be
        deadlock-safe.)"""
        self.registry.counter("health/bundles_total").inc()
        t0 = time.monotonic()
        try:
            return self.recorder.dump(reason, extra)
        finally:
            # wall clock lost to the dump: the goodput ledger's halt
            # bucket (ISSUE 4) reads this counter's per-window delta
            self.registry.counter("health/halt_s").inc(
                time.monotonic() - t0
            )

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self.recorder.uninstall_signal_handlers()

    @property
    def anomaly_count(self) -> int:
        """Cumulative detector firings (NOT bounded by the retained-object
        window)."""
        return self._anomaly_total

    def anomaly_counts_by_detector(self) -> Dict[str, int]:
        return dict(self._by_detector)

    def note_exception_dump(self) -> bool:
        """Budget gate for exception-path bundles: True while under the
        ``max_dumps`` cap (a caller retrying a failing call in a loop must
        not fill the disk with identical corpses)."""
        if self._exception_dumps >= max(1, self.cfg.max_dumps):
            return False
        self._exception_dumps += 1
        return True

    # ----------------------------- observe ----------------------------- #

    def observe(self, step: int,
                sentinel_row: Optional[np.ndarray]) -> List[Anomaly]:
        """Run every detector against one completed optimizer step.

        ``sentinel_row`` is the fetched on-device vector (None when
        sentinels are off — registry-driven detectors still run).  Applies
        each firing's action; a ``halt`` firing raises
        :class:`HealthHaltError` after all detectors ran and the bundle was
        written (the facade calls this at its step boundary, so the raise
        IS the facade-boundary halt).
        """
        self._steps_completed = True  # un-gates the watchdog compile grace
        sentinels = (
            unpack_sentinels(sentinel_row)
            if sentinel_row is not None else None
        )
        if sentinels is not None:
            self.recorder.record(
                "sentinels", {"step": step, "values": sentinels}
            )
        fired: List[Anomaly] = []
        for det in self.detectors:
            try:
                anomaly = det.check(step, sentinels, self)
            except Exception as e:  # a broken detector must not kill a run
                warnings.warn(
                    f"Stoke -- health detector {det.name} raised {e!r}; "
                    f"skipping it this step"
                )
                continue
            if anomaly is not None:
                fired.append(anomaly)
        if not fired:
            return fired
        halts: List[Anomaly] = []
        bundle: Optional[str] = None
        for anomaly in fired:
            self.anomalies.append(anomaly)
            self._anomaly_total += 1
            self._by_detector[anomaly.detector] = (
                self._by_detector.get(anomaly.detector, 0) + 1
            )
            self.registry.counter("health/anomalies_total").inc()
            self.registry.counter(
                f"health/anomaly_{anomaly.detector}_total",
                help=f"{anomaly.detector} detector firings",
            ).inc()
            self.recorder.record("anomaly", anomaly.to_dict())
            if anomaly.action == "warn":
                n = self._warned.get(anomaly.detector, 0)
                if n < MAX_WARNINGS_PER_DETECTOR:
                    self._warned[anomaly.detector] = n + 1
                    warnings.warn(f"Stoke -- health: {anomaly.message}")
            elif anomaly.action == "dump":
                if self._anomaly_dumps < self.cfg.max_dumps:
                    self._anomaly_dumps += 1
                    bundle = self.dump(
                        f"anomaly-{anomaly.detector}",
                        extra=anomaly.to_dict(),
                    )
            elif anomaly.action == "halt":
                halts.append(anomaly)
        if halts:
            self.halted = halts[0].detector
            bundle = self.dump(
                f"halt-{halts[0].detector}",
                extra=[a.to_dict() for a in halts],
            )
            raise HealthHaltError(halts, bundle)
        return fired

"""Cross-cutting collectors: XLA compile tracking, HBM high-watermarks, and
labeled xprof spans.

- :class:`CompileTracker` listens to ``jax.monitoring`` duration events
  (``/jax/core/compile/backend_compile_duration`` fires once per backend
  compile) and feeds registry counters.  Recompiles — compiles beyond the
  expected warm-up set — are the silent TPU perf killer: a shape-polymorphic
  input (ragged batch, drifting pad length) silently triggers a multi-second
  XLA compile per new shape, and nothing in stock JAX tells you.
- :func:`hbm_stats` / :func:`update_hbm_gauges` read
  ``device.memory_stats()`` (None-tolerant: the CPU simulator reports
  nothing) into high-watermark gauges.
- :func:`xprof_span` wraps ``jax.profiler.TraceAnnotation`` so engine phases
  (place/dispatch/accum/step/io) show up *named* in xprof/TensorBoard-profile
  timelines instead of as anonymous python frames.  Spans are process-global
  (annotations are free when no trace is active) but can be disabled via
  :func:`set_xprof_enabled` for pathological host-bound microbenchmarks.

``jax.monitoring`` listeners are process-global and cannot be individually
removed, so ONE module-level dispatcher is installed lazily and fans out to
live trackers (kept in a ``WeakSet`` — a dropped ``Telemetry`` object must
not leak its tracker forever).
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Dict, Optional

#: monitoring event that fires once per XLA backend compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_trackers: "weakref.WeakSet[CompileTracker]" = weakref.WeakSet()
_listener_installed = False
_listener_lock = threading.Lock()


def _dispatch(event: str, duration: float, **kwargs) -> None:
    if event != _COMPILE_EVENT:
        return
    for tracker in list(_trackers):
        tracker._on_compile(duration)


def _ensure_listener() -> None:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_dispatch)
        _listener_installed = True


class CompileTracker:
    """Per-``Telemetry`` compile accounting.

    - ``compiles`` / ``compile_time_s``: every XLA backend compile observed
      since construction (fed by the ``jax.monitoring`` dispatcher; includes
      one-off tiny eager-op programs, so treat as a warm-up-heavy total).
    - ``recompiles``: *structurally detected* re-compilations of an
      already-warm step program under a new input-shape signature, reported
      by the owning facade's engine via :meth:`note_recompile`
      (instance-scoped — the monitoring stream carries no program identity,
      and another facade's shape churn must not be charged here).  The
      actionable "your batches are shape-polymorphic" signal.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_time_s = 0.0
        self.recompiles = 0
        self._registry = registry
        if registry is not None:
            # pre-register so snapshots carry zeros before the first compile
            registry.counter(
                "jax/compiles_total", help="XLA backend compiles observed"
            )
            registry.counter(
                "jax/compile_time_s", help="cumulative XLA compile seconds"
            )
            registry.counter(
                "jax/recompiles_total",
                help="warm step programs re-compiled for a new input-shape "
                "signature",
            )
        _ensure_listener()
        _trackers.add(self)

    def _on_compile(self, duration: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compile_time_s += float(duration)
        if self._registry is not None:
            self._registry.counter("jax/compiles_total").inc()
            self._registry.counter("jax/compile_time_s").inc(float(duration))

    def note_recompile(self, n: int = 1) -> None:
        """Record ``n`` structural recompiles (engine shape-signature
        detection)."""
        with self._lock:
            self.recompiles += int(n)
        if self._registry is not None:
            self._registry.counter("jax/recompiles_total").inc(int(n))

    _on_recompile = note_recompile  # internal alias


# --------------------------------------------------------------------------- #
# HBM high-watermark gauges
# --------------------------------------------------------------------------- #

#: memory_stats keys -> registry gauge names
_HBM_KEYS = {
    "bytes_in_use": "hbm/bytes_in_use",
    "peak_bytes_in_use": "hbm/peak_bytes",
    "bytes_limit": "hbm/bytes_limit",
    "largest_free_block_bytes": "hbm/largest_free_block_bytes",
}


def hbm_stats(device=None) -> Optional[Dict[str, int]]:
    """``memory_stats()`` of ``device`` (default: first local device), or
    None where the backend reports nothing (CPU simulator)."""
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
    except Exception:
        return None
    return stats or None


def update_hbm_gauges(registry, device=None) -> Optional[Dict[str, int]]:
    """Refresh the ``hbm/*`` gauges from ``memory_stats()``; returns the raw
    stats (None on reporting-free backends, gauges left unset)."""
    stats = hbm_stats(device)
    if not stats:
        return None
    for key, gauge_name in _HBM_KEYS.items():
        if key in stats:
            registry.gauge(gauge_name).set(stats[key])
    return stats


# --------------------------------------------------------------------------- #
# labeled xprof spans
# --------------------------------------------------------------------------- #

_xprof_enabled = True


def set_xprof_enabled(enabled: bool) -> None:
    """Process-wide toggle for phase annotations (on by default — a
    TraceAnnotation outside an active trace is nearly free)."""
    global _xprof_enabled
    _xprof_enabled = bool(enabled)


def xprof_span(name: str):
    """Context manager labeling the enclosed host dispatch in xprof traces
    (``jax.profiler.TraceAnnotation``); no-op when disabled or when the
    profiler module is unavailable."""
    if not _xprof_enabled:
        return contextlib.nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler-free builds
        return contextlib.nullcontext()

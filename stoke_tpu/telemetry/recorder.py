"""Crash flight recorder (ISSUE 3): a bounded ring of recent run state and
the post-mortem bundle writer.

A 3-day pod run that dies at step 40k must leave a usable corpse.  The
:class:`FlightRecorder` keeps the last N step events / sentinel rows /
anomaly firings in a host-side ring buffer (no IO on the hot path) and, on
demand — anomaly ``dump`` action, uncaught step-path exception,
SIGTERM/SIGUSR1, or watchdog trip — writes a **post-mortem bundle**
directory containing everything a human (or the bench supervisor) needs to
triage without re-running:

    <bundle_dir>/postmortem-<utc-ts>-<reason>/
        manifest.json       reason, wall time, pid, step, ring length
        ring.jsonl          the ring contents, oldest first
        config.json         the run's StokeStatus.to_dict() (when wired)
        mesh.json           mesh axes/shape, device kinds, process count
        environment.json    python/jax/numpy versions, JAX_*/XLA_* env,
                            argv, cwd
        registry.json       latest telemetry-registry snapshot (when wired)
        goodput.json        goodput/utilization summary (when attribution
                            is on — ISSUE 4)
        cost_cards.json     last analyzed per-program CostCards (ditto)
        trace.json          the structured-trace span ring as Perfetto-
                            loadable trace-event JSON (when tracing is
                            on — ISSUE 10)
        numerics.json       latest per-layer numerics view + non-finite
                            provenance history (when the numerics
                            observatory is on — ISSUE 12)
        stacks.txt          faulthandler all-thread stacks at dump time

Bundles are cheap (the ring is small) and atomic enough for crash paths:
files are written directly into a uniquely named directory, so a partial
bundle is visibly partial rather than corrupting a previous one.  When the
``STOKE_HEALTH_BUNDLE_FILE`` env var is set (scripts/_supervise.py sets it
for supervised workers), every dump also appends the bundle path there so
the supervisor can attach it to its ledger record.
"""

from __future__ import annotations

import faulthandler
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: env var a supervisor sets to receive bundle paths (one per line)
BUNDLE_FILE_ENV = "STOKE_HEALTH_BUNDLE_FILE"

#: signals that trigger a dump when ``HealthConfig.dump_signals`` is on
DUMP_SIGNALS = ("SIGTERM", "SIGUSR1")


def _json_safe(value: Any) -> Any:
    """Best-effort conversion to something json.dumps accepts (ring entries
    may carry numpy scalars; a dump must never fail on its payload)."""
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        pass
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if hasattr(value, "item"):  # numpy/jax scalar
        try:
            return value.item()
        except Exception:
            pass
    return repr(value)


class FlightRecorder:
    """Bounded ring buffer + post-mortem bundle writer.

    Thread-safe: the watchdog thread and signal handlers dump concurrently
    with the training thread recording.  Ring recording is append-only into
    a ``deque(maxlen=ring_size)`` — O(1), no IO, no device touches.
    """

    def __init__(
        self,
        bundle_dir: str,
        ring_size: int = 256,
        *,
        status_dict: Optional[Dict[str, Any]] = None,
        mesh_info: Optional[Dict[str, Any]] = None,
        snapshot_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        install_signal_handlers: bool = False,
        goodput_fn: Optional[Callable[[], Optional[Dict[str, Any]]]] = None,
        cost_cards_fn: Optional[Callable[[], Any]] = None,
        fleet_fn: Optional[Callable[[], Optional[Dict[str, Any]]]] = None,
        trace_fn: Optional[Callable[[], Any]] = None,
        numerics_fn: Optional[Callable[[], Optional[Dict[str, Any]]]] = None,
    ):
        self.bundle_dir = bundle_dir
        self._ring: "deque[dict]" = deque(maxlen=int(ring_size))
        # RLock, not Lock: the SIGTERM/SIGUSR1 dump handler runs ON the
        # main thread and may interrupt a frame that already holds this
        # lock (record() runs every step) — a plain Lock would deadlock
        # the process exactly on the crash path this module exists for
        self._lock = threading.RLock()
        self._status_dict = status_dict
        self._mesh_info = mesh_info
        self._snapshot_fn = snapshot_fn
        # ISSUE 4: utilization at time of death — the goodput summary and
        # the last analyzed CostCards join every bundle when wired
        self._goodput_fn = goodput_fn
        self._cost_cards_fn = cost_cards_fn
        # ISSUE 5: which host was slow at time of death — the latest
        # per-host fleet matrix + straggler verdict join every bundle
        self._fleet_fn = fleet_fn
        # ISSUE 10: what the host was doing at time of death — the span
        # ring as Perfetto-loadable trace.json joins every bundle
        self._trace_fn = trace_fn
        # ISSUE 12: which LAYER was bad at time of death — the per-group
        # numerics view + provenance history as numerics.json
        self._numerics_fn = numerics_fn
        self.dumps: List[str] = []
        self._prev_handlers: Dict[int, Any] = {}
        if install_signal_handlers:
            self._install_signal_handlers()

    # ------------------------------------------------------------------ #
    # ring
    # ------------------------------------------------------------------ #

    def record(self, kind: str, payload: Dict[str, Any]) -> None:
        """Append one entry to the ring (``kind`` tags the entry type:
        ``step_event`` / ``sentinels`` / ``anomaly`` / ``note``)."""
        entry = {"ts": time.time(), "kind": kind, **payload}
        with self._lock:
            self._ring.append(entry)

    def record_event(self, record: Dict[str, Any]) -> None:
        """Append a telemetry step event (the JSONL record verbatim)."""
        self.record("step_event", {"event": record})

    @property
    def ring(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------------ #
    # bundle dump
    # ------------------------------------------------------------------ #

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None) -> str:
        """Write a post-mortem bundle; returns the bundle directory path.

        Never raises: the dump runs on crash paths (signal handlers,
        watchdog thread, exception unwinding) where a secondary failure
        would mask the primary one — IO errors degrade to a partial bundle
        and a stderr note.
        """
        ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        safe_reason = "".join(
            c if (c.isalnum() or c in "-_") else "-" for c in reason
        )[:64]
        # pid in the name: multi-host runs may share bundle_dir on one
        # filesystem, and same-process concurrent dumpers (watchdog
        # thread, signal handler, exception unwind) are serialized by the
        # atomic exist_ok=False create below — a check-then-create would
        # let two same-second dumps overwrite each other's corpse
        base = os.path.join(
            self.bundle_dir,
            f"postmortem-{ts}-pid{os.getpid()}-{safe_reason}",
        )
        path = base
        suffix = 0
        while True:
            try:
                os.makedirs(path, exist_ok=False)
                break
            except FileExistsError:
                suffix += 1
                path = f"{base}.{suffix}"
            except OSError as e:
                sys.stderr.write(
                    f"Stoke -- flight recorder could not create bundle dir "
                    f"{path!r}: {e}\n"
                )
                return path
        ring = self.ring
        self._write_json(path, "manifest.json", {
            "reason": reason,
            "ts": time.time(),
            "pid": os.getpid(),
            "ring_entries": len(ring),
            **({"extra": _json_safe(extra)} if extra else {}),
        })
        self._write_jsonl(path, "ring.jsonl", ring)
        if self._status_dict is not None:
            self._write_json(path, "config.json", self._status_dict)
        if self._mesh_info is not None:
            self._write_json(path, "mesh.json", self._mesh_info)
        self._write_json(path, "environment.json", self._environment())
        if self._snapshot_fn is not None:
            try:
                self._write_json(path, "registry.json", self._snapshot_fn())
            except Exception:
                pass
        if self._goodput_fn is not None:
            try:
                goodput = self._goodput_fn()
                if goodput is not None:
                    self._write_json(path, "goodput.json", goodput)
            except Exception:
                pass
        if self._cost_cards_fn is not None:
            try:
                cards = self._cost_cards_fn()
                if cards:
                    self._write_json(path, "cost_cards.json", cards)
            except Exception:
                pass
        if self._fleet_fn is not None:
            try:
                fleet = self._fleet_fn()
                if fleet is not None:
                    self._write_json(path, "fleet.json", fleet)
            except Exception:
                pass
        if self._trace_fn is not None:
            try:
                events = self._trace_fn()
                if events:
                    self._write_json(
                        path, "trace.json", {"traceEvents": events}
                    )
            except Exception:
                pass
        if self._numerics_fn is not None:
            try:
                numerics = self._numerics_fn()
                if numerics is not None:
                    self._write_json(path, "numerics.json", numerics)
            except Exception:
                pass
        self._write_stacks(path)
        with self._lock:
            self.dumps.append(path)
        self._notify_supervisor(path)
        sys.stderr.write(
            f"Stoke -- health post-mortem bundle written: {path} "
            f"(reason: {reason})\n"
        )
        return path

    def _write_json(self, bundle: str, name: str, payload: Any) -> None:
        try:
            with open(os.path.join(bundle, name), "w") as f:
                json.dump(_json_safe(payload), f, indent=2, default=repr)
                f.write("\n")
        except OSError:
            pass

    def _write_jsonl(self, bundle: str, name: str, entries: List[dict]) -> None:
        try:
            with open(os.path.join(bundle, name), "w") as f:
                for entry in entries:
                    f.write(json.dumps(_json_safe(entry), default=repr))
                    f.write("\n")
        except OSError:
            pass

    def _write_stacks(self, bundle: str) -> None:
        """All-thread python stacks via faulthandler — the "where was
        everyone when it died" file, and the watchdog's main payload."""
        try:
            with open(os.path.join(bundle, "stacks.txt"), "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
        except (OSError, RuntimeError):
            pass

    @staticmethod
    def _environment() -> Dict[str, Any]:
        versions: Dict[str, Any] = {"python": sys.version}
        for mod in ("jax", "jaxlib", "numpy", "optax", "flax"):
            try:
                versions[mod] = __import__(mod).__version__
            except Exception:
                pass
        env = {
            k: v for k, v in os.environ.items()
            if k.startswith(("JAX_", "XLA_", "STOKE_", "TPU_", "LIBTPU"))
        }
        return {
            "versions": versions,
            "env": env,
            "argv": list(sys.argv),
            "cwd": os.getcwd(),
        }

    @staticmethod
    def _notify_supervisor(bundle_path: str) -> None:
        target = os.environ.get(BUNDLE_FILE_ENV)
        if not target:
            return
        try:
            with open(target, "a") as f:
                f.write(bundle_path + "\n")
        except OSError:
            pass

    # ------------------------------------------------------------------ #
    # signals
    # ------------------------------------------------------------------ #

    def _install_signal_handlers(self) -> None:
        """Dump a bundle on SIGTERM/SIGUSR1, then chain to the previous
        handler (so SIGTERM still terminates).  Signal handlers can only be
        installed from the main thread; elsewhere (e.g. a test worker) this
        silently skips — the other dump triggers still work."""
        for name in DUMP_SIGNALS:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                prev = signal.signal(signum, self._on_signal)
            except (ValueError, OSError):  # non-main thread / unsupported
                return
            self._prev_handlers[signum] = prev

    def _on_signal(self, signum, frame) -> None:
        self.dump(f"signal-{signal.Signals(signum).name}")
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL and signum == signal.SIGTERM:
            # default SIGTERM disposition is termination; honor it
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def uninstall_signal_handlers(self) -> None:
        """Restore the previous handlers (test hygiene / facade close)."""
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

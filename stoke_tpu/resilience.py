"""Pod-scale resilience (ISSUE 7 tentpole): preemption-aware save/resume,
supervised-restart primitives, and the deterministic fault-injection harness.

The observability vertical (PRs 1/3/4/5) can *name* the slow or dying host;
this module is what finally *acts* on it.  A SIGTERM used to produce a
flight-recorder bundle and a dead run that lost every step since the last
manual save; now the detect→save→restart→resume loop closes:

1. **Preemption-aware save** — :class:`ResilienceMonitor` installs handlers
   for the preemption-notice signals (SIGTERM by default).  The handler only
   sets a flag; the facade checks it at every optimizer-step boundary, so the
   in-flight step always finishes, the in-flight async checkpoint threads
   drain (``io_ops.wait_for_saves``), and an **emergency checkpoint** —
   tagged with step counters, rng, loss-EMA, and the error-feedback residual
   state — is written synchronously before the process exits with
   :data:`PREEMPTION_EXIT_CODE` (distinct from the health watchdog's 113, so
   supervisors can classify "drained cleanly" vs "hung and self-killed").

2. **Auto-resume** — every checkpoint written under a ``ResilienceConfig``
   carries a ``manifest.json`` of per-file sha256 digests.
   :func:`find_latest_valid_checkpoint` walks tags newest-first, verifies
   each against its manifest, **quarantines** (renames, never deletes)
   corrupt or partially-written tags, and returns the newest valid one —
   ``Stoke.resume()`` then restores state + step counters so a restarted run
   loses at most one save window.

3. **Supervised restarts** — :class:`RestartBackoff` (exponential backoff
   with deterministic-seedable jitter and a restart budget) and
   :func:`classify_exit` (resumable-vs-fatal exit-code classification) are
   the jax-free primitives ``scripts/run_resilient.py`` builds its bounded
   restart loop from.

4. **Fault injection** — a deterministic chaos harness
   (``STOKE_CHAOS`` env var or ``ResilienceConfig.chaos``):
   ``kill_at_step=K`` (graceful SIGTERM, hard SIGKILL, or an exception),
   ``corrupt_save=N`` (flip bytes in the N-th checkpoint written),
   ``wedge_at_step=K,wedge_s=S`` (stall a dispatch so the hang watchdog has
   something to catch).  The tests use it to prove the whole loop
   end-to-end — a run killed at an arbitrary step resumes bit-identically.

This module imports no jax at module scope: the restart supervisor
(``scripts/run_resilient.py``) loads it by file, exactly like the
``scripts/autotune.py`` parent loads the search module, so the supervising
process can never wedge on a dead TPU tunnel.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import random
import signal
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: exit code of a preempted worker that drained and saved cleanly — kept
#: distinct from the health watchdog's 113 ("hung and self-terminated") so
#: supervisors can tell a graceful drain from a wedge.  scripts/_supervise.py
#: keeps a synced copy (it must never import jax-importing packages).
PREEMPTION_EXIT_CODE = 114

#: the health hang-watchdog's exit code (stoke_tpu/telemetry/health.py
#: WATCHDOG_EXIT_CODE — duplicated here so this module stays import-light)
_WATCHDOG_EXIT_CODE = 113

#: exit codes a supervisor restarts by default: watchdog kill (the run hung
#: on a wedged collective — a fresh process usually un-wedges it) and the
#: graceful preemption drain above
RESUMABLE_EXIT_CODES: Tuple[int, ...] = (
    _WATCHDOG_EXIT_CODE,
    PREEMPTION_EXIT_CODE,
)

#: env var the supervisor sets so a restarted worker knows its attempt
#: number (surfaces as the ``resilience/restarts`` gauge / JSONL column)
RESTART_ATTEMPT_ENV = "STOKE_RESTART_ATTEMPT"

#: env var carrying the chaos spec (``ResilienceConfig.chaos`` overrides)
CHAOS_ENV = "STOKE_CHAOS"

#: manifest file name inside a checkpoint tag directory
MANIFEST_NAME = "manifest.json"

#: quarantine subdirectory created next to the tags it quarantines
QUARANTINE_DIRNAME = "quarantine"


class PreemptedError(BaseException):
    """Raised at an optimizer-step boundary after the emergency checkpoint
    was written, when ``ResilienceConfig.exit_on_preempt=False`` (in-process
    tests / smoke drivers that want to resume without a process restart).

    Subclasses ``BaseException`` — like ``SystemExit``, it means "this
    process is leaving", and must not be swallowed by ``except Exception``
    error handling (or dumped as a crash by the health monitor's
    exception-path recorder)."""

    def __init__(self, step: int, tag_dir: Optional[str], exit_code: int):
        self.step = int(step)
        self.tag_dir = tag_dir
        self.exit_code = int(exit_code)
        super().__init__(
            f"Stoke -- preempted at optimizer step {step}; emergency "
            f"checkpoint: {tag_dir or '<save failed>'} "
            f"(resumable exit code {exit_code})"
        )


class ChaosError(RuntimeError):
    """Raised by the ``kill_at_step`` injector in ``mode=exception`` — a
    deterministic stand-in for an uncaught training-loop crash."""


# --------------------------------------------------------------------------- #
# exit-code classification (the supervisor's restart decision)
# --------------------------------------------------------------------------- #


def classify_exit(
    code: int, extra_resumable: Sequence[int] = ()
) -> str:
    """``"ok"`` / ``"resumable"`` / ``"fatal"`` for one worker exit code.

    Resumable: the distinct self-reported codes (watchdog 113, preemption
    114, plus ``extra_resumable``) and signal deaths — negative returncodes
    from ``subprocess`` or the shell convention ``128+signum`` reported by
    wrapper launchers (SIGKILL/SIGTERM are how preempted VMs and OOM
    killers end a process).  Everything else — including a generic python
    crash (exit 1, e.g. a status-validation error) — is fatal: restarting a
    deterministic bug burns the restart budget without ever progressing.
    """
    if code == 0:
        return "ok"
    if code in RESUMABLE_EXIT_CODES or code in tuple(extra_resumable):
        return "resumable"
    if code < 0:  # killed by a signal (host-level disruption)
        return "resumable"
    if 128 < code <= 128 + 64:
        # shell convention for signal deaths (128+signum): what a wrapper
        # launcher — including run_resilient's own main() — reports when
        # the real worker died to SIGKILL/SIGTERM.  Same verdict as the
        # raw negative returncode above.
        return "resumable"
    return "fatal"


# --------------------------------------------------------------------------- #
# restart backoff (exponential + jitter + budget; no sleeping in here)
# --------------------------------------------------------------------------- #


class RestartBackoff:
    """Bounded exponential backoff with jitter for the restart loop.

    Pure scheduling arithmetic: :meth:`next_delay` returns how long the
    caller should sleep before the next restart, or ``None`` once the
    restart budget is exhausted.  It never sleeps itself and takes an
    injectable ``rng`` (``random.Random``), so tests run it deterministic
    and instantaneous.
    """

    def __init__(
        self,
        base_s: float = 1.0,
        factor: float = 2.0,
        max_s: float = 60.0,
        jitter_frac: float = 0.5,
        max_restarts: int = 8,
        rng: Optional[random.Random] = None,
    ):
        if base_s < 0 or factor < 1 or max_s < 0 or jitter_frac < 0:
            raise ValueError(
                "RestartBackoff needs base_s/max_s/jitter_frac >= 0 and "
                "factor >= 1"
            )
        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_s = float(max_s)
        self.jitter_frac = float(jitter_frac)
        self.max_restarts = int(max_restarts)
        self.restarts_used = 0
        self._rng = rng if rng is not None else random.Random()

    @property
    def exhausted(self) -> bool:
        return self.restarts_used >= self.max_restarts

    def next_delay(self) -> Optional[float]:
        """Delay (seconds) before the next restart, or None when the budget
        is spent.  Jitter is additive-uniform in ``[0, jitter_frac * delay]``
        — a fleet of preempted workers must not restart in lockstep."""
        if self.exhausted:
            return None
        n = self.restarts_used
        self.restarts_used += 1
        delay = min(self.max_s, self.base_s * (self.factor ** n))
        return delay + delay * self.jitter_frac * self._rng.random()


# --------------------------------------------------------------------------- #
# checkpoint manifests: per-file integrity digests
# --------------------------------------------------------------------------- #


def _file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _walk_files(tag_dir: str) -> List[str]:
    """Relative paths of every regular file under ``tag_dir`` (sorted; the
    manifest itself excluded).  ``*.tmp`` names are excluded too: every
    writer in this repo lands files atomically via tmp+``os.replace``, so
    a ``.tmp`` is by definition an in-flight write — digesting one (e.g.
    another rank's staged shard mid-write, ISSUE 14) would bake a
    transient name into the manifest and permanently fail verification of
    a healthy checkpoint once the rename retires it."""
    out = []
    for root, _dirs, files in os.walk(tag_dir):
        for name in files:
            if name.endswith(".tmp"):
                continue
            rel = os.path.relpath(os.path.join(root, name), tag_dir)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def write_manifest(tag_dir: str, extra: Optional[Dict[str, Any]] = None) -> str:
    """Write ``manifest.json`` into a completed checkpoint tag: per-file
    sha256 + byte counts over every file currently in the tag.  Written
    LAST (after ``meta.json``), so a tag with a manifest is a tag whose
    write finished — resume-side validation treats digest mismatch AND
    missing listed files as corruption.  Returns the manifest path.

    Digesting re-reads the tag from disk (roughly doubling the save's
    read IO) — a deliberate trade-off even on the emergency path: the
    digest over the bytes that LANDED is what the quarantine guarantee
    rests on, and a grace-window kill mid-hash just leaves a manifest-less
    tag that resume treats as the partial write it is."""
    files = {}
    for rel in _walk_files(tag_dir):
        full = os.path.join(tag_dir, rel)
        files[rel] = {
            "sha256": _file_sha256(full),
            "bytes": os.path.getsize(full),
        }
    manifest = {
        "version": 1,
        "written_ts": time.time(),
        "files": files,
        **(extra or {}),
    }
    path = os.path.join(tag_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)  # atomic: a torn manifest must not look valid
    return path


def verify_checkpoint(
    tag_dir: str, require_manifest: bool = False
) -> Tuple[bool, str]:
    """``(ok, reason)`` for one checkpoint tag directory.

    Validation ladder:
      1. ``meta.json`` must exist and parse (async saves write it last — a
         meta-less tag is a partial write by construction).
      2. A staged (offload) layout must be COMPLETE: meta records how many
         processes wrote shard files for which state keys (ISSUE 14) —
         every process's writer runs independently, so a hard kill can
         strand meta.json ahead of a lagging rank's payload; the missing
         rank file is the half-staged signature this check catches.
      3. With a manifest: every listed file must exist with a matching
         sha256 digest (bit rot, truncation, chaos-injected corruption).
      4. Without a manifest: valid iff ``require_manifest`` is False
         (pre-resilience checkpoints stay loadable).
    """
    meta_path = os.path.join(tag_dir, "meta.json")
    if not os.path.isdir(tag_dir):
        return False, "not a directory"
    if not os.path.exists(meta_path):
        return False, "missing meta.json (partial write)"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable meta.json ({e})"
    staged = meta.get("staged") if isinstance(meta, dict) else None
    if staged:
        try:
            processes = int(staged["processes"])
            keys = list(staged["keys"])
        except (KeyError, TypeError, ValueError):
            return False, "malformed staged marker in meta.json"
        for key in keys:
            for r in range(max(processes, 1)):
                for suffix in ("npz", "json"):
                    rel = f"{key}.staged.rank{r}.{suffix}"
                    if not os.path.exists(os.path.join(tag_dir, rel)):
                        return False, (
                            f"staged payload incomplete: missing {rel}"
                        )
    manifest_path = os.path.join(tag_dir, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        if require_manifest:
            return False, "missing manifest.json"
        return True, "ok (no manifest)"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        listed = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, f"unreadable manifest.json ({e})"
    for rel, entry in listed.items():
        full = os.path.join(tag_dir, rel)
        if not os.path.exists(full):
            return False, f"missing file {rel}"
        try:
            if os.path.getsize(full) != entry.get("bytes"):
                return False, f"size mismatch in {rel}"
            if _file_sha256(full) != entry.get("sha256"):
                return False, f"digest mismatch in {rel}"
        except OSError as e:
            return False, f"unreadable file {rel} ({e})"
    return True, "ok"


def read_manifest(tag_dir: str) -> Optional[Dict[str, Any]]:
    """The parsed ``manifest.json`` of a checkpoint tag, or None when the
    tag carries none / it is unreadable.  The manifest is where ISSUE 14's
    topology/sharding descriptor lives (``manifest["topology"]``) — what
    elastic resume reads to re-shard and to reject incompatible saves."""
    try:
        with open(os.path.join(tag_dir, MANIFEST_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def quarantine_checkpoint(tag_dir: str, reason: str = "") -> Optional[str]:
    """Move a corrupt tag into ``<root>/quarantine/<tag>-<ts>`` — NEVER
    delete it (the bytes are evidence; an operator may hand-recover a
    shard).  Returns the new path, or None when the rename itself failed
    (cross-device, permissions — the tag is then left in place and the
    caller must skip it by step, not by absence)."""
    root = os.path.dirname(os.path.abspath(tag_dir))
    qdir = os.path.join(root, QUARANTINE_DIRNAME)
    ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    dest = os.path.join(qdir, f"{os.path.basename(tag_dir)}-{ts}")
    suffix = 0
    try:
        os.makedirs(qdir, exist_ok=True)
        while os.path.exists(dest):
            suffix += 1
            dest = os.path.join(
                qdir, f"{os.path.basename(tag_dir)}-{ts}.{suffix}"
            )
        os.rename(tag_dir, dest)
    except OSError as e:
        sys.stderr.write(
            f"Stoke -- could not quarantine corrupt checkpoint "
            f"{tag_dir!r}: {e}\n"
        )
        return None
    try:
        with open(os.path.join(dest, "QUARANTINED.json"), "w") as f:
            json.dump({"reason": reason, "ts": time.time(),
                       "original": tag_dir}, f, indent=2)
    except OSError:
        pass
    return dest


# tag name scheme shared with io_ops (duplicated regex so this module stays
# importable without jax; io_ops._TAG_RE is the authority and a test pins
# the two in sync)
import re as _re

_TAG_RE = _re.compile(r"^stoke-(?P<name>.+)-backward-step-(?P<step>\d+)$")


def list_checkpoints(root: str, name: Optional[str]) -> List[Dict[str, Any]]:
    """All checkpoint tags under ``root`` (scoped to ``name`` when given),
    newest first."""
    out = []
    try:
        entries = os.listdir(root)
    except OSError:
        return out
    for entry in entries:
        m = _TAG_RE.match(entry)
        if m and (name is None or m.group("name") == name):
            out.append({
                "root": root,
                "tag": entry,
                "tag_dir": os.path.join(root, entry),
                "name": m.group("name"),
                "step": int(m.group("step")),
            })
    out.sort(key=lambda c: c["step"], reverse=True)
    return out


def find_latest_valid_checkpoint(
    roots: Sequence[Tuple[str, Optional[str]]],
    verify: bool = True,
    quarantine: bool = True,
    require_manifest: bool = False,
    on_quarantine: Optional[Callable[[str, Optional[str], str], None]] = None,
    validate_fn: Optional[Callable[[str], Tuple[bool, str]]] = None,
) -> Optional[Dict[str, Any]]:
    """Newest VALID checkpoint across ``roots`` (``(root, name)`` pairs;
    ``name=None`` matches any run name).

    Candidates are ordered by backward step across all roots; each is
    validated (:func:`verify_checkpoint`) before being trusted.  An invalid
    candidate is quarantined (renamed under ``<root>/quarantine/``, never
    deleted) and discovery falls back to the next-newest tag — the
    corrupted-latest-checkpoint acceptance path.  ``on_quarantine(tag_dir,
    quarantined_path, reason)`` is invoked per quarantined tag (telemetry
    counters, operator warnings).

    ``validate_fn(tag_dir) -> (ok, reason)`` runs AFTER the integrity
    checks pass (ISSUE 14): the facade's topology-descriptor compatibility
    check rides here, so a digest-clean checkpoint whose descriptor cannot
    serve the current run (different model) is quarantined with the remedy
    named instead of crashing the restore mid-flight.
    """
    candidates: List[Dict[str, Any]] = []
    for root, name in roots:
        if root:
            candidates.extend(list_checkpoints(root, name))
    candidates.sort(key=lambda c: c["step"], reverse=True)
    for cand in candidates:
        if not verify:
            # fast path for non-writer ranks after the writer already
            # quarantined the bad tags (multi-host resume protocol)
            if os.path.exists(os.path.join(cand["tag_dir"], "meta.json")):
                return cand
            continue
        ok, reason = verify_checkpoint(
            cand["tag_dir"], require_manifest=require_manifest
        )
        if ok and validate_fn is not None:
            try:
                ok, reason = validate_fn(cand["tag_dir"])
            except Exception as e:  # a broken validator must not resume
                ok, reason = False, f"descriptor validation failed ({e})"
        if ok:
            return cand
        dest = (
            quarantine_checkpoint(cand["tag_dir"], reason)
            if quarantine
            else None
        )
        if on_quarantine is not None:
            try:
                on_quarantine(cand["tag_dir"], dest, reason)
            except Exception:
                pass
    return None


# --------------------------------------------------------------------------- #
# chaos harness: deterministic fault injection
# --------------------------------------------------------------------------- #

#: kill modes ``kill_at_step`` understands
CHAOS_KILL_MODES: Tuple[str, ...] = ("sigterm", "sigkill", "exception")


@dataclass
class ChaosSpec:
    """Parsed fault-injection plan (``STOKE_CHAOS`` env /
    ``ResilienceConfig.chaos``).

    Spec grammar: comma-separated ``key=value`` pairs —
    ``kill_at_step=K`` (+ optional ``kill_mode=sigterm|sigkill|exception``),
    ``corrupt_save=N`` (corrupt the N-th checkpoint this process writes,
    1-based), ``wedge_at_step=K`` (+ ``wedge_s=S`` seconds) stalling the
    dispatch AFTER step K completes, ``kill_during_save=N`` (SIGKILL from
    INSIDE the N-th async save's background writer, after the payload and
    before ``meta.json`` — the half-staged death the manifest validator
    must detect and quarantine, ISSUE 14).  Example::

        STOKE_CHAOS="kill_at_step=5,kill_mode=sigterm"
    """

    kill_at_step: Optional[int] = None
    kill_mode: str = "sigterm"
    corrupt_save: Optional[int] = None
    wedge_at_step: Optional[int] = None
    wedge_s: float = 1.0
    kill_during_save: Optional[int] = None

    @property
    def active(self) -> bool:
        return (
            self.kill_at_step is not None
            or self.corrupt_save is not None
            or self.wedge_at_step is not None
            or self.kill_during_save is not None
        )


def parse_chaos(spec: Optional[str]) -> Optional[ChaosSpec]:
    """``"kill_at_step=5,kill_mode=sigterm"`` → :class:`ChaosSpec`; None /
    empty → None.  Unknown keys and malformed values raise ``ValueError``
    (a typo'd chaos plan silently injecting nothing would fake a green
    resilience test)."""
    if not spec or not spec.strip():
        return None
    fields = {f.name: f for f in dataclasses.fields(ChaosSpec)}
    out = ChaosSpec()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"Stoke -- chaos spec entry {part!r} is not key=value"
            )
        key, _, value = part.partition("=")
        key = key.strip()
        value = value.strip()
        if key not in fields:
            raise ValueError(
                f"Stoke -- unknown chaos key {key!r}; valid: "
                f"{sorted(fields)}"
            )
        if key == "kill_mode":
            if value not in CHAOS_KILL_MODES:
                raise ValueError(
                    f"Stoke -- chaos kill_mode {value!r} unknown; valid: "
                    f"{list(CHAOS_KILL_MODES)}"
                )
            out.kill_mode = value
        elif key == "wedge_s":
            out.wedge_s = float(value)
        else:
            try:
                setattr(out, key, int(value))
            except ValueError as e:
                raise ValueError(
                    f"Stoke -- chaos {key} needs an integer, got {value!r}"
                ) from e
    # an armed injector that can never fire is a fake-green chaos run —
    # the same contract as unknown keys: loud, never a silent no-op
    for key in ("kill_at_step", "corrupt_save", "wedge_at_step",
                "kill_during_save"):
        v = getattr(out, key)
        if v is not None and v < 1:
            raise ValueError(
                f"Stoke -- chaos {key} must be >= 1 (1-based), got {v}"
            )
    if out.wedge_s < 0:
        # 0 is legal: the wedge still fires, it just doesn't stall —
        # the tests use it to exercise injector logic without real sleeps
        raise ValueError(
            f"Stoke -- chaos wedge_s must be >= 0, got {out.wedge_s}"
        )
    return out


class ChaosInjector:
    """Runs one :class:`ChaosSpec` against a live run, deterministically.

    The facade drives it from the optimizer-step boundary
    (:meth:`on_step`), the checkpoint writer from :meth:`note_saved`, and
    the engine from its per-dispatch hook (:meth:`on_dispatch` — see
    ``StepEngine._aot_call``).  ``kill_at_step`` fires only when THIS
    process itself crossed the step (a resumed process whose counter starts
    past K never re-fires, so a supervised restart makes forward progress).
    """

    def __init__(self, spec: Optional[ChaosSpec]):
        self.spec = spec
        self._saves_seen = 0
        self._async_payloads_seen = 0
        self._completed_step: Optional[int] = None
        self._resume_anchor: Optional[int] = None
        self._wedged = False
        self.corrupted: List[str] = []

    @property
    def active(self) -> bool:
        return self.spec is not None and self.spec.active

    def note_resumed(self, step: int) -> None:
        """Anchor the in-process step window after a resume (steps loaded
        from a checkpoint were not executed by this process) — both the
        kill and the wedge injector treat restored steps as already-fired."""
        self._completed_step = int(step)
        self._resume_anchor = int(step)

    def on_step(self, step: int, window: int = 1) -> None:
        """Optimizer-step-boundary hook: ``step`` is the counter AFTER the
        just-completed step(s); ``window`` how many steps the dispatch
        covered.  Fires ``kill_at_step=K`` when K lies inside the window
        this process just executed."""
        self._completed_step = int(step)
        if not self.active:
            return
        k = self.spec.kill_at_step
        if k is None or not (step - window < k <= step):
            return
        mode = self.spec.kill_mode
        sys.stderr.write(
            f"Stoke -- CHAOS: kill_at_step={k} firing at step {step} "
            f"(mode={mode})\n"
        )
        sys.stderr.flush()
        if mode == "exception":
            raise ChaosError(
                f"Stoke -- chaos-injected crash at optimizer step {step}"
            )
        sig = signal.SIGTERM if mode == "sigterm" else signal.SIGKILL
        os.kill(os.getpid(), sig)

    def on_dispatch(self, program: str) -> None:
        """Engine pre-dispatch hook: stalls the first dispatch after
        ``wedge_at_step`` completed steps for ``wedge_s`` seconds — the
        deterministic stand-in for a wedged collective the hang watchdog
        exists to catch."""
        if not self.active or self._wedged:
            return
        k = self.spec.wedge_at_step
        if k is None or self._completed_step is None:
            return
        if self._resume_anchor is not None and self._resume_anchor >= k:
            # a resumed process that restored step >= K already wedged in a
            # previous life; re-arming (the per-process _wedged flag resets
            # each restart) would wedge EVERY supervised attempt until the
            # restart budget burned out — forward progress requires the
            # wedge step to have been executed by THIS process
            return
        if self._completed_step >= k:
            self._wedged = True
            sys.stderr.write(
                f"Stoke -- CHAOS: wedging dispatch of {program!r} for "
                f"{self.spec.wedge_s}s after step {self._completed_step}\n"
            )
            time.sleep(self.spec.wedge_s)

    def on_async_payload(self, tag_dir: str) -> None:
        """Background-writer hook (``io_ops`` calls it between the payload
        write and ``meta.json``): ``kill_during_save=N`` SIGKILLs the
        process from inside the N-th async save — payload files on disk,
        no loadable marker, no manifest.  The resulting tag MUST read as a
        partial write to the resume-time validator and be quarantined,
        never resumed from (the ISSUE 14 chaos acceptance)."""
        self._async_payloads_seen += 1
        if not self.active:
            return
        if self.spec.kill_during_save == self._async_payloads_seen:
            sys.stderr.write(
                f"Stoke -- CHAOS: kill_during_save="
                f"{self.spec.kill_during_save} SIGKILLing mid-save of "
                f"{tag_dir}\n"
            )
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    def note_saved(self, tag_dir: str) -> None:
        """Checkpoint-writer hook: corrupts the bytes of the N-th save this
        process performed (``corrupt_save=N``, 1-based) — the quarantine
        path's deterministic trigger."""
        self._saves_seen += 1
        if not self.active:
            return
        if self.spec.corrupt_save == self._saves_seen:
            path = corrupt_checkpoint(tag_dir)
            if path:
                self.corrupted.append(path)


def corrupt_checkpoint(tag_dir: str, n_bytes: int = 64) -> Optional[str]:
    """Flip ``n_bytes`` in the middle of the largest payload file of a tag
    (never ``meta.json``/``manifest.json`` — the point is bit rot the
    digests catch, not an obviously-absent tag).  Returns the corrupted
    file path, or None when the tag has no payload files."""
    best = None
    for root, _dirs, files in os.walk(tag_dir):
        for name in files:
            if name in ("meta.json", MANIFEST_NAME):
                continue
            full = os.path.join(root, name)
            size = os.path.getsize(full)
            if best is None or size > best[0]:
                best = (size, full)
    if best is None or best[0] == 0:
        return None
    size, path = best
    offset = size // 2
    with open(path, "r+b") as f:
        f.seek(offset)
        chunk = f.read(n_bytes)
        f.seek(offset)
        f.write(bytes((~b) & 0xFF for b in chunk))
    sys.stderr.write(
        f"Stoke -- CHAOS: corrupted {len(chunk)} bytes of {path}\n"
    )
    return path


# --------------------------------------------------------------------------- #
# the monitor (facade-owned; host-side only — never touches step programs)
# --------------------------------------------------------------------------- #

# per-signal install order of LIVE monitors, oldest first — lets overlapping
# monitor lifetimes (resume-while-preempted-run-open) uninstall in any order
# without stranding SIGTERM on a closed monitor's handler
_SIGNAL_STACKS: Dict[int, List[Tuple["ResilienceMonitor", Any]]] = {}


class ResilienceMonitor:
    """Owns the preemption flag, the chaos injector, and the
    ``resilience/*`` counters.  Installed by the facade when a
    ``ResilienceConfig`` is supplied; entirely host-side — the compiled
    step programs are bit-identical with or without it (acceptance-tested
    like every subsystem since PR 1).

    The signal handler ONLY sets a flag (no IO, no locks, no registry —
    deadlock-safe by construction); the facade checks
    :attr:`preempt_requested` at each optimizer-step boundary and runs the
    drain→save→exit sequence there, on the training thread, with the step
    complete and the engine state consistent.
    """

    def __init__(self, cfg, registry, recorder=None):
        self.cfg = cfg
        self.registry = registry
        self.recorder = recorder
        spec = parse_chaos(
            cfg.chaos if cfg.chaos is not None
            else os.environ.get(CHAOS_ENV)
        )
        self.chaos = ChaosInjector(spec)
        self._preempted = threading.Event()
        self._preempt_signal: Optional[str] = None
        self._prev_handlers: Dict[int, Any] = {}
        self.restarts = int(os.environ.get(RESTART_ATTEMPT_ENV, "0") or 0)
        self.resumed_step: Optional[int] = None
        self.lost_steps: Optional[int] = None
        self.emergency_tag: Optional[str] = None
        self.elastic_resume: Optional[Dict[str, Any]] = None
        # pre-register so scrapes carry zeros before the first event
        registry.counter(
            "resilience/preemptions_total",
            help="preemption notices received (signal or explicit request)",
        )
        registry.counter(
            "resilience/emergency_saves_total",
            help="emergency checkpoints written on preemption",
        )
        registry.counter(
            "resilience/quarantined_ckpts_total",
            help="corrupt/partial checkpoint tags quarantined at resume",
        )
        registry.counter(
            "resilience/elastic_resumes_total",
            help="resumes that re-sharded state saved on a DIFFERENT "
            "topology (mesh/process-count/tier change)",
        )
        registry.gauge(
            "resilience/restarts",
            help="supervisor restart attempt this process is (0 = first run)",
        ).set(float(self.restarts))
        self._install_signal_handlers()

    # ------------------------------ signals ----------------------------- #

    def _install_signal_handlers(self) -> None:
        """Claim the preemption signals.  Deliberately does NOT chain to
        previous handlers: with resilience on, SIGTERM means "drain and
        save", and a chained default/recorder handler would terminate (or
        dump) mid-step — the exact data loss this subsystem removes.  Main
        thread only; elsewhere (test workers) the explicit
        :meth:`request_preemption` path still works."""
        for name in self.cfg.preempt_signals:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                prev = signal.signal(signum, self._on_signal)
            except (ValueError, OSError):
                # non-main thread / uncatchable signal — keep trying the
                # REST of the list: one bad name must not silently strip
                # the SIGTERM handler the whole subsystem depends on
                continue
            self._prev_handlers[signum] = prev
            _SIGNAL_STACKS.setdefault(signum, []).append((self, prev))

    def _on_signal(self, signum, frame) -> None:
        # flag only — every heavier action (drain, save, bundle, exit)
        # happens at the next step boundary on the training thread
        self._preempt_signal = signal.Signals(signum).name
        self._preempted.set()

    def uninstall_signal_handlers(self) -> None:
        # Monitors can overlap (resume constructs a new Stoke while the
        # preempted one is still open — telemetry_smoke's own pattern), and
        # they may close in either order.  A per-signal stack keeps the
        # handler chain honest: a middle removal hands its saved `prev` up
        # to the monitor above (so the final close restores the ORIGINAL
        # handler, not a closed monitor's flag-setter), and a top removal
        # only touches the live handler if it is still ours.
        for signum in list(self._prev_handlers):
            stack = _SIGNAL_STACKS.get(signum, [])
            idx = next(
                (i for i, (m, _) in enumerate(stack) if m is self), None
            )
            if idx is None:
                continue
            _, prev = stack.pop(idx)
            if idx < len(stack):
                # middle removal: the monitor above inherits our prev
                above, _ = stack[idx]
                stack[idx] = (above, prev)
                continue
            try:
                if signal.getsignal(signum) == self._on_signal:
                    signal.signal(signum, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    # ------------------------------ surface ----------------------------- #

    @property
    def preempt_requested(self) -> bool:
        return self._preempted.is_set()

    @property
    def preempt_signal(self) -> Optional[str]:
        return self._preempt_signal

    def request_preemption(self, reason: str = "manual") -> None:
        """Programmatic preemption notice (tests, cluster agents that
        learn about preemption out-of-band, e.g. a metadata-server poll)."""
        self._preempt_signal = reason
        self._preempted.set()

    def note_preemption_honored(self) -> None:
        """Counted at the boundary, not in the handler (the registry takes
        locks; a signal handler must not)."""
        self.registry.counter("resilience/preemptions_total").inc()

    def note_emergency_saved(self, tag_dir: str) -> None:
        self.emergency_tag = tag_dir
        self.registry.counter("resilience/emergency_saves_total").inc()

    def note_quarantined(self, tag_dir: str, dest: Optional[str],
                         reason: str) -> None:
        self.registry.counter("resilience/quarantined_ckpts_total").inc()

    def note_elastic_resume(
        self,
        saved: Optional[Dict[str, Any]],
        current: Optional[Dict[str, Any]],
    ) -> None:
        """Record one topology-elastic resume (ISSUE 14): the restored
        checkpoint was saved under a different (mesh, process count, tier,
        shard_updates) than this run — params/opt/EF state were re-sharded
        onto the new layout at load."""
        self.elastic_resume = {"from": saved, "to": current}
        self.registry.counter("resilience/elastic_resumes_total").inc()

    def note_resumed(self, step: int,
                     lost_steps: Optional[int] = None) -> None:
        """Record where this run resumed from: ``resumed_step`` gauges the
        restored optimizer step; ``lost_steps`` the optimizer steps a
        newer-but-unusable tag had recorded beyond the resumed one (0 for
        a clean emergency save — it runs AT the boundary; >0 when resume
        fell back past a quarantined tag)."""
        self.resumed_step = int(step)
        self.registry.gauge(
            "resilience/resumed_step",
            help="optimizer step this run resumed from",
        ).set(float(step))
        if lost_steps is not None:
            self.lost_steps = max(0, int(lost_steps))
            self.registry.gauge(
                "resilience/lost_steps",
                help="steps the preempted run lost beyond the resumed tag",
            ).set(float(self.lost_steps))
        self.chaos.note_resumed(step)

    def exit_or_raise(self, step: int, tag_dir: Optional[str]) -> None:
        """Leave the process with the resumable exit code (the supervisor
        contract), or raise :class:`PreemptedError` for in-process drivers.
        ``os._exit``: a preempted pod host is seconds from disappearing —
        interpreter teardown (atexit barriers, orbax thread joins) can hang
        longer than the grace window, and everything durable was already
        flushed by the caller."""
        if not self.cfg.exit_on_preempt:
            self._preempted.clear()  # in-process driver may resume + retry
            raise PreemptedError(step, tag_dir, self.cfg.exit_code)
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(self.cfg.exit_code)

    def event_fields(self) -> Dict[str, Optional[float]]:
        """The ``resilience/*`` JSONL step-event columns (PR 1 registry
        contract: absent config → keys never appear; present → counters
        ride every record)."""
        def _val(name):
            inst = self.registry.get(name)
            return None if inst is None else float(inst.value)

        return {
            "resilience/preemptions": _val("resilience/preemptions_total"),
            "resilience/emergency_saves": _val(
                "resilience/emergency_saves_total"
            ),
            "resilience/quarantined": _val(
                "resilience/quarantined_ckpts_total"
            ),
            "resilience/restarts": float(self.restarts),
            "resilience/resumed_step": (
                None if self.resumed_step is None
                else float(self.resumed_step)
            ),
            "resilience/lost_steps": (
                None if self.lost_steps is None else float(self.lost_steps)
            ),
            "resilience/elastic_resumes": _val(
                "resilience/elastic_resumes_total"
            ),
        }

    def summary(self) -> Dict[str, Any]:
        """End-of-run resilience accounting (the ``Stoke.resilience_summary``
        surface; the bench ``--resilience`` arm's column source)."""
        def _int(name):
            inst = self.registry.get(name)
            return 0 if inst is None else int(inst.value)

        return {
            "restarts": self.restarts,
            "preemptions": _int("resilience/preemptions_total"),
            "emergency_saves": _int("resilience/emergency_saves_total"),
            "quarantined_ckpts": _int("resilience/quarantined_ckpts_total"),
            "resumed_step": self.resumed_step,
            "lost_steps": self.lost_steps,
            "emergency_tag": self.emergency_tag,
            "elastic_resumes": _int("resilience/elastic_resumes_total"),
            "elastic_resume": self.elastic_resume,
            "chaos_active": self.chaos.active,
        }

    def close(self) -> None:
        self.uninstall_signal_handlers()

"""Disk tier for optimizer-state offload (NVMe-offload equivalent).

Reference: DeepSpeed's ZeRO-Infinity NVMe offload — ``DeepspeedAIOConfig``
(reference configs.py:192-221) + offload device "nvme"
(configs.py:309-372, wired at distributed.py:1026-1102) — keeps optimizer
state on NVMe and streams it through GPU memory at step time via libaio.

TPU translation: optimizer state is only touched at the accumulation
boundary (the apply step), so between optimizer steps it can leave the
device entirely.  :class:`DiskOptimizerStore` spills every optimizer-state
shard this process addresses into disk-backed memory-mapped files and frees
the device buffers; at the next boundary the state is rebuilt onto its
original shardings with ``jax.make_array_from_callback`` reading the
memmaps back.  The OS page cache plays the role of DeepSpeed's pinned
staging buffers — hot pages served from RAM, cold state resident on disk —
and every process writes only its own shards, so the scheme is
multi-controller-correct by construction.

This is a *runtime* spill: the files carry no cross-run durability
guarantees (checkpointing owns persistence, io_ops.py) and are deleted on
re-store.  Trade: HBM *and* host-RAM headroom for h2d/d2h + IO latency at
each boundary — exactly the trade the reference's NVMe tier makes.
"""

from __future__ import annotations

import os
import shutil
import threading
import weakref
from collections import deque
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

__all__ = [
    "DiskOptimizerStore",
    "StagedSnapshot",
    "stage_tree",
    "drain_staged",
    "staged_nbytes",
]


def _cleanup_dirs(directory: str, cleanup_root: Optional[str] = None) -> None:
    shutil.rmtree(directory, ignore_errors=True)
    shutil.rmtree(directory + ".next", ignore_errors=True)
    if cleanup_root is not None:
        shutil.rmtree(cleanup_root, ignore_errors=True)


def reclaim_stale_spills(base: str) -> None:
    """Best-effort removal of spill dirs left by DEAD processes (a killed
    run cannot clean up after itself).  Each live run records its pid in
    ``<run-dir>/pid``; sibling run dirs whose recorded process no longer
    exists are deleted.  Safe with concurrent runs on the same mount."""
    try:
        entries = os.listdir(base)
    except OSError:
        return
    for name in entries:
        run_dir = os.path.join(base, name)
        pid_file = os.path.join(run_dir, "pid")
        try:
            pid = int(open(pid_file).read().strip())
        except (OSError, ValueError):
            continue
        try:
            os.kill(pid, 0)  # probe only; signal 0 delivers nothing
        except ProcessLookupError:
            shutil.rmtree(run_dir, ignore_errors=True)
        except OSError:
            pass  # e.g. EPERM: process exists under another uid — keep


def _norm_index(idx, shape) -> tuple:
    """Normalize a shard index (tuple of slices) to a hashable key."""
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        out.append((start, stop, step))
    return tuple(out)


class DiskOptimizerStore:
    """Spill/restore a (possibly sharded, possibly multi-process) optimizer
    state pytree through disk-backed memmap files.

    Usage::

        store.store(opt_state)          # d2h every addressable shard, free HBM
        opt_state = store.load()        # rebuild global arrays from memmaps
    """

    def __init__(self, directory: str, cleanup_root: Optional[str] = None):
        self._dir = os.path.abspath(directory)
        self._cleanup_root = cleanup_root
        self._spec: Optional[tuple] = None  # (treedef, per-leaf records)
        # spill files are runtime-only state: reclaim them when this store is
        # garbage-collected or the interpreter exits (``cleanup_root``: an
        # enclosing per-run wrapper dir to remove along with the spill)
        self._finalizer = weakref.finalize(
            self, _cleanup_dirs, self._dir, cleanup_root
        )

    @property
    def spilled(self) -> bool:
        return self._spec is not None

    @property
    def directory(self) -> str:
        return self._dir

    def store(self, opt_state: Any, protect: Any = None) -> None:
        """Write every addressable shard to disk and delete the device
        buffers.  Replaces any previously spilled state.

        ``protect``: pytree(s) whose arrays must NOT be deleted even if the
        optimizer state aliases them — e.g. the model params when an optax
        transform keeps ``params`` (or views of them) inside its init state
        (schedule-free, lookahead-style wrappers)."""
        tmp = self._dir + ".next"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        records = []
        for i, leaf in enumerate(leaves):
            if not isinstance(leaf, jax.Array):
                # static python leaf (e.g. an int count baked by optax)
                records.append(("static", leaf))
                continue
            sharding = leaf.sharding
            shape, dtype = leaf.shape, leaf.dtype
            files = {}
            for shard in leaf.addressable_shards:
                key = _norm_index(shard.index, shape)
                if key in files:
                    continue  # replicated across local devices: store once
                base = f"leaf{i}_{len(files)}.npy"
                data = np.asarray(shard.data)
                if not data.flags["C_CONTIGUOUS"]:
                    # NOT ascontiguousarray: that would promote 0-d to 1-d
                    # and corrupt the recorded shard shape
                    data = data.copy()
                # spill RAW BYTES: .npy memmaps silently degrade ml_dtypes
                # (bfloat16/fp8 → void), so the dtype is carried in the
                # record and re-viewed at load
                mm = np.lib.format.open_memmap(
                    os.path.join(tmp, base), mode="w+",
                    dtype=np.uint8, shape=(data.nbytes,),
                )
                mm[...] = data.reshape(-1).view(np.uint8)
                mm.flush()
                del mm
                files[key] = (base, data.shape)
            records.append(("array", (shape, np.dtype(dtype), sharding, files)))
        protected = {
            id(l)
            for l in jax.tree_util.tree_leaves(protect)
            if isinstance(l, jax.Array)
        }
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and id(leaf) not in protected:
                try:
                    leaf.delete()
                except Exception:
                    pass
        # swap: the new spill replaces the old only after it is complete
        shutil.rmtree(self._dir, ignore_errors=True)
        os.replace(tmp, self._dir)
        self._spec = (treedef, records)

    def load(self) -> Any:
        """Rebuild the optimizer state onto its original shardings."""
        if self._spec is None:
            raise RuntimeError("DiskOptimizerStore.load() before store()")
        treedef, records = self._spec
        leaves = []
        for kind, rec in records:
            if kind == "static":
                leaves.append(rec)
                continue
            shape, dtype, sharding, files = rec

            def cb(idx, _files=files, _shape=shape, _dtype=dtype):
                base, shard_shape = _files[_norm_index(idx, _shape)]
                raw = np.load(os.path.join(self._dir, base), mmap_mode="r")
                return raw.view(_dtype).reshape(shard_shape)

            leaves.append(
                jax.make_array_from_callback(shape, sharding, cb)
            )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def abstract(self) -> Any:
        """ShapeDtypeStructs (with shardings) of the spilled state — lets
        AOT lowering/inspection see the avals WITHOUT reading the state back
        into device memory."""
        if self._spec is None:
            raise RuntimeError("DiskOptimizerStore.abstract() before store()")
        treedef, records = self._spec
        leaves = []
        for kind, rec in records:
            if kind == "static":
                leaves.append(rec)
            else:
                shape, dtype, sharding, _files = rec
                leaves.append(
                    jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
                )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def close(self) -> None:
        self._finalizer.detach()
        _cleanup_dirs(self._dir, self._cleanup_root)
        self._spec = None


# --------------------------------------------------------------------------- #
# Device→host checkpoint staging (ISSUE 14 tentpole a: zero-stall saves)
# --------------------------------------------------------------------------- #
#
# The async checkpoint path used to complete a full device→host gather ON THE
# MAIN THREAD before the background writer took over (io_ops.py
# ``_gather_to_host``) — every periodic save stalled the step for the whole
# transfer.  :class:`StagedSnapshot` splits that into three phases:
#
#   1. **Decouple** (main thread, one dispatch): the state pytree runs
#      through a tiny compiled identity program producing FRESH device
#      buffers.  This matters because the very next optimizer step DONATES
#      the live state arrays — donation deletes every alias, including
#      pending-copy references — so the snapshot must not share buffers
#      with anything the step path owns.
#   2. **Land** (async, off the critical path): ``copy_to_host_async`` is
#      issued per addressable shard, so the device→host DMA overlaps the
#      following steps' compute instead of blocking before them.
#   3. **Resolve** (background writer thread): materialize host numpy from
#      the landed copies and release the snapshot's device buffers.
#
# In-flight snapshots are bounded (double buffering, :data:`MAX_STAGED`):
# staging a third snapshot first drains the oldest, so a slow disk can never
# accumulate unbounded HBM/host copies of the training state.

#: maximum staged snapshots in flight (the double buffer)
MAX_STAGED = 2

#: live (unresolved) snapshots, oldest first — module-global like
#: io_ops._ASYNC_SAVES so ``wait_for_saves`` can drain staging buffers
#: before any synchronous gather (the emergency-save ordering contract)
_INFLIGHT_STAGED: "deque[StagedSnapshot]" = deque()
_STAGED_LOCK = threading.Lock()


def _snapshot_copy(tree: Any) -> Any:
    """Compiled identity copy of every jax.Array leaf: one async dispatch,
    fresh un-aliased buffers (see phase 1 above).  Non-array leaves pass
    through untouched."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, l in enumerate(leaves) if isinstance(l, jax.Array)]
    if idx:
        copies = _copy_arrays([leaves[i] for i in idx])
        for i, c in zip(idx, copies):
            leaves[i] = c
    return jax.tree_util.tree_unflatten(treedef, leaves)


@jax.jit
def _copy_arrays(arrays: List[jax.Array]) -> List[jax.Array]:
    # jnp.copy under jit lowers to a pure copy program; outputs inherit the
    # input shardings and are NEW buffers (no donation declared, no alias)
    import jax.numpy as jnp

    return [jnp.copy(a) for a in arrays]


class StagedSnapshot:
    """One pytree mid-flight from device to host.

    Construction is the zero-stall part: it dispatches the decoupling copy
    and issues the async host transfers, then returns.  :meth:`resolve`
    (idempotent, thread-safe — whoever calls first does the work) blocks
    until the copies land and returns the host-side records::

        (treedef, [("static", value)
                   | ("array", (shape, dtype, [(norm_index, np shard,
                                                shard_shape), ...]))])

    Replicated shards are deduplicated by normalized index (the
    :class:`DiskOptimizerStore` convention), so a snapshot carries each
    distinct shard of this process exactly once.
    """

    def __init__(self, tree: Any):
        snap = _snapshot_copy(tree)
        leaves, self._treedef = jax.tree_util.tree_flatten(snap)
        self._pending: List[Any] = []
        for leaf in leaves:
            if not isinstance(leaf, jax.Array):
                self._pending.append(("static", leaf))
                continue
            shape, dtype = leaf.shape, np.dtype(leaf.dtype)
            shards = []
            seen = set()
            for shard in leaf.addressable_shards:
                key = _norm_index(shard.index, shape)
                if key in seen:
                    continue  # replicated across local devices: stage once
                seen.add(key)
                shard.data.copy_to_host_async()
                shards.append((key, shard.data))
            self._pending.append(("array", (shape, dtype, shards)))
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._resolved: Optional[Tuple[Any, List[Any]]] = None
        with _STAGED_LOCK:
            _INFLIGHT_STAGED.append(self)

    @property
    def resolved(self) -> bool:
        return self._done.is_set()

    def resolve(self) -> Tuple[Any, List[Any]]:
        """Host numpy records of the staged tree (see class docstring);
        blocks on whatever transfers have not landed yet, releases the
        snapshot's device buffers, and unregisters from the in-flight
        deque.  Safe to call from any thread, any number of times."""
        with self._lock:
            if self._resolved is None:
                records: List[Any] = []
                for kind, rec in self._pending:
                    if kind == "static":
                        records.append((kind, rec))
                        continue
                    shape, dtype, shards = rec
                    host_shards = []
                    for key, data in shards:
                        arr = np.asarray(data)
                        host_shards.append((key, arr, arr.shape))
                        try:
                            data.delete()
                        except Exception:
                            pass
                    records.append(("array", (shape, dtype, host_shards)))
                self._pending = []
                self._resolved = (self._treedef, records)
                self._done.set()
                with _STAGED_LOCK:
                    try:
                        _INFLIGHT_STAGED.remove(self)
                    except ValueError:
                        pass
        return self._resolved


def stage_tree(tree: Any) -> StagedSnapshot:
    """Stage one pytree for a background checkpoint write.  Enforces the
    double buffer: with :data:`MAX_STAGED` snapshots already in flight the
    OLDEST is resolved (blocking) first — bounding snapshot memory at two
    copies of the state regardless of how slow the writer is."""
    while True:
        with _STAGED_LOCK:
            if len(_INFLIGHT_STAGED) < MAX_STAGED:
                break
            oldest = _INFLIGHT_STAGED[0]
        oldest.resolve()
    return StagedSnapshot(tree)


def drain_staged() -> None:
    """Resolve every in-flight staged snapshot (blocking).  io_ops
    ``wait_for_saves`` calls this BEFORE joining writer threads, so an
    emergency save's carefully-sequenced synchronous gather can never
    overlap a half-landed staging copy (the ISSUE 14 ordering contract)."""
    while True:
        with _STAGED_LOCK:
            if not _INFLIGHT_STAGED:
                return
            snap = _INFLIGHT_STAGED[0]
        snap.resolve()


def staged_nbytes() -> int:
    """Device bytes currently held by in-flight staged snapshots — the
    ISSUE 19 memory ledger's ``snapshot`` component.  Each unresolved
    snapshot pins a decoupling device copy until :meth:`StagedSnapshot
    .resolve` releases it; this sums the pending shard bytes of every
    snapshot still in the deque.  Race-tolerant by construction: a
    snapshot resolving mid-walk contributes whatever of its pending list
    the local copies below captured — the ledger reads 0 for it next
    window, never raises."""
    total = 0
    with _STAGED_LOCK:
        snaps = list(_INFLIGHT_STAGED)
    for snap in snaps:
        for kind, rec in list(snap._pending):
            if kind != "array":
                continue
            _shape, dtype, shards = rec
            for _key, data in list(shards):
                shard_shape = getattr(data, "shape", None)
                if shard_shape is None:
                    continue
                n = 1
                for dim in shard_shape:
                    n *= int(dim)
                total += n * dtype.itemsize
    return int(total)

"""Disk tier for optimizer-state offload (NVMe-offload equivalent).

Reference: DeepSpeed's ZeRO-Infinity NVMe offload — ``DeepspeedAIOConfig``
(reference configs.py:192-221) + offload device "nvme"
(configs.py:309-372, wired at distributed.py:1026-1102) — keeps optimizer
state on NVMe and streams it through GPU memory at step time via libaio.

TPU translation: optimizer state is only touched at the accumulation
boundary (the apply step), so between optimizer steps it can leave the
device entirely.  :class:`DiskOptimizerStore` spills every optimizer-state
shard this process addresses into disk-backed memory-mapped files and frees
the device buffers; at the next boundary the state is rebuilt onto its
original shardings with ``jax.make_array_from_callback`` reading the
memmaps back.  The OS page cache plays the role of DeepSpeed's pinned
staging buffers — hot pages served from RAM, cold state resident on disk —
and every process writes only its own shards, so the scheme is
multi-controller-correct by construction.

This is a *runtime* spill: the files carry no cross-run durability
guarantees (checkpointing owns persistence, io_ops.py) and are deleted on
re-store.  Trade: HBM *and* host-RAM headroom for h2d/d2h + IO latency at
each boundary — exactly the trade the reference's NVMe tier makes.
"""

from __future__ import annotations

import os
import shutil
import weakref
from typing import Any, Optional

import jax
import numpy as np

__all__ = ["DiskOptimizerStore"]


def _cleanup_dirs(directory: str, cleanup_root: Optional[str] = None) -> None:
    shutil.rmtree(directory, ignore_errors=True)
    shutil.rmtree(directory + ".next", ignore_errors=True)
    if cleanup_root is not None:
        shutil.rmtree(cleanup_root, ignore_errors=True)


def reclaim_stale_spills(base: str) -> None:
    """Best-effort removal of spill dirs left by DEAD processes (a killed
    run cannot clean up after itself).  Each live run records its pid in
    ``<run-dir>/pid``; sibling run dirs whose recorded process no longer
    exists are deleted.  Safe with concurrent runs on the same mount."""
    try:
        entries = os.listdir(base)
    except OSError:
        return
    for name in entries:
        run_dir = os.path.join(base, name)
        pid_file = os.path.join(run_dir, "pid")
        try:
            pid = int(open(pid_file).read().strip())
        except (OSError, ValueError):
            continue
        try:
            os.kill(pid, 0)  # probe only; signal 0 delivers nothing
        except ProcessLookupError:
            shutil.rmtree(run_dir, ignore_errors=True)
        except OSError:
            pass  # e.g. EPERM: process exists under another uid — keep


def _norm_index(idx, shape) -> tuple:
    """Normalize a shard index (tuple of slices) to a hashable key."""
    out = []
    for sl, dim in zip(idx, shape):
        start, stop, step = sl.indices(dim)
        out.append((start, stop, step))
    return tuple(out)


class DiskOptimizerStore:
    """Spill/restore a (possibly sharded, possibly multi-process) optimizer
    state pytree through disk-backed memmap files.

    Usage::

        store.store(opt_state)          # d2h every addressable shard, free HBM
        opt_state = store.load()        # rebuild global arrays from memmaps
    """

    def __init__(self, directory: str, cleanup_root: Optional[str] = None):
        self._dir = os.path.abspath(directory)
        self._cleanup_root = cleanup_root
        self._spec: Optional[tuple] = None  # (treedef, per-leaf records)
        # spill files are runtime-only state: reclaim them when this store is
        # garbage-collected or the interpreter exits (``cleanup_root``: an
        # enclosing per-run wrapper dir to remove along with the spill)
        self._finalizer = weakref.finalize(
            self, _cleanup_dirs, self._dir, cleanup_root
        )

    @property
    def spilled(self) -> bool:
        return self._spec is not None

    @property
    def directory(self) -> str:
        return self._dir

    def store(self, opt_state: Any, protect: Any = None) -> None:
        """Write every addressable shard to disk and delete the device
        buffers.  Replaces any previously spilled state.

        ``protect``: pytree(s) whose arrays must NOT be deleted even if the
        optimizer state aliases them — e.g. the model params when an optax
        transform keeps ``params`` (or views of them) inside its init state
        (schedule-free, lookahead-style wrappers)."""
        tmp = self._dir + ".next"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        records = []
        for i, leaf in enumerate(leaves):
            if not isinstance(leaf, jax.Array):
                # static python leaf (e.g. an int count baked by optax)
                records.append(("static", leaf))
                continue
            sharding = leaf.sharding
            shape, dtype = leaf.shape, leaf.dtype
            files = {}
            for shard in leaf.addressable_shards:
                key = _norm_index(shard.index, shape)
                if key in files:
                    continue  # replicated across local devices: store once
                base = f"leaf{i}_{len(files)}.npy"
                data = np.asarray(shard.data)
                if not data.flags["C_CONTIGUOUS"]:
                    # NOT ascontiguousarray: that would promote 0-d to 1-d
                    # and corrupt the recorded shard shape
                    data = data.copy()
                # spill RAW BYTES: .npy memmaps silently degrade ml_dtypes
                # (bfloat16/fp8 → void), so the dtype is carried in the
                # record and re-viewed at load
                mm = np.lib.format.open_memmap(
                    os.path.join(tmp, base), mode="w+",
                    dtype=np.uint8, shape=(data.nbytes,),
                )
                mm[...] = data.reshape(-1).view(np.uint8)
                mm.flush()
                del mm
                files[key] = (base, data.shape)
            records.append(("array", (shape, np.dtype(dtype), sharding, files)))
        protected = {
            id(l)
            for l in jax.tree_util.tree_leaves(protect)
            if isinstance(l, jax.Array)
        }
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and id(leaf) not in protected:
                try:
                    leaf.delete()
                except Exception:
                    pass
        # swap: the new spill replaces the old only after it is complete
        shutil.rmtree(self._dir, ignore_errors=True)
        os.replace(tmp, self._dir)
        self._spec = (treedef, records)

    def load(self) -> Any:
        """Rebuild the optimizer state onto its original shardings."""
        if self._spec is None:
            raise RuntimeError("DiskOptimizerStore.load() before store()")
        treedef, records = self._spec
        leaves = []
        for kind, rec in records:
            if kind == "static":
                leaves.append(rec)
                continue
            shape, dtype, sharding, files = rec

            def cb(idx, _files=files, _shape=shape, _dtype=dtype):
                base, shard_shape = _files[_norm_index(idx, _shape)]
                raw = np.load(os.path.join(self._dir, base), mmap_mode="r")
                return raw.view(_dtype).reshape(shard_shape)

            leaves.append(
                jax.make_array_from_callback(shape, sharding, cb)
            )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def abstract(self) -> Any:
        """ShapeDtypeStructs (with shardings) of the spilled state — lets
        AOT lowering/inspection see the avals WITHOUT reading the state back
        into device memory."""
        if self._spec is None:
            raise RuntimeError("DiskOptimizerStore.abstract() before store()")
        treedef, records = self._spec
        leaves = []
        for kind, rec in records:
            if kind == "static":
                leaves.append(rec)
            else:
                shape, dtype, sharding, _files = rec
                leaves.append(
                    jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
                )
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def close(self) -> None:
        self._finalizer.detach()
        _cleanup_dirs(self._dir, self._cleanup_root)
        self._spec = None

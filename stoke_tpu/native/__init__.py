"""Native (C++) host-runtime components.

The compute path of this framework is JAX/XLA/Pallas; the host runtime around
it is native where the reference's is: the reference leans on torch's C++
DataLoader machinery for its input pipeline (SURVEY.md §2.6 #24 / L0 native
deps).  Here ``batcher.cpp`` provides a GIL-free thread-pool for the
memory-bound host batching jobs (row gather, fused uint8→f32 normalize,
ragged gather+pad), bound via ctypes (no pybind11 in the build image).

The shared library is compiled on first use (g++, ~1s) and cached next to
the source; environments without a toolchain fall back to numpy with the same
API (``NativeBatcher.available`` tells you which path is active).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "batcher.cpp")
_LIB = os.path.join(_HERE, "libstoke_batcher.so")
_BUILD_LOCK = threading.Lock()
_LIB_HANDLE: Optional[ctypes.CDLL] = None
_BUILD_FAILED = False


def _build_library() -> Optional[str]:
    """Compile batcher.cpp → libstoke_batcher.so (idempotent, cached)."""
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return _LIB
    try:
        subprocess.run(
            [
                "g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
                _SRC, "-o", _LIB + ".tmp",
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(_LIB + ".tmp", _LIB)
        return _LIB
    except (subprocess.SubprocessError, OSError, FileNotFoundError):
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _LIB_HANDLE, _BUILD_FAILED
    if _LIB_HANDLE is not None or _BUILD_FAILED:
        return _LIB_HANDLE
    with _BUILD_LOCK:
        if _LIB_HANDLE is not None or _BUILD_FAILED:
            return _LIB_HANDLE
        path = _build_library()
        if path is None:
            _BUILD_FAILED = True
            return None
        lib = ctypes.CDLL(path)
        lib.stoke_pool_new.restype = ctypes.c_void_p
        lib.stoke_pool_new.argtypes = [ctypes.c_int]
        lib.stoke_pool_free.argtypes = [ctypes.c_void_p]
        lib.stoke_pool_size.restype = ctypes.c_int
        lib.stoke_pool_size.argtypes = [ctypes.c_void_p]
        lib.stoke_gather_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.stoke_u8_to_f32_norm.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
        ]
        lib.stoke_gather_pad_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        _LIB_HANDLE = lib
        return lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class NativeBatcher:
    """Thread-pool batch assembler with numpy fallback.

    Args:
        n_threads: worker threads (default: cpu count, capped at 8 — host
            batching saturates memory bandwidth quickly).
    """

    def __init__(self, n_threads: Optional[int] = None):
        lib = _load()
        self._lib = lib
        n = n_threads or min(os.cpu_count() or 1, 8)
        self._pool = lib.stoke_pool_new(n) if lib else None

    @property
    def available(self) -> bool:
        """True when the C++ path is active (False = numpy fallback)."""
        return self._pool is not None

    def __del__(self):
        if getattr(self, "_pool", None) and self._lib:
            self._lib.stoke_pool_free(self._pool)
            self._pool = None

    def gather_rows(self, src: np.ndarray, idx: Sequence[int]) -> np.ndarray:
        """out[i] = src[idx[i]] — the sampler→batch gather."""
        idx_arr = np.ascontiguousarray(idx, np.int64)
        src = np.ascontiguousarray(src)
        out = np.empty((len(idx_arr),) + src.shape[1:], src.dtype)
        if not self.available or src.nbytes == 0:
            np.take(src, idx_arr, axis=0, out=out)
            return out
        row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
        self._lib.stoke_gather_rows(
            self._pool, _ptr(src), _ptr(idx_arr), len(idx_arr), row_bytes, _ptr(out)
        )
        return out

    def u8_to_f32_norm(
        self,
        src: np.ndarray,
        mean: Sequence[float],
        std: Sequence[float],
    ) -> np.ndarray:
        """Fused uint8→float32 ``(x/255 - mean)/std`` over a channels-last
        array (the CIFAR/ImageNet preprocessing hot path)."""
        src = np.ascontiguousarray(src, np.uint8)
        channels = src.shape[-1]
        mean_a = np.ascontiguousarray(mean, np.float32)
        std_a = np.ascontiguousarray(std, np.float32)
        if mean_a.size != channels or std_a.size != channels:
            raise ValueError("mean/std must have one entry per channel")
        out = np.empty(src.shape, np.float32)
        if not self.available:
            out[:] = (src.astype(np.float32) / 255.0 - mean_a) / std_a
            return out
        self._lib.stoke_u8_to_f32_norm(
            self._pool, _ptr(src), src.size, _ptr(mean_a), _ptr(std_a),
            channels, _ptr(out),
        )
        return out

    def gather_pad(
        self,
        ragged: np.ndarray,
        offsets: np.ndarray,
        lengths: np.ndarray,
        idx: Sequence[int],
        max_len: Optional[int] = None,
        pad_multiple: int = 1,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Batch variable-length int32 sequences from a ragged buffer into a
        zero-padded [n, max_len] matrix + 0/1 mask (the BERT bucketed-sampler
        collate in one native call)."""
        idx_arr = np.ascontiguousarray(idx, np.int64)
        lengths = np.ascontiguousarray(lengths, np.int32)
        offsets = np.ascontiguousarray(offsets, np.int64)
        ragged = np.ascontiguousarray(ragged, np.int32)
        if max_len is None:
            max_len = int(lengths[idx_arr].max()) if len(idx_arr) else 0
        if pad_multiple > 1:
            max_len = ((max_len + pad_multiple - 1) // pad_multiple) * pad_multiple
        out = np.empty((len(idx_arr), max_len), np.int32)
        mask = np.empty((len(idx_arr), max_len), np.int32)
        if not self.available:
            for i, r in enumerate(idx_arr):
                L = min(int(lengths[r]), max_len)
                row = ragged[offsets[r] : offsets[r] + L]
                out[i, :L] = row
                out[i, L:] = 0
                mask[i, :L] = 1
                mask[i, L:] = 0
            return out, mask
        self._lib.stoke_gather_pad_i32(
            self._pool, _ptr(ragged), _ptr(offsets), _ptr(lengths),
            _ptr(idx_arr), len(idx_arr), max_len, _ptr(out), _ptr(mask),
        )
        return out, mask


__all__ = ["NativeBatcher"]

// Native host-side batch assembly for the stoke_tpu data pipeline.
//
// The reference delegates its input-pipeline hot path to torch's C++
// DataLoader machinery (multi-worker collation; SURVEY.md §2.6 #24).  This is
// the TPU-framework equivalent: a GIL-free thread-pool that does the two
// memory-bound jobs of host-side batching —
//
//   1. gather_rows:   out[i, :] = src[idx[i], :]        (sampler -> batch)
//   2. u8_to_f32_norm: fused uint8 -> float32 (x/255 - mean)/std per channel
//                      (image decode/normalize without a numpy temp per op)
//
// Both are trivially data-parallel, so the "pool" is a static partition over
// persistent worker threads (no work queue; wake-all, run slice, wait).
// Exposed via a C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -shared -fPIC -pthread batcher.cpp -o libstoke_batcher.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

class Pool {
 public:
  explicit Pool(int n_threads) : n_(n_threads > 0 ? n_threads : 1) {
    for (int t = 0; t < n_; ++t) {
      threads_.emplace_back([this, t] { Worker(t); });
    }
  }

  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
      epoch_++;
    }
    cv_start_.notify_all();
    for (auto& th : threads_) th.join();
  }

  // Run job(t, n) on every worker t in [0, n) and wait for completion.
  void Run(const std::function<void(int, int)>& job) {
    std::unique_lock<std::mutex> lk(mu_);
    job_ = &job;
    remaining_ = n_;
    epoch_++;
    cv_start_.notify_all();
    cv_done_.wait(lk, [this] { return remaining_ == 0; });
    job_ = nullptr;
  }

  int size() const { return n_; }

 private:
  void Worker(int t) {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int, int)>* job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_start_.wait(lk, [&] { return epoch_ != seen; });
        seen = epoch_;
        if (stop_) return;
        job = job_;
      }
      if (job) (*job)(t, n_);
      {
        std::unique_lock<std::mutex> lk(mu_);
        if (--remaining_ == 0) cv_done_.notify_all();
      }
    }
  }

  int n_;
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::condition_variable cv_start_, cv_done_;
  const std::function<void(int, int)>* job_ = nullptr;
  int remaining_ = 0;
  uint64_t epoch_ = 0;
  bool stop_ = false;
};

inline void Slice(int t, int n, int64_t total, int64_t* lo, int64_t* hi) {
  int64_t chunk = (total + n - 1) / n;
  *lo = t * chunk;
  *hi = std::min<int64_t>(total, *lo + chunk);
}

}  // namespace

extern "C" {

void* stoke_pool_new(int n_threads) { return new Pool(n_threads); }

void stoke_pool_free(void* pool) { delete static_cast<Pool*>(pool); }

int stoke_pool_size(void* pool) { return static_cast<Pool*>(pool)->size(); }

// out[i, :] = src[idx[i], :] for i in [0, n_idx); rows are row_bytes wide.
void stoke_gather_rows(void* pool, const void* src, const int64_t* idx,
                       int64_t n_idx, int64_t row_bytes, void* out) {
  auto* p = static_cast<Pool*>(pool);
  const char* s = static_cast<const char*>(src);
  char* o = static_cast<char*>(out);
  p->Run([&](int t, int n) {
    int64_t lo, hi;
    Slice(t, n, n_idx, &lo, &hi);
    for (int64_t i = lo; i < hi; ++i) {
      std::memcpy(o + i * row_bytes, s + idx[i] * row_bytes, row_bytes);
    }
  });
}

// Fused uint8 -> float32 normalize: out[j] = (src[j]/255 - mean[c]) / std[c]
// where c = j % channels (interleaved channel-last layout).
void stoke_u8_to_f32_norm(void* pool, const uint8_t* src, int64_t n,
                          const float* mean, const float* stdv, int channels,
                          float* out) {
  auto* p = static_cast<Pool*>(pool);
  // precompute per-channel scale/shift: out = src * a[c] + b[c]
  std::vector<float> a(channels), b(channels);
  for (int c = 0; c < channels; ++c) {
    a[c] = 1.0f / (255.0f * stdv[c]);
    b[c] = -mean[c] / stdv[c];
  }
  p->Run([&](int t, int nthreads) {
    int64_t lo, hi;
    Slice(t, nthreads, n / channels, &lo, &hi);
    for (int64_t px = lo; px < hi; ++px) {
      int64_t base = px * channels;
      for (int c = 0; c < channels; ++c) {
        out[base + c] = static_cast<float>(src[base + c]) * a[c] + b[c];
      }
    }
  });
}

// Gather + pad 2-D: rows of variable length (lengths[i]) from a ragged
// concatenated int32 buffer (offsets[i] gives start of row i in src);
// out is [n_idx, max_len] zero-padded, mask likewise 0/1.
void stoke_gather_pad_i32(void* pool, const int32_t* src,
                          const int64_t* offsets, const int32_t* lengths,
                          const int64_t* idx, int64_t n_idx, int64_t max_len,
                          int32_t* out, int32_t* mask) {
  auto* p = static_cast<Pool*>(pool);
  p->Run([&](int t, int n) {
    int64_t lo, hi;
    Slice(t, n, n_idx, &lo, &hi);
    for (int64_t i = lo; i < hi; ++i) {
      int64_t row = idx[i];
      int64_t len = lengths[row];
      if (len > max_len) len = max_len;
      const int32_t* s = src + offsets[row];
      int32_t* o = out + i * max_len;
      int32_t* m = mask + i * max_len;
      std::memcpy(o, s, len * sizeof(int32_t));
      std::memset(o + len, 0, (max_len - len) * sizeof(int32_t));
      for (int64_t j = 0; j < len; ++j) m[j] = 1;
      std::memset(m + len, 0, (max_len - len) * sizeof(int32_t));
    }
  });
}

}  // extern "C"

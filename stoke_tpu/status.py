"""State & validation layer: `StokeStatus`.

TPU-native re-design of the reference status layer (stoke/status.py:54-654):
a single source of truth for the run configuration that

1. deduplicates user-supplied config objects by class (reference
   ``_set_configs``, status.py:321-343),
2. enforces the legal-combination matrix *before* any device work happens
   (reference ``_check_all_raised_combinations``, status.py:192-289 — the
   README compatibility table), and
3. lazily materializes per-concern default configs via properties
   (reference status.py:473-627).

The combination matrix is table-driven (a list of rule functions) so tests can
enumerate it exhaustively — SURVEY.md §4 calls this "a table-driven test
goldmine".
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from stoke_tpu.configs import (
    ALL_CONFIG_CLASSES,
    ActivationCheckpointingConfig,
    CheckpointConfig,
    ClipGradConfig,
    ClipGradNormConfig,
    DataParallelConfig,
    DeviceOptions,
    DistributedInitConfig,
    DistributedOptions,
    FSDPConfig,
    MeshConfig,
    OffloadOptimizerConfig,
    OSSConfig,
    PartitionRulesConfig,
    PrecisionConfig,
    PrecisionOptions,
    ProfilerConfig,
    SDDPConfig,
    ShardingOptions,
    TensorboardConfig,
    asdict_config,
)


class StokeValidationError(ValueError):
    """Raised when constructor flags form an illegal combination
    (reference raises bare ValueError from status.py:192-289)."""


# Aliases accepted for reference-API compatibility: users of the reference
# select among {ddp, horovod, deepspeed} (status.py:31-38); on TPU these are
# all the one SPMD data-parallel engine.
_DISTRIBUTED_ALIASES = {
    "ddp": DistributedOptions.dp,
    "horovod": DistributedOptions.dp,
    "deepspeed": DistributedOptions.dp,
    "dp": DistributedOptions.dp,
    "xla": DistributedOptions.dp,
}

# Reference FP16Options {apex_O1, apex_O2, amp, deepspeed} (status.py:40-45)
# all meant "fp16 with a loss scaler" on GPU; on TPU the native answer is bf16.
_PRECISION_ALIASES = {
    "full": PrecisionOptions.full,
    "fp32": PrecisionOptions.full,
    "bf16": PrecisionOptions.bf16,
    "bfloat16": PrecisionOptions.bf16,
    "fp16": PrecisionOptions.fp16,
    "float16": PrecisionOptions.fp16,
    "amp": PrecisionOptions.bf16,
    "apex_O1": PrecisionOptions.bf16,
    "apex_O2": PrecisionOptions.bf16,
    "deepspeed": PrecisionOptions.bf16,
}


def _coerce(value, enum_cls, aliases, what):
    if value is None:
        return None
    if isinstance(value, enum_cls):
        return value
    if isinstance(value, str):
        if value in aliases:
            return aliases[value]
        try:
            return enum_cls(value)
        except ValueError:
            pass
    raise StokeValidationError(
        f"Unknown {what} option {value!r}; valid: "
        f"{sorted({*aliases, *[e.value for e in enum_cls]})}"
    )


class StokeStatus:
    """Single source of truth for the run configuration.

    Mirrors reference ``StokeStatus`` (status.py:54-654): holds the canonical
    status dict, validates flag combinations, and materializes per-concern
    default configs lazily.

    Args:
        batch_size_per_device: micro-batch size per device (reference
            ``batch_size`` is per-process; on TPU one process feeds all local
            devices so per-device is the invariant unit).
        grad_accum: gradient accumulation steps (reference stoke.py:137).
        grad_clip: ClipGradConfig | ClipGradNormConfig | None (stoke.py:139).
        device: "cpu" | "tpu" (reference ``gpu: bool``, stoke.py:141).
        distributed: None | "dp" (+ reference aliases ddp/horovod/deepspeed).
        precision: None/"full" | "bf16" | "fp16" (+ reference FP16 aliases).
        oss / sddp / fsdp: the sharding-tier ladder (reference
            fairscale_oss/sddp/fsdp flags, stoke.py:147-152).
        configs: optional list of config-class instances, deduped by class
            (reference status.py:321-343).
    """

    def __init__(
        self,
        batch_size_per_device: int,
        grad_accum: Optional[int] = None,
        grad_clip: Optional[Union[ClipGradConfig, ClipGradNormConfig]] = None,
        device: Union[str, DeviceOptions] = DeviceOptions.cpu,
        distributed: Optional[Union[str, DistributedOptions]] = None,
        precision: Optional[Union[str, PrecisionOptions]] = None,
        oss: bool = False,
        sddp: bool = False,
        fsdp: bool = False,
        configs: Optional[Sequence[Any]] = None,
    ):
        self._configs = self._set_configs(configs)
        self._status: Dict[str, Any] = {
            "batch_size_per_device": batch_size_per_device,
            "grad_accum": 1 if grad_accum is None else int(grad_accum),
            "grad_clip": grad_clip,
            "device": _coerce(device, DeviceOptions, {}, "device"),
            "distributed": _coerce(
                distributed, DistributedOptions, _DISTRIBUTED_ALIASES, "distributed"
            ),
            "precision": _coerce(
                precision, PrecisionOptions, _PRECISION_ALIASES, "precision"
            )
            or PrecisionOptions.full,
            "oss": bool(oss),
            "sddp": bool(sddp),
            "fsdp": bool(fsdp),
            # filled in post-init (reference set_post_init_values, status.py:345)
            "world_size": None,
            "n_devices": None,
            "n_processes": None,
            "effective_batch_size": None,
        }
        self._check_all_raised_combinations()

    # ------------------------------------------------------------------ #
    # Config dedupe (reference status.py:321-343)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _set_configs(configs: Optional[Sequence[Any]]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cfg in configs or ():
            name = type(cfg).__name__
            if not isinstance(cfg, ALL_CONFIG_CLASSES):
                raise StokeValidationError(
                    f"Unrecognized config object of type {name}; expected one of "
                    f"{[c.__name__ for c in ALL_CONFIG_CLASSES]}"
                )
            if name in out:
                warnings.warn(
                    f"Stoke -- Duplicate config {name} supplied; keeping the "
                    f"last one (mirrors reference status.py:321-343)"
                )
            out[name] = cfg
        return out

    # ------------------------------------------------------------------ #
    # The legal-combination matrix (reference status.py:192-289)
    # ------------------------------------------------------------------ #

    def _rules(self) -> List[Tuple[Callable[[Dict[str, Any]], bool], str]]:
        """Table of (predicate, message).  A predicate returning True means the
        combination is ILLEGAL.  Table-driven so tests enumerate it."""
        return [
            (
                lambda s: s["batch_size_per_device"] is None
                or s["batch_size_per_device"] < 1,
                "batch_size_per_device must be >= 1",
            ),
            (
                lambda s: s["grad_accum"] < 1,
                "grad_accum must be >= 1",
            ),
            (
                lambda s: s["grad_clip"] is not None
                and not isinstance(s["grad_clip"], (ClipGradConfig, ClipGradNormConfig)),
                "grad_clip must be ClipGradConfig, ClipGradNormConfig, or None",
            ),
            # sharding ladder legality (reference status.py:239-263):
            # SDDP requires OSS (status.py:240-243)
            (
                lambda s: s["sddp"] and not s["oss"],
                "sddp (gradient sharding) requires oss (optimizer-state "
                "sharding) — reference status.py:240-243",
            ),
            # FSDP subsumes and excludes OSS/SDDP (reference status.py:244-263)
            (
                lambda s: s["fsdp"] and (s["oss"] or s["sddp"]),
                "fsdp (fully-sharded) already shards optimizer state and "
                "gradients; combining with oss/sddp is illegal — reference "
                "status.py:244-263",
            ),
            # sharding requires the distributed engine (reference: fairscale
            # extensions require DDP, status.py:231-263)
            (
                lambda s: (s["oss"] or s["sddp"] or s["fsdp"])
                and s["distributed"] is None,
                "sharding tiers (oss/sddp/fsdp) require distributed='dp' — "
                "reference status.py:231-263",
            ),
        ]

    def _check_all_raised_combinations(self) -> None:
        for predicate, message in self._rules():
            if predicate(self._status):
                raise StokeValidationError(f"Stoke -- illegal combination: {message}")

    # ------------------------------------------------------------------ #
    # Post-init values (reference status.py:345-372, effective batch :373-375)
    # ------------------------------------------------------------------ #

    def set_post_init_values(
        self, world_size: int, n_processes: int = 1
    ) -> None:
        """Record device/process topology once the engine exists (reference
        ``set_post_init_values``, status.py:345; effective batch size calc
        status.py:373-375)."""
        self._status["world_size"] = world_size
        self._status["n_devices"] = world_size
        self._status["n_processes"] = n_processes
        self._status["effective_batch_size"] = (
            self._status["batch_size_per_device"]
            * world_size
            * self._status["grad_accum"]
        )

    # ------------------------------------------------------------------ #
    # Flag accessors
    # ------------------------------------------------------------------ #

    @property
    def status(self) -> Dict[str, Any]:
        """Canonical status dict (reference status.py:171-188)."""
        return dict(self._status)

    @property
    def batch_size(self) -> int:
        return self._status["batch_size_per_device"]

    @property
    def effective_batch_size(self) -> Optional[int]:
        return self._status["effective_batch_size"]

    @property
    def grad_accum(self) -> int:
        return self._status["grad_accum"]

    @property
    def grad_clip(self):
        return self._status["grad_clip"]

    @property
    def device(self) -> DeviceOptions:
        return self._status["device"]

    @property
    def is_tpu(self) -> bool:
        return self._status["device"] is DeviceOptions.tpu

    @property
    def distributed(self) -> Optional[DistributedOptions]:
        return self._status["distributed"]

    @property
    def is_distributed(self) -> bool:
        return self._status["distributed"] is not None

    @property
    def precision(self) -> PrecisionOptions:
        return self._status["precision"]

    @property
    def is_scaled_precision(self) -> bool:
        """True when a dynamic loss scaler is in play (fp16 only; bf16 needs
        none — SURVEY.md §3.2 hot-loop observation (c))."""
        return self._status["precision"] is PrecisionOptions.fp16

    @property
    def oss(self) -> bool:
        return self._status["oss"]

    @property
    def sddp(self) -> bool:
        return self._status["sddp"]

    @property
    def fsdp(self) -> bool:
        return self._status["fsdp"]

    @property
    def sharding_tier(self) -> ShardingOptions:
        """Collapse the three booleans to the ladder rung (post-validation the
        combinations are mutually consistent)."""
        if self._status["fsdp"]:
            return ShardingOptions.fsdp
        if self._status["sddp"]:
            return ShardingOptions.sddp
        if self._status["oss"]:
            return ShardingOptions.oss
        return ShardingOptions.none

    @property
    def world_size(self) -> Optional[int]:
        return self._status["world_size"]

    # ------------------------------------------------------------------ #
    # Lazily-materialized per-concern configs (reference status.py:473-627)
    # ------------------------------------------------------------------ #

    def _get_or_default(self, cls):
        name = cls.__name__
        if name not in self._configs:
            self._configs[name] = cls()
        return self._configs[name]

    @property
    def precision_config(self) -> PrecisionConfig:
        return self._get_or_default(PrecisionConfig)

    @property
    def dp_config(self) -> DataParallelConfig:
        return self._get_or_default(DataParallelConfig)

    @property
    def mesh_config(self) -> MeshConfig:
        return self._get_or_default(MeshConfig)

    @property
    def dist_init_config(self) -> DistributedInitConfig:
        return self._get_or_default(DistributedInitConfig)

    @property
    def oss_config(self) -> OSSConfig:
        return self._get_or_default(OSSConfig)

    @property
    def sddp_config(self) -> SDDPConfig:
        return self._get_or_default(SDDPConfig)

    @property
    def fsdp_config(self) -> FSDPConfig:
        return self._get_or_default(FSDPConfig)

    @property
    def partition_rules_config(self):
        """None unless explicitly supplied (tensor parallelism is opt-in)."""
        return self._configs.get("PartitionRulesConfig")

    @property
    def offload_optimizer_config(self):
        """None unless explicitly supplied (offload is opt-in, reference
        configs.py:309-343)."""
        return self._configs.get("OffloadOptimizerConfig")

    @property
    def activation_checkpointing_config(self) -> Optional[ActivationCheckpointingConfig]:
        """None unless explicitly supplied (remat is opt-in, matching the
        reference where activation checkpointing is DeepSpeed-only
        passthrough, configs.py:222-248)."""
        return self._configs.get("ActivationCheckpointingConfig")

    @property
    def checkpoint_config(self) -> CheckpointConfig:
        return self._get_or_default(CheckpointConfig)

    @property
    def profiler_config(self) -> ProfilerConfig:
        return self._get_or_default(ProfilerConfig)

    @property
    def tensorboard_config(self):
        """None unless explicitly supplied (metrics logging is opt-in,
        reference configs.py:392-405)."""
        return self._configs.get("TensorboardConfig")

    # ------------------------------------------------------------------ #
    # Serialization / display (reference status.py:629-654)
    # ------------------------------------------------------------------ #

    def to_dict(self) -> Dict[str, Any]:
        """JSON-friendly dump for checkpoints (reference saves the status dict
        inside every checkpoint, io_ops.py:224-236)."""
        out = {}
        for k, v in self._status.items():
            if hasattr(v, "value") and not isinstance(v, (int, float, str)):
                v = v.value
            elif isinstance(v, (ClipGradConfig, ClipGradNormConfig)):
                v = {"type": type(v).__name__, **asdict_config(v)}
            out[k] = v
        out["configs"] = {k: asdict_config(v) for k, v in self._configs.items()}
        return out

    def __repr__(self) -> str:  # reference status.py:629-654
        lines = ["Stoke -- Status:"]
        for k, v in self.to_dict().items():
            lines.append(f"  {k}: {v}")
        return "\n".join(lines)

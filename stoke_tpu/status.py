"""State & validation layer: `StokeStatus`.

TPU-native re-design of the reference status layer (stoke/status.py:54-654):
a single source of truth for the run configuration that

1. deduplicates user-supplied config objects by class (reference
   ``_set_configs``, status.py:321-343),
2. enforces the legal-combination matrix *before* any device work happens
   (reference ``_check_all_raised_combinations``, status.py:192-289 — the
   README compatibility table), and
3. lazily materializes per-concern default configs via properties
   (reference status.py:473-627).

The combination matrix is table-driven (a list of rule functions) so tests can
enumerate it exhaustively — SURVEY.md §4 calls this "a table-driven test
goldmine".
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

from stoke_tpu.configs import (
    ALL_CONFIG_CLASSES,
    COMM_DTYPES,
    COMM_STRATEGIES,
    comm_shard_updates,
    FLEET_ACTIONS,
    HEALTH_ACTIONS,
    ActivationCheckpointingConfig,
    AttributionConfig,
    CheckpointConfig,
    CheckpointFormat,
    HealthConfig,
    ClipGradConfig,
    ClipGradNormConfig,
    CommConfig,
    CompileConfig,
    DataParallelConfig,
    DeviceOptions,
    DistributedInitConfig,
    DistributedOptions,
    FleetConfig,
    FSDPConfig,
    MemoryConfig,
    MeshConfig,
    NumericsConfig,
    OffloadDiskConfig,
    OffloadOptimizerConfig,
    OffloadParamsConfig,
    OpsPlaneConfig,
    OSSConfig,
    PartitionRulesConfig,
    PrecisionConfig,
    PrecisionOptions,
    ProfilerConfig,
    ResilienceConfig,
    SDDPConfig,
    SERVE_ATTENTION_KERNELS,
    SERVE_DECODE_KERNELS,
    SERVE_KV_DTYPES,
    SERVE_QUANT_MODES,
    ServeConfig,
    ShardingOptions,
    TelemetryConfig,
    TensorboardConfig,
    TraceConfig,
    asdict_config,
)


class StokeValidationError(ValueError):
    """Raised when constructor flags form an illegal combination
    (reference raises bare ValueError from status.py:192-289)."""


# Aliases accepted for reference-API compatibility: users of the reference
# select among {ddp, horovod, deepspeed} (status.py:31-38); on TPU these are
# all the one SPMD data-parallel engine.
_DISTRIBUTED_ALIASES = {
    "ddp": DistributedOptions.dp,
    "horovod": DistributedOptions.dp,
    "deepspeed": DistributedOptions.dp,
    "dp": DistributedOptions.dp,
    "xla": DistributedOptions.dp,
}

# Reference FP16Options {apex_O1, apex_O2, amp, deepspeed} (status.py:40-45)
# all meant "fp16 with a loss scaler" on GPU; on TPU the native answer is bf16.
_PRECISION_ALIASES = {
    "full": PrecisionOptions.full,
    "fp32": PrecisionOptions.full,
    "bf16": PrecisionOptions.bf16,
    "bfloat16": PrecisionOptions.bf16,
    "fp16": PrecisionOptions.fp16,
    "float16": PrecisionOptions.fp16,
    "amp": PrecisionOptions.bf16,
    "apex_O1": PrecisionOptions.bf16,
    "apex_O2": PrecisionOptions.bf16,
    "deepspeed": PrecisionOptions.bf16,
}


def _coerce(value, enum_cls, aliases, what):
    if value is None:
        return None
    if isinstance(value, enum_cls):
        return value
    if isinstance(value, str):
        if value in aliases:
            return aliases[value]
        try:
            return enum_cls(value)
        except ValueError:
            pass
    raise StokeValidationError(
        f"Unknown {what} option {value!r}; valid: "
        f"{sorted({*aliases, *[e.value for e in enum_cls]})}"
    )


class StokeStatus:
    """Single source of truth for the run configuration.

    Mirrors reference ``StokeStatus`` (status.py:54-654): holds the canonical
    status dict, validates flag combinations, and materializes per-concern
    default configs lazily.

    Args:
        batch_size_per_device: micro-batch size per device (reference
            ``batch_size`` is per-process; on TPU one process feeds all local
            devices so per-device is the invariant unit).
        grad_accum: gradient accumulation steps (reference stoke.py:137).
        grad_clip: ClipGradConfig | ClipGradNormConfig | None (stoke.py:139).
        device: "cpu" | "tpu" (reference ``gpu: bool``, stoke.py:141).
        distributed: None | "dp" (+ reference aliases ddp/horovod/deepspeed).
        precision: None/"full" | "bf16" | "fp16" (+ reference FP16 aliases).
        oss / sddp / fsdp: the sharding-tier ladder (reference
            fairscale_oss/sddp/fsdp flags, stoke.py:147-152).
        configs: optional list of config-class instances, deduped by class
            (reference status.py:321-343).
    """

    def __init__(
        self,
        batch_size_per_device: int,
        grad_accum: Optional[int] = None,
        grad_clip: Optional[Union[ClipGradConfig, ClipGradNormConfig]] = None,
        device: Union[str, DeviceOptions] = DeviceOptions.cpu,
        distributed: Optional[Union[str, DistributedOptions]] = None,
        precision: Optional[Union[str, PrecisionOptions]] = None,
        oss: bool = False,
        sddp: bool = False,
        fsdp: bool = False,
        configs: Optional[Sequence[Any]] = None,
    ):
        self._configs = self._set_configs(configs)
        self._status: Dict[str, Any] = {
            "batch_size_per_device": batch_size_per_device,
            "grad_accum": 1 if grad_accum is None else int(grad_accum),
            "grad_clip": grad_clip,
            "device": _coerce(device, DeviceOptions, {}, "device"),
            "distributed": _coerce(
                distributed, DistributedOptions, _DISTRIBUTED_ALIASES, "distributed"
            ),
            "precision": _coerce(
                precision, PrecisionOptions, _PRECISION_ALIASES, "precision"
            )
            or PrecisionOptions.full,
            "oss": bool(oss),
            "sddp": bool(sddp),
            "fsdp": bool(fsdp),
            # filled in post-init (reference set_post_init_values, status.py:345)
            "world_size": None,
            "n_devices": None,
            "n_processes": None,
            "effective_batch_size": None,
        }
        self._check_all_raised_combinations()

    # ------------------------------------------------------------------ #
    # Config dedupe (reference status.py:321-343)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _set_configs(configs: Optional[Sequence[Any]]) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cfg in configs or ():
            name = type(cfg).__name__
            if not isinstance(cfg, ALL_CONFIG_CLASSES):
                raise StokeValidationError(
                    f"Unrecognized config object of type {name}; expected one of "
                    f"{[c.__name__ for c in ALL_CONFIG_CLASSES]}"
                )
            if name in out:
                warnings.warn(
                    f"Stoke -- Duplicate config {name} supplied; keeping the "
                    f"last one (mirrors reference status.py:321-343)"
                )
            out[name] = cfg
        return out

    # ------------------------------------------------------------------ #
    # The legal-combination matrix (reference status.py:192-289)
    # ------------------------------------------------------------------ #

    def _mesh_axes(self) -> Tuple[str, ...]:
        """Axis names of the mesh this run would build (build_mesh uses
        MeshConfig.axes verbatim; default 1-D ("data",))."""
        mc = self._configs.get("MeshConfig")
        return tuple(mc.axes) if mc is not None else ("data",)

    def _rules(self) -> List[Tuple[Callable[[Dict[str, Any]], Any], str]]:
        """Table of (predicate, message).  A predicate returning truthy means
        the combination is ILLEGAL; returning a string overrides the static
        message (for named-axis diagnostics).  Table-driven so tests
        enumerate it (reference ``_check_all_raised_combinations``,
        status.py:192-289)."""

        def _ignored_without_distributed(cfg_name):
            def rule(s):
                return cfg_name in self._configs and s["distributed"] is None
            return rule

        def _mesh_shape_mismatch(s):
            mc = self._configs.get("MeshConfig")
            if mc is None:
                return False
            if len(set(mc.axes)) != len(mc.axes):
                return f"MeshConfig has duplicate axis names {mc.axes}"
            if mc.shape is not None and len(mc.shape) != len(mc.axes):
                return (
                    f"MeshConfig shape {mc.shape} has {len(mc.shape)} entries "
                    f"but axes {mc.axes} has {len(mc.axes)}"
                )
            return False

        def _partition_rule_axis_unknown(s):
            prc = self._configs.get("PartitionRulesConfig")
            if prc is None or s["distributed"] is None:
                return False
            axes = set(self._mesh_axes())
            for rx, spec in prc.rules:
                for entry in spec:
                    # multi-axis dims may arrive as tuples or (from YAML) lists
                    names = (
                        tuple(entry)
                        if isinstance(entry, (tuple, list))
                        else (entry,)
                    )
                    for n in names:
                        if isinstance(n, str) and n != "..." and n not in axes:
                            return (
                                f"partition rule {rx!r} names mesh axis "
                                f"{n!r} but the mesh only has axes "
                                f"{sorted(axes)} — add it to MeshConfig.axes "
                                f"or fix the rule"
                            )
            return False

        def _seq_axis_missing(s):
            dp = self._configs.get("DataParallelConfig")
            if dp is None or dp.shard_seq_dim is None:
                return False
            if s["distributed"] is None:
                return (
                    "DataParallelConfig.shard_seq_dim is set but "
                    "distributed=None; it would be silently ignored"
                )
            if dp.seq_axis_name not in self._mesh_axes():
                return (
                    f"DataParallelConfig.shard_seq_dim is set but the mesh "
                    f"has no {dp.seq_axis_name!r} axis (axes: "
                    f"{list(self._mesh_axes())}) — add it to MeshConfig.axes"
                )
            return False

        def _tier_axis_missing(s):
            if not (s["oss"] or s["sddp"] or s["fsdp"]):
                return False
            dp = self._configs.get("DataParallelConfig")
            axis = dp.axis_name if dp is not None else "data"
            if axis not in self._mesh_axes():
                tier = "fsdp" if s["fsdp"] else ("sddp" if s["sddp"] else "oss")
                return (
                    f"{tier} shards state over mesh axis {axis!r} but the "
                    f"mesh only has axes {list(self._mesh_axes())} — the "
                    f"tier would silently do nothing"
                )
            return False

        def _probe_writable(target):
            """Create ``target`` and prove a file can be written there.
            Returns the OSError on failure, None on success.  NOTE:
            validation intentionally creates the directory (so the first
            mid-training log call can't fail on a missing path) and probes
            actual writability with a throwaway file — makedirs succeeding
            does not prove files can be written (permissions/quota can
            still fail at first write)."""
            import os
            import uuid

            try:
                os.makedirs(target, exist_ok=True)
                probe = os.path.join(
                    target, f".stoke-write-probe-{uuid.uuid4().hex[:8]}"
                )
                with open(probe, "wb") as f:
                    f.write(b"ok")
                os.remove(probe)
                return None
            except OSError as e:
                return e

        def _rank0_only(message):
            """Sink-path failures only matter on the writing process — a
            worker on a read-only mount of a coordinator-owned log dir must
            not kill the whole job."""
            import jax

            if jax.process_index() != 0:
                return False
            return message

        def _tensorboard_writable(s):
            # metrics use the in-repo native event writer
            # (utils/tb_writer.py) — no import to validate, but the output
            # path must be creatable so failures surface at init, not at
            # the first mid-training log call
            cfg = self._configs.get("TensorboardConfig")
            if cfg is None:
                return False
            import os

            err = _probe_writable(os.path.join(cfg.output_path, cfg.job_name))
            if err is None:
                return False
            return _rank0_only(
                f"TensorboardConfig output path "
                f"{cfg.output_path!r}/{cfg.job_name!r} is not writable: {err}"
            )

        def _telemetry_invalid(s):
            # merged observability validation (TelemetryConfig +
            # ProfilerConfig) — cadence/flag errors are structural (raise on
            # every rank); sink-path errors are rank-0 only, same policy as
            # the TB rule above
            cfg = self._configs.get("TelemetryConfig")
            if cfg is None:
                return False
            if cfg.log_every_n_steps < 1:
                return (
                    f"TelemetryConfig.log_every_n_steps must be >= 1, got "
                    f"{cfg.log_every_n_steps}"
                )
            if cfg.prometheus or cfg.tensorboard or cfg.jsonl:
                err = _probe_writable(cfg.output_dir)
                if err is not None:
                    msg = (
                        f"TelemetryConfig.output_dir {cfg.output_dir!r} is "
                        f"not writable: {err}"
                    )
                    # all-rank sinks write on every process: the error is
                    # fatal everywhere, not only on rank 0
                    if (cfg.jsonl and cfg.jsonl_all_ranks) or (
                        cfg.prometheus and cfg.prometheus_all_ranks
                    ):
                        return msg
                    return _rank0_only(msg)
            return False

        def _profiler_invalid(s):
            cfg = self._configs.get("ProfilerConfig")
            if cfg is None or cfg.trace_dir is None:
                return False
            err = _probe_writable(cfg.trace_dir)
            if err is None:
                return False
            # jax.profiler traces write from every process
            return (
                f"ProfilerConfig.trace_dir {cfg.trace_dir!r} is not "
                f"writable: {err}"
            )

        def _comm_invalid(s):
            """Gradient-transport legality (ISSUE 2, extended by ISSUE 8):
            a CommConfig that would silently do nothing (no distributed
            engine), that names an unknown dtype/strategy, or that
            combines quantization with incompatible features is rejected
            HERE — not at compile time, not silently.

            The quantized wire format reaches every sharding tier now:
            tiers none/oss keep PR 2's replicated exchange by default,
            sddp/fsdp auto-engage the ISSUE 8 weight-update-sharded path
            (quantized reduce-scatter → shard-local step → param
            all-gather; ``CommConfig.shard_updates`` overrides either
            way).  Still illegal: fp16 loss scalers with any lossy wire,
            the replicated exchange forced under a sharded grad buffer,
            sharded updates with nothing sharded (tier none) or with the
            single-stage ``all_reduce`` schedule, and a missing data
            axis."""
            cfg = self._configs.get("CommConfig")
            if cfg is None:
                return False
            if s["distributed"] is None:
                return (
                    "CommConfig supplied but distributed=None; the gradient "
                    "transport would be silently ignored — set "
                    "distributed='dp' or drop the config"
                )
            if cfg.dtype not in COMM_DTYPES:
                return (
                    f"CommConfig.dtype {cfg.dtype!r} unknown; valid: "
                    f"{list(COMM_DTYPES)}"
                )
            if cfg.strategy not in COMM_STRATEGIES:
                return (
                    f"CommConfig.strategy {cfg.strategy!r} unknown; valid: "
                    f"{list(COMM_STRATEGIES)}"
                )
            if cfg.bucket_mb <= 0:
                return f"CommConfig.bucket_mb must be > 0, got {cfg.bucket_mb}"
            if cfg.chunk_elems < 1:
                return (
                    f"CommConfig.chunk_elems must be >= 1, got "
                    f"{cfg.chunk_elems}"
                )
            if cfg.dtype == "fp32":
                return False  # exact pass-through composes with everything
            if s["precision"] is PrecisionOptions.fp16:
                # fp16 carries dynamic loss scalers: the single-scaler mode
                # stores SCALED grads in the buffer (quantization chunk
                # scales would alias the loss scale) and per-loss mode
                # updates scaler state from per-micro finiteness — both
                # interact with lossy transport in ways neither the
                # replicated nor the sharded path supports
                return (
                    f"CommConfig(dtype={cfg.dtype!r}) with precision='fp16' "
                    f"is unsupported — the dynamic loss scaler interacts "
                    f"with lossy gradient transport; use bf16 (the TPU "
                    f"path) or full precision"
                )
            tier = self.sharding_tier
            if comm_shard_updates(cfg, tier):
                # ISSUE 8 sharded weight-update path: quantized
                # reduce-scatter → per-shard EF + dequantize → shard-local
                # optimizer step → param all-gather
                if tier is ShardingOptions.none:
                    return (
                        f"CommConfig(dtype={cfg.dtype!r}, shard_updates="
                        f"True) needs a sharded tier — the weight-update-"
                        f"sharded transport partitions the optimizer step "
                        f"over the data axis; enable oss/sddp/fsdp or drop "
                        f"shard_updates"
                    )
                if cfg.strategy != "rs_ag":
                    return (
                        f"CommConfig(strategy={cfg.strategy!r}) cannot "
                        f"shard weight updates — the sharded path IS the "
                        f"rs_ag schedule (quantized reduce-scatter + param "
                        f"all-gather); the single-stage all_reduce assumes "
                        f"every replica consumes the full gradient"
                    )
            elif s["sddp"] or s["fsdp"]:
                # only reachable with an explicit shard_updates=False:
                # sddp/fsdp shard the gradient accumulation buffer over the
                # data axis and the REPLICATED transport needs the
                # replicated grad buffer of tiers none/oss
                tier_name = "fsdp" if s["fsdp"] else "sddp"
                return (
                    f"CommConfig(dtype={cfg.dtype!r}, shard_updates=False) "
                    f"forces the replicated gradient exchange under "
                    f"{tier_name} gradient sharding — the replicated "
                    f"transport needs the replicated grad buffer of tiers "
                    f"none/oss; drop shard_updates to use the sharded "
                    f"weight-update path"
                )
            dp = self._configs.get("DataParallelConfig")
            axis = dp.axis_name if dp is not None else "data"
            if axis not in self._mesh_axes():
                return (
                    f"CommConfig(dtype={cfg.dtype!r}) exchanges gradients "
                    f"over mesh axis {axis!r} but the mesh only has axes "
                    f"{list(self._mesh_axes())} — add it to MeshConfig.axes"
                )
            return False

        def _health_invalid(s):
            """Health-monitor legality (ISSUE 3): sentinels ride the
            telemetry pipeline (their values surface in the JSONL step
            events), halting on non-finite gradients conflicts with fp16's
            skip-on-overflow scaler (transient infs are its normal
            operation), and a watchdog without a positive timeout would
            either never fire or fire immediately."""
            cfg = self._configs.get("HealthConfig")
            if cfg is None:
                return False
            if cfg.sentinels and "TelemetryConfig" not in self._configs:
                return (
                    "HealthConfig(sentinels=True) requires a TelemetryConfig"
                    " — the sentinel values surface through the telemetry "
                    "step events; add one or set sentinels=False"
                )
            if cfg.ring_size < 1:
                return (
                    f"HealthConfig.ring_size must be >= 1, got "
                    f"{cfg.ring_size}"
                )
            if cfg.detector_warmup_steps < 1:
                return (
                    f"HealthConfig.detector_warmup_steps must be >= 1, got "
                    f"{cfg.detector_warmup_steps}"
                )
            for field in (
                "loss_spike_action", "grad_spike_action", "nonfinite_action",
                "scaler_skip_action", "recompile_storm_action",
                "starvation_action", "comm_residual_action",
            ):
                action = getattr(cfg, field)
                if action not in HEALTH_ACTIONS:
                    return (
                        f"HealthConfig.{field} {action!r} unknown; valid: "
                        f"{list(HEALTH_ACTIONS)}"
                    )
            if (
                cfg.nonfinite_action == "halt"
                and s["precision"] is PrecisionOptions.fp16
            ):
                return (
                    "HealthConfig(nonfinite_action='halt') is incompatible "
                    "with precision='fp16' — the dynamic loss scaler "
                    "tolerates transient infs by skipping the step; use "
                    "'record'/'warn'/'dump', or bf16/full precision"
                )
            if cfg.watchdog and cfg.watchdog_timeout_s <= 0:
                return (
                    f"HealthConfig.watchdog requires watchdog_timeout_s > 0,"
                    f" got {cfg.watchdog_timeout_s}"
                )
            # detector-threshold sanity (ISSUE 15 knob-coverage lint): a
            # zero/negative threshold is a detector that fires every step
            # or never — a typo, not a tuning choice
            if not (0.0 < cfg.ema_alpha <= 1.0):
                return (
                    f"HealthConfig.ema_alpha must be in (0, 1], got "
                    f"{cfg.ema_alpha}"
                )
            for field in ("loss_spike_zscore", "grad_spike_zscore",
                          "comm_residual_factor"):
                if getattr(cfg, field) <= 0:
                    return (
                        f"HealthConfig.{field} must be > 0, got "
                        f"{getattr(cfg, field)}"
                    )
            for field in ("scaler_skip_streak", "recompile_storm_threshold",
                          "recompile_storm_window", "starvation_streak"):
                if getattr(cfg, field) < 1:
                    return (
                        f"HealthConfig.{field} must be >= 1, got "
                        f"{getattr(cfg, field)}"
                    )
            if cfg.max_dumps < 0:
                return (
                    f"HealthConfig.max_dumps must be >= 0 (0 disables "
                    f"capped dumps), got {cfg.max_dumps}"
                )
            if cfg.watchdog_compile_grace_s < 0:
                return (
                    f"HealthConfig.watchdog_compile_grace_s must be >= 0,"
                    f" got {cfg.watchdog_compile_grace_s}"
                )
            return False

        def _attribution_invalid(s):
            """Attribution legality (ISSUE 4): the MFU/goodput gauges
            surface through the telemetry step events (so a
            TelemetryConfig is required), MFU needs a positive peak to
            divide by, and the anomaly-triggered profiler capture writes
            xprof traces into ``ProfilerConfig.trace_dir`` (so enabling
            it without one would silently capture nothing)."""
            cfg = self._configs.get("AttributionConfig")
            if cfg is None:
                return False
            if "TelemetryConfig" not in self._configs:
                return (
                    "AttributionConfig requires a TelemetryConfig — the "
                    "MFU/goodput attribution surfaces through the telemetry "
                    "step events; add one or drop the config"
                )
            if cfg.peak_tflops <= 0:
                return (
                    f"AttributionConfig.peak_tflops must be > 0 (MFU's "
                    f"denominator — measure it with scripts/flops_probe.py "
                    f"or use the datasheet number), got {cfg.peak_tflops}"
                )
            if cfg.peak_hbm_gbps < 0 or cfg.ici_gbps < 0:
                return (
                    "AttributionConfig.peak_hbm_gbps/ici_gbps must be >= 0 "
                    "(0 disables that roofline leg)"
                )
            if not (0.0 < cfg.ema_alpha <= 1.0):
                return (
                    f"AttributionConfig.ema_alpha must be in (0, 1], got "
                    f"{cfg.ema_alpha}"
                )
            if cfg.capture_warmup_windows < 0:
                return (
                    f"AttributionConfig.capture_warmup_windows must be "
                    f">= 0, got {cfg.capture_warmup_windows}"
                )
            if cfg.auto_capture:
                pc = self._configs.get("ProfilerConfig")
                if pc is None or pc.trace_dir is None:
                    return (
                        "AttributionConfig(auto_capture=True) requires "
                        "ProfilerConfig.trace_dir — the captured xprof "
                        "trace windows are written there; set it or "
                        "disable auto_capture"
                    )
                if cfg.max_captures < 1 or cfg.capture_steps < 1:
                    return (
                        "AttributionConfig auto-capture needs "
                        "max_captures >= 1 and capture_steps >= 1"
                    )
                if (
                    cfg.capture_mfu_below <= 0
                    and cfg.capture_step_zscore <= 0
                ):
                    return (
                        "AttributionConfig(auto_capture=True) with both "
                        "triggers disabled (capture_mfu_below <= 0 and "
                        "capture_step_zscore <= 0) would never capture — "
                        "enable at least one trigger"
                    )
            # 'halt' is deliberately excluded: a diagnostic trace capture
            # must never be able to kill a multi-day run
            valid_capture = [a for a in HEALTH_ACTIONS if a != "halt"]
            if cfg.capture_action not in valid_capture:
                return (
                    f"AttributionConfig.capture_action "
                    f"{cfg.capture_action!r} invalid; valid: "
                    f"{valid_capture} (halt is not allowed — a profiler "
                    f"capture is diagnostic, not fatal)"
                )
            return False

        def _fleet_invalid(s):
            """Fleet-observability legality (ISSUE 5): the fleet view
            surfaces through the telemetry step events (so a
            TelemetryConfig is required), the exchange window must be a
            positive step count, the straggler thresholds must be able to
            fire, and the detector action must be a known non-fatal one
            (a slow host is a diagnosis, never a reason to halt)."""
            cfg = self._configs.get("FleetConfig")
            if cfg is None:
                return False
            if "TelemetryConfig" not in self._configs:
                return (
                    "FleetConfig requires a TelemetryConfig — the fleet "
                    "view surfaces through the telemetry step events; add "
                    "one or drop the config"
                )
            if cfg.window_steps < 1:
                return (
                    f"FleetConfig.window_steps must be >= 1, got "
                    f"{cfg.window_steps}"
                )
            if cfg.straggler_zscore <= 0:
                return (
                    f"FleetConfig.straggler_zscore must be > 0, got "
                    f"{cfg.straggler_zscore}"
                )
            if cfg.straggler_rel_frac <= 0:
                return (
                    f"FleetConfig.straggler_rel_frac must be > 0, got "
                    f"{cfg.straggler_rel_frac}"
                )
            if cfg.straggler_windows < 1:
                return (
                    f"FleetConfig.straggler_windows must be >= 1, got "
                    f"{cfg.straggler_windows}"
                )
            if cfg.straggler_action not in FLEET_ACTIONS:
                return (
                    f"FleetConfig.straggler_action "
                    f"{cfg.straggler_action!r} unknown; valid: "
                    f"{list(FLEET_ACTIONS)} (halt is not allowed — a "
                    f"straggler is a performance diagnosis, not fatal)"
                )
            if cfg.rebalance:
                # skew-reactive input rebalancing (ISSUE 14): the bounded
                # actuator's knobs must be able to act — a zero step size
                # or an empty/full share band is a silently-dead actuator
                # (the chaos-spec discipline: loud, never a no-op)
                if cfg.rebalance_rows < 1:
                    return (
                        f"FleetConfig.rebalance_rows must be >= 1, got "
                        f"{cfg.rebalance_rows}"
                    )
                if not (0.0 < cfg.rebalance_max_frac < 1.0):
                    return (
                        f"FleetConfig.rebalance_max_frac must be in "
                        f"(0, 1) — a host sheds at most that fraction of "
                        f"its read share, never all of it; got "
                        f"{cfg.rebalance_max_frac}"
                    )
            return False

        def _numerics_invalid(s):
            """Per-layer-numerics legality (ISSUE 12): the per-group view
            surfaces through the telemetry pipeline (so a TelemetryConfig
            is required), the provenance action must be a known health
            action — with ``halt`` banned under fp16 for the same reason
            the nonfinite detector's is (transient infs are the dynamic
            scaler's normal operation) — and the config must observe at
            least one signal family (a fully-disabled observatory would
            silently record nothing)."""
            cfg = self._configs.get("NumericsConfig")
            if cfg is None:
                return False
            if "TelemetryConfig" not in self._configs:
                return (
                    "NumericsConfig requires a TelemetryConfig — the "
                    "per-layer numerics surface through the telemetry step "
                    "events; add one or drop the config"
                )
            if cfg.provenance_action not in HEALTH_ACTIONS:
                return (
                    f"NumericsConfig.provenance_action "
                    f"{cfg.provenance_action!r} unknown; valid: "
                    f"{list(HEALTH_ACTIONS)}"
                )
            if (
                cfg.provenance_action == "halt"
                and s["precision"] is PrecisionOptions.fp16
            ):
                return (
                    "NumericsConfig(provenance_action='halt') is "
                    "incompatible with precision='fp16' — the dynamic loss "
                    "scaler tolerates transient infs by skipping the step; "
                    "use 'record'/'warn'/'dump', or bf16/full precision"
                )
            if cfg.top_k < 1:
                return (
                    f"NumericsConfig.top_k must be >= 1, got {cfg.top_k}"
                )
            if not (cfg.grad_stats or cfg.wire_error):
                return (
                    "NumericsConfig with grad_stats=False and "
                    "wire_error=False observes nothing — enable at least "
                    "one signal family or drop the config"
                )
            if not cfg.grad_stats and cfg.provenance_action in (
                "dump", "halt"
            ):
                # provenance is derived FROM the grad-stats matrix: with
                # grad_stats off the detector can never fire, and an
                # explicit escalation that silently no-ops would fake a
                # guarded run (the chaos-spec discipline: typo'd intent
                # is a status error, never a silent no-op)
                return (
                    f"NumericsConfig(provenance_action="
                    f"{cfg.provenance_action!r}) requires grad_stats=True "
                    f"— NaN provenance is derived from the per-group "
                    f"stats matrix, so with grad_stats=False it can "
                    f"never fire; enable grad_stats or drop the "
                    f"escalated action"
                )
            return False

        def _memory_invalid(s):
            """HBM-observatory legality (ISSUE 19): the ledger surfaces
            through the telemetry pipeline (so a TelemetryConfig is
            required), the pre-flight margin must be a usable fraction,
            and a capacity override must be a positive byte count (the
            silently-ignored-knob anti-pattern: a zero/negative capacity
            would make the pre-flight fire always or never)."""
            cfg = self._configs.get("MemoryConfig")
            if cfg is None:
                return False
            if "TelemetryConfig" not in self._configs:
                return (
                    "MemoryConfig requires a TelemetryConfig — the HBM "
                    "capacity ledger surfaces through the telemetry step "
                    "events; add one or drop the config"
                )
            if not (0.0 < cfg.oom_margin_frac <= 1.0):
                return (
                    f"MemoryConfig.oom_margin_frac must be in (0, 1] — "
                    f"the pre-flight warns when predicted peak crosses "
                    f"that fraction of capacity; got "
                    f"{cfg.oom_margin_frac}"
                )
            if cfg.capacity_bytes is not None and cfg.capacity_bytes <= 0:
                return (
                    f"MemoryConfig.capacity_bytes must be a positive "
                    f"byte count when set (None reads the live "
                    f"memory_stats limit); got {cfg.capacity_bytes}"
                )
            return False

        def _opsplane_invalid(s):
            """Ops-plane legality (ISSUE 20): the plane serves the
            telemetry registry (so a TelemetryConfig is required), the
            bind address/port must be usable, and the capture/table
            bounds must actually bound (the silently-ignored-knob
            anti-pattern: a zero requests_limit or an inverted
            default-vs-max capture length would make an endpoint lie)."""
            cfg = self._configs.get("OpsPlaneConfig")
            if cfg is None:
                return False
            if "TelemetryConfig" not in self._configs:
                return (
                    "OpsPlaneConfig requires a TelemetryConfig — the "
                    "plane serves the telemetry registry and reuses its "
                    "Prometheus sink labels; add one or drop the config"
                )
            if not (0 <= cfg.port <= 65535):
                return (
                    f"OpsPlaneConfig.port must be in 0..65535 (0 binds "
                    f"an ephemeral port; rank r binds port + r); got "
                    f"{cfg.port}"
                )
            if not isinstance(cfg.host, str) or not cfg.host:
                return (
                    f"OpsPlaneConfig.host must be a non-empty bind "
                    f"address (loopback '127.0.0.1' by default; "
                    f"'0.0.0.0' to expose to fleet scrapers); got "
                    f"{cfg.host!r}"
                )
            if cfg.profile_max_seconds <= 0:
                return (
                    f"OpsPlaneConfig.profile_max_seconds must be > 0 — "
                    f"it is the hard per-capture ceiling /profile clamps "
                    f"to; got {cfg.profile_max_seconds}"
                )
            if not (
                0 < cfg.profile_default_seconds <= cfg.profile_max_seconds
            ):
                return (
                    f"OpsPlaneConfig.profile_default_seconds must be in "
                    f"(0, profile_max_seconds={cfg.profile_max_seconds}] "
                    f"— /profile without ?seconds= uses it, and a "
                    f"default above the ceiling would silently clamp; "
                    f"got {cfg.profile_default_seconds}"
                )
            if cfg.requests_limit < 1:
                return (
                    f"OpsPlaneConfig.requests_limit must be >= 1 — it "
                    f"caps the /requests table (the response marks "
                    f"itself truncated past it); got {cfg.requests_limit}"
                )
            return False

        def _checkpoint_invalid(s):
            """Checkpoint-layout legality (ISSUE 14, extended by ISSUE
            15's knob-coverage lint): the periodic-save cadence must be
            able to fire — ``save_every_n_steps`` without an
            ``auto_path`` makes ``_maybe_auto_save`` a silent no-op
            (the silently-ignored-knob anti-pattern) — and offload
            staging is the zero-stall path for ASYNC CONSOLIDATED saves
            only — on the sync path there is no background writer to
            hand the staged references to, and the sharded (orbax) path
            already stages its own device→host copy."""
            cfg = self._configs.get("CheckpointConfig")
            if cfg is None:
                return False
            if cfg.save_every_n_steps is not None:
                if cfg.save_every_n_steps < 1:
                    return (
                        f"CheckpointConfig.save_every_n_steps must be "
                        f">= 1 or None, got {cfg.save_every_n_steps}"
                    )
                if not cfg.auto_path:
                    return (
                        "CheckpointConfig.save_every_n_steps is set but "
                        "auto_path is not — the periodic auto-save would "
                        "silently never write; set auto_path or drop the "
                        "cadence"
                    )
            if cfg.save_rank < 0:
                return (
                    f"CheckpointConfig.save_rank must be >= 0 (taken "
                    f"modulo the process count), got {cfg.save_rank}"
                )
            if not getattr(cfg, "offload_staging", False):
                return False
            if not cfg.async_save:
                return (
                    "CheckpointConfig.offload_staging requires "
                    "async_save=True — staging hands device references to "
                    "the background writer; a synchronous save has none. "
                    "Enable async_save or drop offload_staging"
                )
            if cfg.format is CheckpointFormat.sharded:
                return (
                    "CheckpointConfig.offload_staging applies to the "
                    "consolidated format only — the sharded (orbax) async "
                    "path stages its own device→host copy. Use "
                    "format='consolidated' or drop offload_staging"
                )
            return False

        def _resilience_invalid(s):
            """Resilience legality (ISSUE 7): the emergency-save root must
            be writable on EVERY process (sharded emergency saves write
            from all ranks), the resumable exit code must be expressible
            AND distinct from the health watchdog's (supervisors classify
            drained-vs-hung on exactly that difference), the preemption
            signals must exist on this platform, and a chaos spec — config
            field or ``STOKE_CHAOS`` env — must parse (a typo'd plan
            silently injecting nothing would fake a green chaos test)."""
            cfg = self._configs.get("ResilienceConfig")
            if cfg is None:
                return False
            from stoke_tpu.resilience import (
                CHAOS_ENV,
                _WATCHDOG_EXIT_CODE,
                parse_chaos,
            )

            if not (0 < cfg.exit_code < 256):
                return (
                    f"ResilienceConfig.exit_code must be 1..255 (a process "
                    f"exit status), got {cfg.exit_code}"
                )
            if cfg.exit_code == _WATCHDOG_EXIT_CODE:
                return (
                    f"ResilienceConfig.exit_code {cfg.exit_code} collides "
                    f"with the health watchdog's exit code — supervisors "
                    f"classify 'drained cleanly' vs 'hung and self-killed' "
                    f"on that difference; pick another code"
                )
            if not cfg.preempt_signals:
                return (
                    "ResilienceConfig.preempt_signals is empty — the "
                    "preemption handler would never arm; name at least one "
                    "signal or drop the config"
                )
            import signal as _signal

            for name in cfg.preempt_signals:
                if not isinstance(name, str) or getattr(
                    _signal, name, None
                ) is None:
                    return (
                        f"ResilienceConfig.preempt_signals names unknown "
                        f"signal {name!r} (e.g. 'SIGTERM', 'SIGUSR1')"
                    )
            if cfg.max_to_keep is not None and cfg.max_to_keep < 1:
                return (
                    f"ResilienceConfig.max_to_keep must be >= 1 or None, "
                    f"got {cfg.max_to_keep}"
                )
            ckpt = self._configs.get("CheckpointConfig")
            if (
                ckpt is not None
                and ckpt.auto_path
                and cfg.save_name == ckpt.auto_name
                and os.path.abspath(cfg.save_path)
                == os.path.abspath(ckpt.auto_path)
            ):
                return (
                    f"ResilienceConfig.save_name {cfg.save_name!r} "
                    f"collides with CheckpointConfig.auto_name under the "
                    f"same directory — the two save cadences would prune "
                    f"each other's tags; rename one or separate the paths"
                )
            spec = (
                cfg.chaos if cfg.chaos is not None
                else os.environ.get(CHAOS_ENV)
            )
            try:
                parse_chaos(spec)
            except ValueError as e:
                return str(e)
            err = _probe_writable(cfg.save_path)
            if err is not None:
                return (
                    f"ResilienceConfig.save_path {cfg.save_path!r} is not "
                    f"writable: {err}"
                )
            return False

        def _compile_invalid(s):
            """Compile-cache legality (ISSUE 6): the cache directory must
            be writable on EVERY process (each serializes its own step
            executables), and the XLA-cache persistence threshold must be
            a sane duration."""
            cfg = self._configs.get("CompileConfig")
            if cfg is None:
                return False
            if cfg.min_compile_time_s < 0:
                return (
                    f"CompileConfig.min_compile_time_s must be >= 0, got "
                    f"{cfg.min_compile_time_s}"
                )
            if not (cfg.aot or cfg.xla_cache):
                return (
                    "CompileConfig with aot=False and xla_cache=False "
                    "caches nothing — enable a layer or drop the config"
                )
            err = _probe_writable(cfg.cache_dir)
            if err is not None:
                return (
                    f"CompileConfig.cache_dir {cfg.cache_dir!r} is not "
                    f"writable: {err}"
                )
            return False

        def _trace_invalid(s):
            """Structured-tracing legality (ISSUE 10): the recorder's ring
            must be able to hold at least one span, and — since EVERY rank
            exports its own ``trace.rank<N>.json`` — an unwritable output
            dir is fatal on every process, not only rank 0.  The config is
            purely host-side; its presence never touches the compiled step
            programs (default-OFF contract, tests/test_tracing.py asserts
            HLO bit-identity)."""
            cfg = self._configs.get("TraceConfig")
            if cfg is None:
                return False
            if cfg.ring_size < 1:
                return (
                    f"TraceConfig.ring_size must be >= 1, got "
                    f"{cfg.ring_size}"
                )
            if cfg.export_on_close:
                err = _probe_writable(cfg.output_dir)
                if err is not None:
                    return (
                        f"TraceConfig.output_dir {cfg.output_dir!r} is not "
                        f"writable: {err}"
                    )
            return False

        def _serve_invalid(s):
            """Serving-stack legality (ISSUE 9): a ServeConfig that could
            never admit a request, that names an unknown kernel/dtype/
            quant mode, or whose block pool cannot hold even one
            max-length sequence is rejected at construction — not at the
            first ``serve()`` call mid-deployment.  The config is only
            READ by ``Stoke.serve()``; its presence never touches the
            training paths (default-OFF contract, tests/test_serving.py
            asserts HLO bit-identity)."""
            cfg = self._configs.get("ServeConfig")
            if cfg is None:
                return False
            for field in ("max_seqs", "kv_block_size", "max_seq_len",
                          "max_new_tokens", "prefill_pad_multiple",
                          "log_every_n_steps"):
                if getattr(cfg, field) < 1:
                    return (
                        f"ServeConfig.{field} must be >= 1, got "
                        f"{getattr(cfg, field)}"
                    )
            if cfg.attention not in SERVE_ATTENTION_KERNELS:
                return (
                    f"ServeConfig.attention {cfg.attention!r} unknown; "
                    f"valid: {list(SERVE_ATTENTION_KERNELS)}"
                )
            if cfg.decode_kernel not in SERVE_DECODE_KERNELS:
                return (
                    f"ServeConfig.decode_kernel {cfg.decode_kernel!r} "
                    f"unknown; valid: {list(SERVE_DECODE_KERNELS)}"
                )
            if (
                cfg.decode_kernel == "pallas"
                and s["device"] is DeviceOptions.cpu
            ):
                # the streaming kernel is a TPU fast path; a REAL serve
                # config on a CPU device would silently run the pallas
                # INTERPRETER (orders of magnitude slower than the
                # reference kernel it exists to beat).  Tests exercise
                # interpreter parity through ServingEngine directly.
                return (
                    "ServeConfig.decode_kernel='pallas' on device='cpu': "
                    "the streaming decode kernel needs a TPU backend — "
                    "use decode_kernel='reference' on CPU (the pallas "
                    "interpreter parity mode is for tests, via a "
                    "standalone ServingEngine)"
                )
            for field in ("decode_pages_per_block", "decode_block_h"):
                v = getattr(cfg, field)
                if v is not None and v < 1:
                    return (
                        f"ServeConfig.{field} must be >= 1 when set, "
                        f"got {v}"
                    )
                if v is not None and cfg.decode_kernel != "pallas":
                    # same contract as the sampling-knob rule below: a
                    # knob the selected kernel never reads is rejected,
                    # never silently ignored
                    return (
                        f"ServeConfig.{field}={v} set but decode_kernel="
                        f"{cfg.decode_kernel!r} — only the pallas "
                        f"streaming kernel reads the block knobs; set "
                        f"decode_kernel='pallas' or drop the knob"
                    )
            if cfg.prefill_chunk_tokens is not None:
                c = cfg.prefill_chunk_tokens
                if c < 1:
                    return (
                        f"ServeConfig.prefill_chunk_tokens must be >= 1, "
                        f"got {c}"
                    )
                if c % cfg.prefill_pad_multiple:
                    return (
                        f"ServeConfig.prefill_chunk_tokens={c} must be a "
                        f"multiple of prefill_pad_multiple="
                        f"{cfg.prefill_pad_multiple} — chunk shapes ride "
                        f"the same bucket discipline that bounds compiled-"
                        f"program count"
                    )
                if c > cfg.max_seq_len:
                    return (
                        f"ServeConfig.prefill_chunk_tokens={c} exceeds "
                        f"max_seq_len={cfg.max_seq_len} — no prompt could "
                        f"ever be chunked"
                    )
            if cfg.temperature < 0.0:
                return (
                    f"ServeConfig.temperature must be >= 0, got "
                    f"{cfg.temperature}"
                )
            if cfg.top_k is not None and cfg.top_k < 1:
                return (
                    f"ServeConfig.top_k must be >= 1 when set, got "
                    f"{cfg.top_k}"
                )
            if cfg.top_p is not None and not (0.0 < cfg.top_p <= 1.0):
                return (
                    f"ServeConfig.top_p must be in (0, 1] when set, got "
                    f"{cfg.top_p}"
                )
            if not cfg.sampling and (
                cfg.temperature != 0.0
                or cfg.top_k is not None
                or cfg.top_p is not None
            ):
                # a sampled-looking config that silently serves greedy is
                # the chaos-spec anti-pattern: never ignore, always name
                # the remedy
                return (
                    "ServeConfig sampling knobs set (temperature/top_k/"
                    "top_p) but sampling=False — the greedy programs "
                    "would silently ignore them; set sampling=True or "
                    "drop the knobs"
                )
            if cfg.quant not in SERVE_QUANT_MODES:
                return (
                    f"ServeConfig.quant {cfg.quant!r} unknown; valid: "
                    f"{list(SERVE_QUANT_MODES)}"
                )
            if cfg.kv_dtype not in SERVE_KV_DTYPES:
                return (
                    f"ServeConfig.kv_dtype {cfg.kv_dtype!r} unknown; "
                    f"valid: {list(SERVE_KV_DTYPES)}"
                )
            if cfg.quant_chunk_elems < 1:
                return (
                    f"ServeConfig.quant_chunk_elems must be >= 1, got "
                    f"{cfg.quant_chunk_elems}"
                )
            if cfg.quant_min_size < 0:
                return (
                    f"ServeConfig.quant_min_size must be >= 0 (leaves "
                    f"below it stay unquantized), got {cfg.quant_min_size}"
                )
            if cfg.eos_id is not None and cfg.eos_id < 0:
                return (
                    f"ServeConfig.eos_id must be a token id >= 0 when "
                    f"set (None = run to the token cap), got {cfg.eos_id}"
                )
            if cfg.prefill_pad_multiple > cfg.max_seq_len:
                return (
                    f"ServeConfig.prefill_pad_multiple "
                    f"{cfg.prefill_pad_multiple} exceeds max_seq_len "
                    f"{cfg.max_seq_len} — every padded prompt would be "
                    f"rejected"
                )
            if cfg.kv_blocks is not None:
                # one max-length sequence needs ceil(max_seq_len/bs)
                # blocks, plus the reserved scratch block 0
                need = -(-cfg.max_seq_len // cfg.kv_block_size) + 1
                if cfg.kv_blocks < need:
                    return (
                        f"ServeConfig.kv_blocks={cfg.kv_blocks} cannot "
                        f"hold one max_seq_len={cfg.max_seq_len} sequence "
                        f"(needs {need} blocks of {cfg.kv_block_size} "
                        f"tokens incl. the reserved scratch block 0) — no "
                        f"request could ever be admitted"
                    )
            for field in ("slo_ttft_target_s", "slo_tpot_target_s"):
                v = getattr(cfg, field)
                if v is not None and not v > 0.0:
                    # a non-positive deadline is violated before the
                    # request even arrives — reject with the remedy, not
                    # a 100%-violation dashboard mystery (ISSUE 16)
                    return (
                        f"ServeConfig.{field} must be > 0 seconds when "
                        f"set, got {v} (None = requests carry their own "
                        f"RequestSLO targets)"
                    )
            # speculative decoding (ISSUE 17): same knob discipline as
            # sampling — misconfigurations name the remedy, knobs a
            # disabled feature would silently ignore are rejected
            if cfg.speculative_k is not None:
                if cfg.speculative_k < 1:
                    return (
                        f"ServeConfig.speculative_k must be >= 1 when set "
                        f"(None = speculative decoding off), got "
                        f"{cfg.speculative_k}"
                    )
                if not cfg.sampling:
                    return (
                        f"ServeConfig.speculative_k={cfg.speculative_k} "
                        f"needs sampling=True — the verify program rides "
                        f"the key-threaded sampling programs "
                        f"(temperature=0.0 keeps exact greedy streams); "
                        f"set sampling=True or drop speculative_k"
                    )
                if (
                    cfg.prefill_chunk_tokens is not None
                    and cfg.speculative_k + 1 > cfg.prefill_chunk_tokens
                ):
                    return (
                        f"ServeConfig.speculative_k={cfg.speculative_k} "
                        f"puts the verify query width (k+1="
                        f"{cfg.speculative_k + 1}) over the chunk budget "
                        f"prefill_chunk_tokens={cfg.prefill_chunk_tokens} "
                        f"— the multi-token programs share that "
                        f"per-iteration bound; shrink speculative_k or "
                        f"raise prefill_chunk_tokens"
                    )
                if cfg.speculative_ngram_min < 1:
                    return (
                        f"ServeConfig.speculative_ngram_min must be >= 1, "
                        f"got {cfg.speculative_ngram_min}"
                    )
                if cfg.speculative_ngram_max < cfg.speculative_ngram_min:
                    return (
                        f"ServeConfig.speculative_ngram_max="
                        f"{cfg.speculative_ngram_max} < "
                        f"speculative_ngram_min="
                        f"{cfg.speculative_ngram_min} — the drafter's "
                        f"n-gram range is empty"
                    )
            else:
                if (
                    cfg.speculative_ngram_max != 3
                    or cfg.speculative_ngram_min != 1
                ):
                    return (
                        "ServeConfig speculative drafter knobs set "
                        "(speculative_ngram_max/speculative_ngram_min) "
                        "but speculative_k=None — the non-speculative "
                        "engine would silently ignore them; set "
                        "speculative_k or drop the knobs"
                    )
            for field in ("verify_pages_per_block", "verify_block_h"):
                v = getattr(cfg, field)
                if v is None:
                    continue
                if v < 1:
                    return (
                        f"ServeConfig.{field} must be >= 1 when set, "
                        f"got {v}"
                    )
                if cfg.speculative_k is None:
                    return (
                        f"ServeConfig.{field}={v} set but "
                        f"speculative_k=None — only the speculative "
                        f"verify kernel reads the verify block knobs; "
                        f"set speculative_k or drop the knob"
                    )
                if cfg.decode_kernel != "pallas":
                    return (
                        f"ServeConfig.{field}={v} set but decode_kernel="
                        f"{cfg.decode_kernel!r} — the verify block knobs "
                        f"feed the pallas verify kernel; set "
                        f"decode_kernel='pallas' or drop the knob"
                    )
            # roofline observatory (ISSUE 18): the cost cards divide by
            # hardware peaks — both roofline legs need a ceiling, so an
            # AttributionConfig with a positive HBM bandwidth is required
            # (peak_tflops > 0 the attribution rule already enforces)
            if cfg.cost_cards:
                attr = self._configs.get("AttributionConfig")
                if attr is None:
                    return (
                        "ServeConfig.cost_cards=True requires an "
                        "AttributionConfig — the serve roofline divides "
                        "by its peak_tflops / peak_hbm_gbps ceilings; "
                        "add one or drop cost_cards"
                    )
                if attr.peak_hbm_gbps <= 0:
                    return (
                        f"ServeConfig.cost_cards=True needs "
                        f"AttributionConfig.peak_hbm_gbps > 0 (the "
                        f"memory leg of the decode roofline — attainable "
                        f"TPOT is bandwidth-bound), got "
                        f"{attr.peak_hbm_gbps}"
                    )
            return False

        def _remat_invalid(s):
            """Rematerialization legality (ISSUE 15 knob-coverage lint):
            a typo'd checkpoint policy previously surfaced as a bare
            AttributeError at the FIRST step compile, deep inside the
            engine — validate it here with the remedy named instead."""
            cfg = self._configs.get("ActivationCheckpointingConfig")
            if cfg is None:
                return False
            import jax

            if not isinstance(cfg.policy, str) or not hasattr(
                jax.checkpoint_policies, cfg.policy
            ):
                return (
                    f"ActivationCheckpointingConfig.policy {cfg.policy!r} "
                    f"is not a jax.checkpoint_policies member — use e.g. "
                    f"'nothing_saveable', 'dots_saveable', "
                    f"'dots_with_no_batch_dims_saveable', or "
                    f"'everything_saveable'"
                )
            return False

        def _precision_scaler_invalid(s):
            """Loss-scaler knob sanity (ISSUE 15 knob-coverage lint): a
            non-positive scale or a backoff that GROWS the scale is a
            scaler that can never recover from overflow — a typo, not a
            tuning choice.  Checked whenever a PrecisionConfig is
            supplied (the values must be sane even while fp16 is off)."""
            cfg = self._configs.get("PrecisionConfig")
            if cfg is None:
                return False
            if cfg.init_scale <= 0 or cfg.min_scale <= 0:
                return (
                    f"PrecisionConfig.init_scale/min_scale must be > 0, "
                    f"got {cfg.init_scale}/{cfg.min_scale}"
                )
            if cfg.growth_factor < 1.0:
                return (
                    f"PrecisionConfig.growth_factor must be >= 1 (growth "
                    f"never shrinks the scale), got {cfg.growth_factor}"
                )
            if not (0.0 < cfg.backoff_factor <= 1.0):
                return (
                    f"PrecisionConfig.backoff_factor must be in (0, 1] "
                    f"(backoff never grows the scale), got "
                    f"{cfg.backoff_factor}"
                )
            if cfg.growth_interval < 1:
                return (
                    f"PrecisionConfig.growth_interval must be >= 1, got "
                    f"{cfg.growth_interval}"
                )
            return False

        def _fsdp_pref_invalid(s):
            """A typo'd ``shard_axis_preference`` previously fell through
            to the 'largest' branch silently (ISSUE 15 knob-coverage
            lint caught it; parallel/sharding.py dispatches on the
            string)."""
            cfg = self._configs.get("FSDPConfig")
            if cfg is None:
                return False
            if cfg.shard_axis_preference not in ("largest", "first"):
                return (
                    f"FSDPConfig.shard_axis_preference "
                    f"{cfg.shard_axis_preference!r} unknown; valid: "
                    f"['largest', 'first'] — any other value would "
                    f"silently act as 'largest'"
                )
            return False

        def _offload_cpu_no_fallback(s):
            for name in ("OffloadOptimizerConfig", "OffloadParamsConfig"):
                cfg = self._configs.get(name)
                if (
                    cfg is not None
                    and not cfg.fallback_to_device
                    and s["device"] is DeviceOptions.cpu
                ):
                    return (
                        f"{name}(fallback_to_device=False) on device='cpu': "
                        f"the CPU runtime has no pinned_host memory kind; "
                        f"allow fallback or use device='tpu'"
                    )
            return False

        def _param_offload_requires_fsdp(s):
            return "OffloadParamsConfig" in self._configs and not s["fsdp"]

        def _offload_tier_conflict(s):
            return (
                "OffloadDiskConfig" in self._configs
                and "OffloadOptimizerConfig" in self._configs
            )

        return [
            (
                lambda s: s["batch_size_per_device"] is None
                or s["batch_size_per_device"] < 1,
                "batch_size_per_device must be >= 1",
            ),
            (
                lambda s: s["grad_accum"] < 1,
                "grad_accum must be >= 1",
            ),
            (
                lambda s: s["grad_clip"] is not None
                and not isinstance(s["grad_clip"], (ClipGradConfig, ClipGradNormConfig)),
                "grad_clip must be ClipGradConfig, ClipGradNormConfig, or None",
            ),
            # clip-bound sanity (ISSUE 15 knob-coverage lint): a zero or
            # negative bound zeroes/flips every gradient — a typo, never
            # a tuning choice; norm_type < 1 is not a norm
            (
                lambda s: isinstance(s["grad_clip"], ClipGradConfig)
                and s["grad_clip"].clip_value <= 0,
                "ClipGradConfig.clip_value must be > 0 (an elementwise "
                "bound of 0 zeroes every gradient)",
            ),
            (
                lambda s: isinstance(s["grad_clip"], ClipGradNormConfig)
                and (
                    s["grad_clip"].max_norm <= 0
                    or s["grad_clip"].norm_type < 1
                ),
                "ClipGradNormConfig needs max_norm > 0 and norm_type >= 1 "
                "(inf is legal)",
            ),
            # per-loss scalers are an fp16 feature (reference: Apex
            # num_losses configures amp loss scalers, fp16.py:656-691;
            # full/bf16 have no scaler to multiply)
            (
                lambda s: (
                    (pc := self._configs.get("PrecisionConfig")) is not None
                    and pc.num_losses != 1
                    and (
                        pc.num_losses < 1
                        or s["precision"] is not PrecisionOptions.fp16
                    )
                ),
                "PrecisionConfig.num_losses > 1 (per-loss scalers) requires "
                "precision='fp16' and num_losses >= 1 — reference Apex "
                "num_losses, fp16.py:656-691",
            ),
            # sharding ladder legality (reference status.py:239-263):
            # SDDP requires OSS (status.py:240-243)
            (
                lambda s: s["sddp"] and not s["oss"],
                "sddp (gradient sharding) requires oss (optimizer-state "
                "sharding) — reference status.py:240-243",
            ),
            # FSDP subsumes and excludes OSS/SDDP (reference status.py:244-263)
            (
                lambda s: s["fsdp"] and (s["oss"] or s["sddp"]),
                "fsdp (fully-sharded) already shards optimizer state and "
                "gradients; combining with oss/sddp is illegal — reference "
                "status.py:244-263",
            ),
            # sharding requires the distributed engine (reference: fairscale
            # extensions require DDP, status.py:231-263)
            (
                lambda s: (s["oss"] or s["sddp"] or s["fsdp"])
                and s["distributed"] is None,
                "sharding tiers (oss/sddp/fsdp) require distributed='dp' — "
                "reference status.py:231-263",
            ),
            # --- configs supplied but structurally ignored (fail loud at
            # init instead of silently doing nothing / erroring at compile) ---
            (
                _ignored_without_distributed("MeshConfig"),
                "MeshConfig supplied but distributed=None; the mesh would be "
                "silently ignored — set distributed='dp' or drop the config",
            ),
            (
                _ignored_without_distributed("PartitionRulesConfig"),
                "PartitionRulesConfig supplied but distributed=None; the "
                "rules would be silently ignored — set distributed='dp' or "
                "drop the config",
            ),
            # --- mesh-axis consistency (a bad axis otherwise surfaces as a
            # cryptic GSPMD error at compile time) ---
            (
                _mesh_shape_mismatch,
                "MeshConfig axes/shape inconsistent",
            ),
            (
                _partition_rule_axis_unknown,
                "partition rule names an unknown mesh axis",
            ),
            (
                _seq_axis_missing,
                "sequence-dim sharding configured without a seq mesh axis",
            ),
            (
                _tier_axis_missing,
                "sharding tier's data axis missing from the mesh",
            ),
            # --- dependency checks ---
            (
                _tensorboard_writable,
                "TensorboardConfig output path is not writable",
            ),
            (
                _telemetry_invalid,
                "TelemetryConfig is invalid",
            ),
            (
                _profiler_invalid,
                "ProfilerConfig.trace_dir is not writable",
            ),
            (
                _comm_invalid,
                "CommConfig is invalid for this combination",
            ),
            (
                _health_invalid,
                "HealthConfig is invalid for this combination",
            ),
            (
                _attribution_invalid,
                "AttributionConfig is invalid for this combination",
            ),
            (
                _fleet_invalid,
                "FleetConfig is invalid for this combination",
            ),
            (
                _numerics_invalid,
                "NumericsConfig is invalid for this combination",
            ),
            (
                _memory_invalid,
                "MemoryConfig is invalid for this combination",
            ),
            (
                _opsplane_invalid,
                "OpsPlaneConfig is invalid for this combination",
            ),
            (
                _checkpoint_invalid,
                "CheckpointConfig is invalid",
            ),
            (
                _resilience_invalid,
                "ResilienceConfig is invalid",
            ),
            (
                _compile_invalid,
                "CompileConfig is invalid",
            ),
            (
                _serve_invalid,
                "ServeConfig is invalid",
            ),
            (
                _trace_invalid,
                "TraceConfig is invalid",
            ),
            (
                _remat_invalid,
                "ActivationCheckpointingConfig.policy is invalid",
            ),
            (
                _precision_scaler_invalid,
                "PrecisionConfig scaler knobs are invalid",
            ),
            (
                _fsdp_pref_invalid,
                "FSDPConfig.shard_axis_preference is invalid",
            ),
            (
                _offload_cpu_no_fallback,
                "offload config with fallback_to_device=False on device='cpu'",
            ),
            (
                _param_offload_requires_fsdp,
                "OffloadParamsConfig requires fsdp=True — parameter offload "
                "is a ZeRO-3 feature (reference DeepspeedOffloadParamConfig "
                "legal only at stage 3, configs.py:346-372)",
            ),
            (
                _offload_tier_conflict,
                "OffloadDiskConfig and OffloadOptimizerConfig are mutually "
                "exclusive — one offload tier per state (reference: a single "
                "offload_optimizer device choice, configs.py:309-343)",
            ),
        ]

    def _check_all_raised_combinations(self) -> None:
        for predicate, message in self._rules():
            result = predicate(self._status)
            if result:
                msg = result if isinstance(result, str) else message
                raise StokeValidationError(f"Stoke -- illegal combination: {msg}")

    # ------------------------------------------------------------------ #
    # Post-init values (reference status.py:345-372, effective batch :373-375)
    # ------------------------------------------------------------------ #

    def set_post_init_values(
        self, world_size: int, n_processes: int = 1
    ) -> None:
        """Record device/process topology once the engine exists (reference
        ``set_post_init_values``, status.py:345; effective batch size calc
        status.py:373-375)."""
        self._status["world_size"] = world_size
        self._status["n_devices"] = world_size
        self._status["n_processes"] = n_processes
        self._status["effective_batch_size"] = (
            self._status["batch_size_per_device"]
            * world_size
            * self._status["grad_accum"]
        )

    # ------------------------------------------------------------------ #
    # Flag accessors
    # ------------------------------------------------------------------ #

    @property
    def status(self) -> Dict[str, Any]:
        """Canonical status dict (reference status.py:171-188)."""
        return dict(self._status)

    @property
    def batch_size(self) -> int:
        return self._status["batch_size_per_device"]

    @property
    def effective_batch_size(self) -> Optional[int]:
        return self._status["effective_batch_size"]

    @property
    def grad_accum(self) -> int:
        return self._status["grad_accum"]

    @property
    def grad_clip(self):
        return self._status["grad_clip"]

    @property
    def device(self) -> DeviceOptions:
        return self._status["device"]

    @property
    def is_tpu(self) -> bool:
        return self._status["device"] is DeviceOptions.tpu

    @property
    def distributed(self) -> Optional[DistributedOptions]:
        return self._status["distributed"]

    @property
    def is_distributed(self) -> bool:
        return self._status["distributed"] is not None

    @property
    def precision(self) -> PrecisionOptions:
        return self._status["precision"]

    @property
    def is_scaled_precision(self) -> bool:
        """True when a dynamic loss scaler is in play (fp16 only; bf16 needs
        none — SURVEY.md §3.2 hot-loop observation (c))."""
        return self._status["precision"] is PrecisionOptions.fp16

    @property
    def oss(self) -> bool:
        return self._status["oss"]

    @property
    def sddp(self) -> bool:
        return self._status["sddp"]

    @property
    def fsdp(self) -> bool:
        return self._status["fsdp"]

    @property
    def sharding_tier(self) -> ShardingOptions:
        """Collapse the three booleans to the ladder rung (post-validation the
        combinations are mutually consistent)."""
        if self._status["fsdp"]:
            return ShardingOptions.fsdp
        if self._status["sddp"]:
            return ShardingOptions.sddp
        if self._status["oss"]:
            return ShardingOptions.oss
        return ShardingOptions.none

    @property
    def world_size(self) -> Optional[int]:
        return self._status["world_size"]

    # ------------------------------------------------------------------ #
    # Lazily-materialized per-concern configs (reference status.py:473-627)
    # ------------------------------------------------------------------ #

    def _get_or_default(self, cls):
        name = cls.__name__
        if name not in self._configs:
            self._configs[name] = cls()
        return self._configs[name]

    @property
    def precision_config(self) -> PrecisionConfig:
        return self._get_or_default(PrecisionConfig)

    @property
    def dp_config(self) -> DataParallelConfig:
        return self._get_or_default(DataParallelConfig)

    @property
    def mesh_config(self) -> MeshConfig:
        return self._get_or_default(MeshConfig)

    @property
    def dist_init_config(self) -> DistributedInitConfig:
        return self._get_or_default(DistributedInitConfig)

    @property
    def oss_config(self) -> OSSConfig:
        return self._get_or_default(OSSConfig)

    @property
    def sddp_config(self) -> SDDPConfig:
        return self._get_or_default(SDDPConfig)

    @property
    def fsdp_config(self) -> FSDPConfig:
        return self._get_or_default(FSDPConfig)

    @property
    def comm_config(self) -> Optional[CommConfig]:
        """None unless explicitly supplied (the gradient-transport layer is
        opt-in and defaults OFF; without it gradients sync through the
        compiler-inserted fp32 collectives exactly as before)."""
        return self._configs.get("CommConfig")

    @property
    def partition_rules_config(self):
        """None unless explicitly supplied (tensor parallelism is opt-in)."""
        return self._configs.get("PartitionRulesConfig")

    @property
    def offload_optimizer_config(self):
        """None unless explicitly supplied (offload is opt-in, reference
        configs.py:309-343)."""
        return self._configs.get("OffloadOptimizerConfig")

    @property
    def offload_params_config(self):
        """None unless explicitly supplied (param offload is opt-in and
        fsdp-only, reference configs.py:346-372)."""
        return self._configs.get("OffloadParamsConfig")

    @property
    def offload_disk_config(self):
        """None unless explicitly supplied (disk/NVMe tier is opt-in,
        reference DeepspeedAIOConfig configs.py:192-221)."""
        return self._configs.get("OffloadDiskConfig")

    @property
    def activation_checkpointing_config(self) -> Optional[ActivationCheckpointingConfig]:
        """None unless explicitly supplied (remat is opt-in, matching the
        reference where activation checkpointing is DeepSpeed-only
        passthrough, configs.py:222-248)."""
        return self._configs.get("ActivationCheckpointingConfig")

    @property
    def checkpoint_config(self) -> CheckpointConfig:
        return self._get_or_default(CheckpointConfig)

    @property
    def profiler_config(self) -> ProfilerConfig:
        return self._get_or_default(ProfilerConfig)

    @property
    def tensorboard_config(self):
        """None unless explicitly supplied (metrics logging is opt-in,
        reference configs.py:392-405)."""
        return self._configs.get("TensorboardConfig")

    @property
    def health_config(self) -> Optional[HealthConfig]:
        """None unless explicitly supplied (the health monitor is opt-in;
        without it the step paths are bit-identical to pre-ISSUE-3)."""
        return self._configs.get("HealthConfig")

    @property
    def attribution_config(self) -> Optional[AttributionConfig]:
        """None unless explicitly supplied (step-time attribution is
        opt-in; without it the step paths run no cost analysis and the
        compiled programs are bit-identical to pre-ISSUE-4)."""
        return self._configs.get("AttributionConfig")

    @property
    def fleet_config(self) -> Optional[FleetConfig]:
        """None unless explicitly supplied (fleet observability is
        opt-in; without it no cross-host exchange ever runs and the step
        paths are bit-identical to pre-ISSUE-5)."""
        return self._configs.get("FleetConfig")

    @property
    def numerics_config(self) -> Optional[NumericsConfig]:
        """None unless explicitly supplied (the per-layer numerics
        observatory is opt-in; without it the compiled step programs are
        bit-identical to pre-ISSUE-12)."""
        return self._configs.get("NumericsConfig")

    @property
    def memory_config(self) -> Optional[MemoryConfig]:
        """None unless explicitly supplied (the HBM capacity observatory
        is opt-in; without it no ``mem/*`` field or gauge exists and the
        compiled programs are bit-identical to pre-ISSUE-19)."""
        return self._configs.get("MemoryConfig")

    @property
    def opsplane_config(self) -> Optional[OpsPlaneConfig]:
        """None unless explicitly supplied (the live ops plane is
        opt-in; without it no thread starts and no socket binds, and the
        step paths are bit-identical to pre-ISSUE-20)."""
        return self._configs.get("OpsPlaneConfig")

    @property
    def resilience_config(self) -> Optional[ResilienceConfig]:
        """None unless explicitly supplied (pod-scale resilience is
        opt-in; without it the step paths, signal dispositions, and
        checkpoint layout are bit-identical to pre-ISSUE-7)."""
        return self._configs.get("ResilienceConfig")

    @property
    def compile_config(self) -> Optional[CompileConfig]:
        """None unless explicitly supplied (the persistent compilation
        cache is opt-in; without it the engine dispatches its jit
        programs exactly as before — bit-identical HLO)."""
        return self._configs.get("CompileConfig")

    @property
    def serve_config(self) -> Optional[ServeConfig]:
        """None unless explicitly supplied (the serving stack is opt-in
        and only read by ``Stoke.serve()``; without — or even with — the
        config the training step paths are bit-identical to pre-ISSUE-9)."""
        return self._configs.get("ServeConfig")

    @property
    def telemetry_config(self) -> Optional[TelemetryConfig]:
        """None unless explicitly supplied (the unified telemetry pipeline
        is opt-in; a None config keeps the facade's registry alive but
        attaches no sinks/collectors)."""
        return self._configs.get("TelemetryConfig")

    @property
    def trace_config(self) -> Optional[TraceConfig]:
        """None unless explicitly supplied (structured tracing is opt-in;
        without it no span recorder is registered and the composed span
        helper degrades to the bare xprof annotation)."""
        return self._configs.get("TraceConfig")

    # ------------------------------------------------------------------ #
    # Serialization / display (reference status.py:629-654)
    # ------------------------------------------------------------------ #

    def to_dict(self) -> Dict[str, Any]:
        """JSON-friendly dump for checkpoints (reference saves the status dict
        inside every checkpoint, io_ops.py:224-236)."""
        out = {}
        for k, v in self._status.items():
            if hasattr(v, "value") and not isinstance(v, (int, float, str)):
                v = v.value
            elif isinstance(v, (ClipGradConfig, ClipGradNormConfig)):
                v = {"type": type(v).__name__, **asdict_config(v)}
            out[k] = v
        out["configs"] = {k: asdict_config(v) for k, v in self._configs.items()}
        return out

    def __repr__(self) -> str:  # reference status.py:629-654
        lines = ["Stoke -- Status:"]
        for k, v in self.to_dict().items():
            lines.append(f"  {k}: {v}")
        return "\n".join(lines)

"""Paged KV-cache: block pool + per-request block tables + attention hook.

ISSUE 9 pillar 1.  Serving memory is dominated by the KV-cache, and naive
per-request contiguous caches fragment HBM so badly that batch size — the
thing TPU serving throughput actually scales with (arXiv:2605.25645) — is
capped by the WORST-case sequence length.  The paged layout (vLLM lineage)
fixes that: one pool of fixed-size blocks, per-request block tables mapping
sequence position -> (block, offset), freed blocks refilling mid-flight as
requests complete.

Three pieces:

- :class:`BlockAllocator` — host-side free list over the pool.  Block 0 is
  RESERVED as a scratch block: inactive decode slots write their (discarded)
  K/V there, so the compiled decode program always runs the full fixed-shape
  slot batch with no active-mask branching.
- :class:`PagedKVCache` — the device arrays: ``[n_layers, n_blocks,
  block_size, heads, head_dim]`` K and V page planes, created zeroed on the
  target device/mesh.  The serving engine threads them functionally through
  its compiled programs (donated, so updates are in-place in HBM).
- :class:`PagedAttentionHook` — the per-trace bridge into ``models/gpt.py``:
  ``GPT(..., kv_cache=hook)`` asks it for one attention fn per layer.  In
  prefill mode the fn writes the prompt's K/V into the slot's blocks and
  runs ordinary causal attention (dense or the flash kernel) over the
  prompt; in decode mode it writes the single fresh token's K/V and attends
  over the gathered cached blocks
  (:func:`stoke_tpu.ops.flash_attention.paged_decode_attention`).  The hook
  carries the updated page arrays across layers within one trace; the
  caller reads them back after ``apply`` and returns them from the jitted
  program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from stoke_tpu.models.bert import dense_attention
from stoke_tpu.ops.flash_attention import (
    flash_attention,
    paged_decode_attention,
    paged_decode_attention_pallas,
    paged_prefill_chunk_attention,
    paged_verify_attention,
    paged_verify_attention_pallas,
)

#: block id every unused block-table entry (and every inactive slot) points
#: at — allocated to no request, read by nothing meaningful
SCRATCH_BLOCK = 0


class BlockAllocator:
    """Host-side free list over the KV block pool (block 0 reserved).

    Pure bookkeeping — never touches a device.  The scheduler allocates a
    request's FULL worst-case block budget at admission (prompt + token
    cap), so a mid-flight decode step can never fail on an empty pool;
    freed blocks return to the tail and are reused by later admissions
    (tests assert occupancy returns to 0 after drain).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"BlockAllocator needs >= 2 blocks (one is the reserved "
                f"scratch block {SCRATCH_BLOCK}), got {num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(1, num_blocks))

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache entries."""
        return -(-max(int(n_tokens), 1) // self.block_size)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        """Blocks currently owned by requests (scratch excluded)."""
        return (self.num_blocks - 1) - len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (pool minus the scratch block)."""
        return self.num_blocks - 1

    @property
    def occupancy(self) -> float:
        """Fraction of the allocatable pool currently owned (the
        ``serve/kv_block_occupancy`` gauge)."""
        return self.used_blocks / max(self.capacity, 1)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` blocks, or None (allocator unchanged) when the pool
        cannot supply them — the scheduler then keeps the request queued."""
        if n > len(self._free):
            return None
        taken, self._free = self._free[:n], self._free[n:]
        return taken

    def free(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            if b == SCRATCH_BLOCK:
                raise ValueError("cannot free the reserved scratch block")
            if b in self._free:
                raise ValueError(f"double free of KV block {b}")
            self._free.append(int(b))


class PagedKVCache:
    """The device-side block pool: K and V page planes per layer.

    Layout ``[n_layers, n_blocks, block_size, heads, head_dim]`` — layer
    outermost so each layer's hook update is one static-index plane, block
    next so a request's window gathers as per-block slices out of HBM.

    ``sharding`` (optional ``jax.sharding.Sharding``) places the pool on
    the serving mesh — replicated by default (data-parallel serving
    replicas each own a full pool; a model-sharded pool over a heads axis
    is a placement change here, not a layout change).
    """

    def __init__(
        self,
        n_layers: int,
        num_blocks: int,
        block_size: int,
        heads: int,
        head_dim: int,
        dtype=jnp.float32,
        sharding=None,
    ):
        self.n_layers = int(n_layers)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.dtype = jnp.dtype(dtype)
        shape = (n_layers, num_blocks, block_size, heads, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        if sharding is not None:
            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.k_pages = k
        self.v_pages = v

    @property
    def nbytes(self) -> int:
        """HBM footprint of the pool (both planes)."""
        return int(self.k_pages.size + self.v_pages.size) * self.dtype.itemsize


def _flatten_heads(t):
    """[B, H, L, D] attention layout -> [B*L, H, D] page-write layout."""
    B, H, L, D = t.shape
    return jnp.swapaxes(t, 1, 2).reshape(B * L, H, D)


class PagedAttentionHook:
    """Per-trace cache bridge for ``GPT(..., kv_cache=hook)``.

    Constructed INSIDE the serving engine's jitted prefill/decode programs
    around the (donated) page arrays; ``layer_attention(i)`` returns the
    attention fn layer ``i``'s transformer block calls.  Page updates are
    functional (``.at[].set``) and threaded through ``self.k_pages`` /
    ``self.v_pages`` so the program returns the updated pool.

    Args:
        k_pages / v_pages: ``[n_layers, NB, BS, H, D]`` pool planes.
        block_tables: ``[B, MAX_BLOCKS] int32`` per-slot block ids.
        positions: ``[B, L] int32`` token positions being written this
            call (prefill: ``arange`` rows; decode: each slot's current
            position, L == 1).
        mode: ``"prefill"``, ``"chunk"`` (chunked prefill, ISSUE 13),
            ``"decode"``, or ``"verify"`` (speculative k-token verify,
            ISSUE 17 — chunk-style positional writes/attention, plus
            save-before-write so :meth:`rollback` can restore rejected
            draft positions exactly).
        lengths: ``[B] int32`` — prefill/chunk: true prompt lengths
            (padding positions write to the scratch block and are
            masked); decode: context lengths INCLUDING the fresh token;
            verify: context + draft length + 1 (the write budget —
            padding query rows past it steer to scratch).
        attention_impl: prefill kernel, ``"dense"`` or ``"flash"``.
        decode_impl: decode kernel — ``"reference"`` (the jnp
            gathered-block :func:`paged_decode_attention`) or
            ``"pallas"`` (the ISSUE 13 streaming kernel
            :func:`paged_decode_attention_pallas`).
        decode_pages_per_block / decode_block_h: the pallas kernel's
            block knobs (``None`` = its defaults; autotune catalog
            entries).
        decode_interpret: run the pallas kernel through the interpreter
            (``None`` = auto off-TPU — the CPU parity mode).
        verify_pages_per_block / verify_block_h: the verify kernel's
            block knobs (``None`` = its defaults; autotune catalog
            entries ``verify_pages_per_block`` / ``verify_block_h``).
            ``decode_impl`` selects reference vs pallas for verify too —
            both kernels share the streaming memory schedule.
    """

    def __init__(
        self,
        k_pages,
        v_pages,
        block_tables,
        positions,
        *,
        mode: str,
        lengths,
        attention_impl: str = "dense",
        decode_impl: str = "reference",
        decode_pages_per_block: Optional[int] = None,
        decode_block_h: Optional[int] = None,
        decode_interpret: Optional[bool] = None,
        verify_pages_per_block: Optional[int] = None,
        verify_block_h: Optional[int] = None,
    ):
        if mode not in ("prefill", "chunk", "decode", "verify"):
            raise ValueError(f"unknown PagedAttentionHook mode {mode!r}")
        if decode_impl not in ("reference", "pallas"):
            raise ValueError(
                f"unknown PagedAttentionHook decode_impl {decode_impl!r}; "
                f"valid: ['reference', 'pallas']"
            )
        self.k_pages = k_pages
        self.v_pages = v_pages
        self.block_tables = block_tables
        self.positions = positions
        self.mode = mode
        self.lengths = lengths
        self.attention_impl = attention_impl
        self.decode_impl = decode_impl
        self.decode_pages_per_block = decode_pages_per_block
        self.decode_block_h = decode_block_h
        self.decode_interpret = decode_interpret
        self.verify_pages_per_block = verify_pages_per_block
        self.verify_block_h = verify_block_h
        self.block_size = int(k_pages.shape[2])
        # verify mode: per-layer (blocks, offs, old_k, old_v) snapshots
        # taken before each write, consumed by rollback()
        self._saved: List[tuple] = []

    # ------------------------------ writes ----------------------------- #

    def _write_layer(self, layer: int, k, v) -> None:
        """Scatter this call's fresh K/V into layer ``layer``'s planes.

        Valid (position < budget) tokens land at ``(block_table[b,
        pos // BS], pos % BS)``; invalid ones — prompt padding, inactive
        decode slots are steered by their all-scratch block tables — land
        in the scratch block, which nothing reads.  Distinct live slots
        own distinct blocks, so in-batch writes never collide.
        """
        B, L = self.positions.shape
        pos = self.positions.reshape(-1)  # [B*L]
        slot = jnp.repeat(jnp.arange(B, dtype=jnp.int32), L)
        blk_idx = pos // self.block_size
        if self.mode in ("prefill", "chunk", "verify"):
            # chunk rows past the prompt end (the last chunk's padding)
            # carry clamped positions >= the prompt length, so the same
            # predicate steers them to scratch; verify's lengths bound
            # the real write window (context + draft + 1) the same way
            valid = (
                self.positions
                < self.lengths[:, None].astype(self.positions.dtype)
            ).reshape(-1)
        else:
            valid = jnp.ones_like(pos, dtype=bool)
        # clamp the table column so padding positions past the allocated
        # window index legally, then steer invalid writes to scratch
        blk_idx = jnp.minimum(blk_idx, self.block_tables.shape[1] - 1)
        blocks = self.block_tables[slot, blk_idx]
        blocks = jnp.where(valid, blocks, SCRATCH_BLOCK)
        offs = pos % self.block_size
        if self.mode == "verify":
            # snapshot what the write clobbers so rollback() can undo the
            # rejected tail exactly — acceptance is only known after the
            # forward, but the chunk-attention semantics need the draft
            # K/V resident DURING it
            old_k = self.k_pages[layer, blocks, offs]
            old_v = self.v_pages[layer, blocks, offs]
            self._saved.append((blocks, offs, old_k, old_v))
        kw = _flatten_heads(k).astype(self.k_pages.dtype)
        vw = _flatten_heads(v).astype(self.v_pages.dtype)
        self.k_pages = self.k_pages.at[layer, blocks, offs].set(kw)
        self.v_pages = self.v_pages.at[layer, blocks, offs].set(vw)

    def rollback(self, n_keep) -> None:
        """Restore every verify write PAST the accepted window (ISSUE 17).

        Called after acceptance is computed, inside the same trace: query
        row ``i`` of slot ``b`` keeps its written K/V iff ``i <
        n_keep[b]``; every other row's destination is restored to the
        snapshot ``_write_layer`` took.  Restores are steered like
        writes: kept rows' restore targets flip to the scratch block
        (their old values land somewhere nothing reads), so the scatter
        stays fixed-shape with no branching, and rejected draft
        positions never dirty the cache across dispatches.

        Args:
            n_keep: ``[B] int32`` accepted-row counts (the sampling
                layer's ``n_emit``).
        """
        if self.mode != "verify":
            raise ValueError(
                f"rollback() is a verify-mode operation; hook mode is "
                f"{self.mode!r}"
            )
        B, L = self.positions.shape
        within = jnp.tile(jnp.arange(L, dtype=jnp.int32), B)
        slot = jnp.repeat(jnp.arange(B, dtype=jnp.int32), L)
        keep = within < n_keep.astype(jnp.int32)[slot]
        for layer, (blocks, offs, old_k, old_v) in enumerate(self._saved):
            blocks_r = jnp.where(keep, SCRATCH_BLOCK, blocks)
            self.k_pages = self.k_pages.at[layer, blocks_r, offs].set(old_k)
            self.v_pages = self.v_pages.at[layer, blocks_r, offs].set(old_v)

    # ----------------------------- attention --------------------------- #

    def layer_attention(self, layer: int):
        """The ``attention_fn`` (bert.py signature) for layer ``layer``."""

        def attention_fn(q, k, v, bias, dropout_rng=None, dropout_rate=0.0,
                         deterministic=True):
            if dropout_rate > 0.0 and not deterministic:
                raise NotImplementedError(
                    "paged-cache attention is inference-only; attention "
                    "dropout is not supported"
                )
            self._write_layer(layer, k, v)
            if self.mode == "decode":
                if self.decode_impl == "pallas":
                    return paged_decode_attention_pallas(
                        q,
                        self.k_pages[layer],
                        self.v_pages[layer],
                        self.block_tables,
                        self.lengths,
                        pages_per_block=self.decode_pages_per_block,
                        block_h=self.decode_block_h,
                        interpret=self.decode_interpret,
                    )
                return paged_decode_attention(
                    q,
                    self.k_pages[layer],
                    self.v_pages[layer],
                    self.block_tables,
                    self.lengths,
                )
            if self.mode == "verify":
                # speculative verify: S = k+1 query rows attend the paged
                # prefix (draft K/V just written) under the chunk-style
                # positional predicate; reference delegates to the chunk
                # attention, pallas streams pages once for all S rows
                if self.decode_impl == "pallas":
                    return paged_verify_attention_pallas(
                        q,
                        self.k_pages[layer],
                        self.v_pages[layer],
                        self.block_tables,
                        self.positions,
                        pages_per_block=self.verify_pages_per_block,
                        block_h=self.verify_block_h,
                        interpret=self.decode_interpret,
                    )
                return paged_verify_attention(
                    q,
                    self.k_pages[layer],
                    self.v_pages[layer],
                    self.block_tables,
                    self.positions,
                )
            if self.mode == "chunk":
                # chunked prefill: the chunk's K/V were just written, so
                # attention is one paged gather masked causally by GLOBAL
                # position — earlier chunks' prefix and the intra-chunk
                # causal mask fall out of the same predicate
                return paged_prefill_chunk_attention(
                    q,
                    self.k_pages[layer],
                    self.v_pages[layer],
                    self.block_tables,
                    self.positions,
                )
            # prefill: ordinary causal attention over the (padded) prompt
            # — the pages were just written for DECODE's benefit; the
            # prompt itself is fully in registers/VMEM here, so the
            # training-side kernels serve it unchanged
            B, H, L, D = q.shape
            key_valid = (
                jnp.arange(L, dtype=jnp.int32)[None, :]
                < self.lengths[:, None].astype(jnp.int32)
            )  # [B, L]
            if self.attention_impl == "flash":
                return flash_attention(
                    q, k, v, key_valid.astype(jnp.int32), causal=True
                )
            causal = jnp.tril(jnp.ones((L, L), bool))
            allow = causal[None, None, :, :] & key_valid[:, None, None, :]
            pbias = jnp.where(allow, 0.0, -1e9).astype(q.dtype)
            return dense_attention(q, k, v, pbias)

        return attention_fn

"""Serve roofline observatory (ISSUE 18): per-dispatch cost cards,
bandwidth-bound TPOT ceilings, and achieved-vs-attainable accounting.

The serving stack is five program families deep (prefill, chunk, packed
chunk, decode, speculative verify) and reports tokens/s against an SLO
(PR 16) — but nothing says how far any number sits from the hardware
ceiling.  This module closes that gap with the PR-10 cost-card machinery
(:class:`~stoke_tpu.telemetry.attribution.CostCardCache`, generalized
with a ``counter_prefix``): one XLA cost analysis per (program, shape
signature) at the engine's ``_dispatch`` funnel, per-dispatch FLOP/byte
counters, and a decode **roofline** —

- arithmetic intensity per program (FLOPs / byte accessed);
- attainable TPOT = ``max(bytes/HBM-BW, flops/peak)`` of the decode-
  family program at the ``AttributionConfig`` peaks, vs the achieved
  per-dispatch decode wall (``decode_s / decode_steps``);
- per-program bound classification (steady-state decode is memory-bound
  on every real accelerator; the speculative verify program's k-token
  intensity uplift over plain decode is a *measured* gauge here, closing
  the loop on PR 17's tokens-per-dispatch claim);
- model-FLOPs-per-token for the per-request cost attribution the
  ``SLOTracker`` turns into an SLO-aware TFLOP-goodput column.

Everything is host-side bookkeeping over programs the engine compiles
anyway: with ``ServeConfig.cost_cards`` off nothing here is constructed
and the dispatched serve programs are HLO bit-identical (the PR-16
``audit_specs`` discipline); with it on, the only extra work is one
``cost_analysis`` per program signature (lowering-only) plus one
``memory_analysis`` compile per signature for the peak-HBM attachment.

The ``serve/cost_*`` JSONL block is conditional — absent, not null,
without the config (the ``serve/slo_*`` discipline), and its field list
is pinned append-only in ``analysis/manifests/wire_formats.json``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from stoke_tpu.telemetry.attribution import (
    CostCard,
    CostCardCache,
    cost_analysis_of,
    roofline_time_s,
)

#: the ``serve/cost_*`` JSONL field block (ISSUE 18) — emitted only by
#: engines with ``ServeConfig.cost_cards`` on (the default-OFF contract:
#: unconfigured records carry zero new fields).  Pinned append-only by
#: the ``analysis/manifests/wire_formats.json`` manifest.
COST_FIELDS = (
    "serve/cost_flops",
    "serve/cost_bytes",
    "serve/cost_flops_per_token",
    "serve/cost_mfu",
    "serve/cost_hbm_bw_util",
    "serve/cost_attainable_tpot_s",
    "serve/cost_achieved_tpot_s",
    "serve/cost_decode_intensity",
    "serve/cost_verify_intensity",
    "serve/cost_decode_bound",
    "serve/cost_cards",
)


def program_bound(
    card: Optional[CostCard], peak_tflops: float, peak_hbm_gbps: float
) -> Optional[str]:
    """Per-program roofline bound: ``"memory"`` when the bandwidth leg of
    the roofline dominates (``bytes/BW >= flops/peak``), ``"compute"``
    otherwise; ``None`` without both peaks or reported bytes.  Distinct
    from the per-window :func:`~stoke_tpu.telemetry.attribution
    .classify_bound` — this is an analytic property of ONE program, not a
    measured window."""
    if (
        card is None
        or not card.bytes_accessed
        or card.flops <= 0
        or peak_tflops <= 0
        or peak_hbm_gbps <= 0
    ):
        return None
    memory_s = card.bytes_accessed / (peak_hbm_gbps * 1e9)
    compute_s = card.flops / (peak_tflops * 1e12)
    return "memory" if memory_s >= compute_s else "compute"


class ServeCostObservatory:
    """Cost accounting over one serving engine's dispatch funnel.

    Constructed by :class:`~stoke_tpu.serving.engine.ServingEngine` when
    ``ServeConfig.cost_cards`` is on (the facade supplies the run's
    ``AttributionConfig`` peaks).  The engine calls :meth:`note_dispatch`
    beside its audit-spec funnel — one cost analysis per (program, shape
    signature), every dispatch accumulating the card's analytic FLOPs /
    bytes into the ``serve/cost/*`` registry counters — and
    :meth:`refresh` at its gauge cadence.
    """

    #: the decode-family programs, in the order a per-token TPOT ceiling
    #: should prefer them (a speculative engine dispatches verify INSTEAD
    #: of plain decode — its ceiling is the verify program's)
    _DECODE_FAMILY = ("serve_verify", "serve_decode")

    def __init__(
        self,
        metrics,
        peak_tflops: float = 0.0,
        peak_hbm_gbps: float = 0.0,
        *,
        memory_analysis: bool = True,
    ):
        self.metrics = metrics
        self.registry = metrics.registry
        self.peak_tflops = float(peak_tflops)
        self.peak_hbm_gbps = float(peak_hbm_gbps)
        self.cache = CostCardCache(
            metrics.registry,
            peak_tflops,
            peak_hbm_gbps,
            counter_prefix="serve/cost",
            memory_analysis=memory_analysis,
        )
        metrics.enable_cost()
        #: dispatch count per (program, shape-signature) key — with the
        #: per-key cards this recombines EXACTLY into the counter totals
        #: (sum over keys of card.flops * dispatches == flops_total; the
        #: tests/test_serve_cost.py recombination contract)
        self.dispatch_counts: Dict[Tuple[str, Any], int] = {}
        #: most recent card per program NAME (the roofline reads the
        #: decode-family member)
        self.program_cards: Dict[str, CostCard] = {}
        #: analytic card of the plain-decode program a speculative engine
        #: never dispatches — the comparison leg the verify-intensity
        #: uplift is measured against (set by the engine, lowering-only)
        self.baseline_decode_card: Optional[CostCard] = None

    # ------------------------------ feeds ------------------------------ #

    def note_dispatch(self, program: str, fn, args: tuple, sig) -> None:
        """Per-dispatch hook (the engine's ``_dispatch`` funnel): first
        call per (program, signature) runs the cost analysis; every call
        books the card's analytic FLOPs/bytes and the dispatch count."""
        key = (program, sig)
        card = self.cache.note_dispatch(key, program, fn, args, steps=0)
        self.dispatch_counts[key] = self.dispatch_counts.get(key, 0) + 1
        if card is not None and card.flops > 0:
            self.program_cards[program] = card

    def set_decode_baseline(self, fn, abstract_args: tuple) -> None:
        """Cost-analyze the plain-decode program from its ABSTRACT args
        (lowering only — never dispatched, never counted): a speculative
        engine routes every decode-family dispatch through the verify
        program, so its intensity uplift needs this counterfactual."""
        cost = cost_analysis_of(fn, *abstract_args)
        if cost is None:
            return
        self.baseline_decode_card = CostCard.from_cost(
            cost, "serve_decode", 0, self.peak_tflops, self.peak_hbm_gbps
        )

    # ----------------------------- derived ----------------------------- #

    def _decode_card(self) -> Optional[CostCard]:
        """The decode-family card the TPOT roofline reads (verify for a
        speculative engine, plain decode otherwise)."""
        for program in self._DECODE_FAMILY:
            card = self.program_cards.get(program)
            if card is not None:
                return card
        return None

    def _plain_decode_card(self) -> Optional[CostCard]:
        """Plain decode's card: live when this engine dispatches it, the
        lowered baseline otherwise."""
        return self.program_cards.get("serve_decode") or (
            self.baseline_decode_card
        )

    def flops_total(self) -> float:
        return self.registry.counter("serve/cost/flops_total").value

    def bytes_total(self) -> float:
        return self.registry.counter("serve/cost/bytes_total").value

    def cards_total(self) -> int:
        return int(
            self.registry.counter("serve/cost/cost_cards_total").value
        )

    def flops_per_token(self) -> Optional[float]:
        """Model FLOPs per EMITTED token — cumulative analytic FLOPs over
        cumulative tokens out (prefill included: that IS the per-request
        serving cost).  The per-request attribution the SLO TFLOP-goodput
        column multiplies through."""
        tokens = self.metrics.tokens_out.value
        flops = self.flops_total()
        if tokens <= 0 or flops <= 0:
            return None
        return flops / tokens

    def attainable_tpot_s(self) -> Optional[float]:
        """Roofline-optimal seconds per decode-family DISPATCH — the
        bandwidth-bound TPOT ceiling (one token per request per dispatch
        for plain decode; a verify dispatch's per-token ceiling is this
        over its accepted-tokens-per-dispatch)."""
        card = self._decode_card()
        if card is None:
            return None
        return roofline_time_s(
            card.flops,
            card.bytes_accessed,
            self.peak_tflops,
            self.peak_hbm_gbps,
        )

    def achieved_tpot_s(self) -> Optional[float]:
        """Measured decode wall per dispatch (same unit as
        :meth:`attainable_tpot_s`; their ratio is the roofline gap)."""
        steps = self.metrics.decode_steps.value
        if steps <= 0:
            return None
        return self.metrics.decode_s.value / steps

    def decode_intensity(self) -> Optional[float]:
        card = self._plain_decode_card()
        return card.intensity if card is not None else None

    def verify_intensity(self) -> Optional[float]:
        card = self.program_cards.get("serve_verify")
        return card.intensity if card is not None else None

    def decode_bound(self) -> Optional[str]:
        """Analytic bound class of the decode-family program ("memory" /
        "compute") — steady-state decode should classify memory-bound."""
        return program_bound(
            self._decode_card(), self.peak_tflops, self.peak_hbm_gbps
        )

    def mfu(self) -> Optional[float]:
        """Serve MFU: analytic FLOPs over dispatch-BUSY wall seconds
        (prefill + decode — queue/idle time excluded: an empty engine is
        idle, not slow) against the configured peak."""
        busy = (
            self.metrics.prefill_s.value + self.metrics.decode_s.value
        )
        flops = self.flops_total()
        if busy <= 0 or flops <= 0 or self.peak_tflops <= 0:
            return None
        return flops / busy / 1e12 / self.peak_tflops

    def hbm_bw_util(self) -> Optional[float]:
        """HBM bandwidth utilization over dispatch-busy seconds."""
        busy = (
            self.metrics.prefill_s.value + self.metrics.decode_s.value
        )
        nbytes = self.bytes_total()
        if busy <= 0 or nbytes <= 0 or self.peak_hbm_gbps <= 0:
            return None
        return nbytes / busy / (self.peak_hbm_gbps * 1e9)

    # ----------------------------- gauges ------------------------------ #

    def refresh_gauges(self) -> None:
        """Publish the achieved-vs-attainable gauges (engine gauge
        cadence) and feed the SLO tracker's per-token cost."""
        reg = self.registry
        for name, v in (
            ("serve/cost/mfu", self.mfu()),
            ("serve/cost/hbm_bw_util", self.hbm_bw_util()),
            ("serve/cost/attainable_tpot_s", self.attainable_tpot_s()),
            ("serve/cost/achieved_tpot_s", self.achieved_tpot_s()),
            ("serve/cost/flops_per_token", self.flops_per_token()),
            ("serve/cost/decode_intensity", self.decode_intensity()),
            ("serve/cost/verify_intensity", self.verify_intensity()),
        ):
            if v is not None:
                reg.gauge(name).set(v)

    # --------------------------- JSONL fields --------------------------- #

    def event_fields(self) -> Dict[str, Any]:
        """The conditional ``serve/cost_*`` block of one JSONL serve
        record — only engines constructed with ``cost_cards`` carry an
        observatory at all, so unconfigured records stay byte-identical
        to pre-ISSUE-18 ones (``build_step_event`` honors the omission,
        the ``serve/slo_*`` discipline)."""
        return {
            "serve/cost_flops": self.flops_total(),
            "serve/cost_bytes": self.bytes_total(),
            "serve/cost_flops_per_token": self.flops_per_token(),
            "serve/cost_mfu": self.mfu(),
            "serve/cost_hbm_bw_util": self.hbm_bw_util(),
            "serve/cost_attainable_tpot_s": self.attainable_tpot_s(),
            "serve/cost_achieved_tpot_s": self.achieved_tpot_s(),
            "serve/cost_decode_intensity": self.decode_intensity(),
            "serve/cost_verify_intensity": self.verify_intensity(),
            "serve/cost_decode_bound": self.decode_bound(),
            "serve/cost_cards": float(self.cards_total()),
        }

    # ----------------------------- summary ----------------------------- #

    def summary(self) -> Dict[str, Any]:
        """The cost block of ``ServingEngine.summary()``: per-program
        cards, the decode roofline, and the verify-over-decode intensity
        uplift (None until both cards exist)."""
        decode_i = self.decode_intensity()
        verify_i = self.verify_intensity()
        return {
            "active": True,
            "peak_tflops": self.peak_tflops,
            "peak_hbm_gbps": self.peak_hbm_gbps,
            "flops_total": self.flops_total(),
            "bytes_total": self.bytes_total(),
            "flops_per_token": self.flops_per_token(),
            "mfu": self.mfu(),
            "hbm_bw_util": self.hbm_bw_util(),
            "attainable_tpot_s": self.attainable_tpot_s(),
            "achieved_tpot_s": self.achieved_tpot_s(),
            "decode_bound": self.decode_bound(),
            "decode_intensity": decode_i,
            "verify_intensity": verify_i,
            "verify_intensity_uplift": (
                verify_i / decode_i
                if verify_i is not None and decode_i
                else None
            ),
            "cards": {
                program: card.to_dict()
                for program, card in sorted(self.program_cards.items())
            },
            "baseline_decode_card": (
                self.baseline_decode_card.to_dict()
                if self.baseline_decode_card is not None
                else None
            ),
        }

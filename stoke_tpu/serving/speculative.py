"""Host-side self-speculative drafting for the serving engine (ISSUE 17).

Speculative decoding needs a cheap guess at the next k tokens; this module
is the guesser.  It is a **prompt-lookup / n-gram drafter**: the only
model it consults is the request's own token history (prompt + everything
emitted so far), which the scheduler already owns on the host — no second
model, no new weights, no device work.  The bet is the one prompt-lookup
decoding makes: generated text constantly re-quotes its own context
(code, summaries, structured output, any loop the model falls into), so
the continuation of the most recent earlier occurrence of the current
tail n-gram is a strong draft.

The drafter is allowed to be wrong — the verify program
(``serving/engine.py``) scores every draft position against the real
model in one dispatch and the accept rule keeps only the leading exact
matches, so a bad draft costs nothing but the wasted query rows.  It is
**not** allowed to be slow or to touch the device: `propose_draft` is
plain Python over the host-side history and runs once per request per
decode iteration.

Knobs (``ServeConfig.speculative_ngram_max`` / ``speculative_ngram_min``)
bound the matched tail length: longer matches are tried first (more
specific ⇒ higher acceptance when they hit), falling back to shorter
ones down to ``ngram_min``.
"""

from __future__ import annotations

from typing import List, Sequence

__all__ = ["propose_draft"]


def propose_draft(
    history: Sequence[int],
    k: int,
    *,
    ngram_max: int = 3,
    ngram_min: int = 1,
) -> List[int]:
    """Propose up to ``k`` draft tokens continuing ``history``.

    For each n from ``ngram_max`` down to ``ngram_min``, the last n
    tokens of ``history`` are the search pattern; the MOST RECENT earlier
    occurrence of that pattern wins (recency tracks the local repetition
    structure better than the first occurrence), and the tokens that
    followed it are returned as the draft.  First n that matches wins —
    longer patterns are more specific, so their continuations are
    accepted more often.

    Args:
        history: the request's full token history, prompt + emitted, in
            order.  The next real token continues this sequence.
        k: maximum draft length (``ServeConfig.speculative_k``).
        ngram_max / ngram_min: tail-pattern length bounds, inclusive.

    Returns up to ``k`` proposed tokens (possibly empty — no match, or
    history too short).  Never raises on degenerate inputs; config
    validation happens in ``status.py``.
    """
    h = list(history)
    L = len(h)
    if k <= 0 or L < ngram_min + 1:
        return []
    for n in range(min(ngram_max, L - 1), ngram_min - 1, -1):
        pattern = h[L - n:]
        # most recent earlier occurrence: scan candidate start positions
        # from the right; the match may overlap the tail's own window as
        # long as it starts earlier (periodic text matches itself).
        for start in range(L - n - 1, -1, -1):
            if h[start:start + n] == pattern:
                # start < L - n guarantees at least one continuation
                # token; the continuation may run into the tail window
                # itself — that is fine, those ARE the latest tokens.
                return h[start + n:start + n + k]
    return []

"""Serving-side weight quantization: int8/bf16 params, matmul-side dequant.

ISSUE 9 pillar 4.  Serving replicas are HBM-capacity-bound — every byte of
weights is a byte the KV-cache (and therefore the batch size throughput
scales with) cannot have (arXiv:2605.25645).  The PR-2 gradient-wire
quantizer already ships the exact primitive needed: per-chunk-absmax int8
with optional unbiased stochastic rounding
(:func:`stoke_tpu.parallel.collectives.quantize_chunks` /
``dequantize_chunks`` — arXiv:2506.17615 wire format).  This module points
it at the PARAMS instead of the gradients: quantize once at engine build
("load time"), keep int8 payloads + f32 chunk scales in HBM, dequantize
inside the compiled prefill/decode programs right before the matmuls
(XLA fuses the dequant into the consumer; the stored tree stays int8).

``quantize_params`` walks the param pytree and replaces every float leaf
with ``ndim >= 2`` and ``size >= min_size`` (matmul kernels, embeddings —
the bytes that matter) by a :class:`QuantizedTensor`; biases/layernorm
scales stay untouched (quantizing them saves ~nothing and costs accuracy).
``dequantize_params`` is the in-program inverse.  ``param_bytes`` gives the
HBM accounting both the telemetry gauge and the acceptance test
(compression >= 3.5x for int8) read.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.parallel.collectives import (
    dequantize_chunks,
    quantize_chunks,
)


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """One int8-quantized weight: payload + per-chunk f32 scales.

    A pytree node (payload/scales are children) so quantized param trees
    thread through ``jax.jit`` like any other param tree; shape/dtype/pad
    ride as static aux data.
    """

    def __init__(self, q, scales, shape: Tuple[int, ...], dtype, pad: int,
                 chunk: int):
        self.q = q              # int8 [padded_elems]
        self.scales = scales    # f32 [padded_elems / chunk]
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.pad = int(pad)
        self.chunk = int(chunk)

    def dequantize(self):
        flat = dequantize_chunks(self.q, self.scales, self.chunk)
        if self.pad:
            flat = flat[: flat.shape[0] - self.pad]
        return flat.reshape(self.shape).astype(self.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.q.size) + 4 * int(self.scales.size)

    def tree_flatten(self):
        return (self.q, self.scales), (
            self.shape, str(self.dtype), self.pad, self.chunk
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        shape, dtype, pad, chunk = aux
        return cls(children[0], children[1], shape, dtype, pad, chunk)

    def __repr__(self):
        return (
            f"QuantizedTensor(shape={self.shape}, chunk={self.chunk}, "
            f"bytes={self.nbytes})"
        )


def _is_quantizable(leaf, min_size: int) -> bool:
    return (
        hasattr(leaf, "shape")
        and getattr(leaf, "ndim", 0) >= 2
        and leaf.size >= min_size
        and jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    )


def _quantize_leaf(leaf, chunk: int, stochastic: bool, key) -> QuantizedTensor:
    x = jnp.asarray(leaf, jnp.float32).reshape(-1)
    pad = (-x.shape[0]) % chunk
    if pad:
        x = jnp.pad(x, (0, pad))
    q, scales = quantize_chunks(
        x, chunk, rng=key if stochastic else None, stochastic=stochastic
    )
    return QuantizedTensor(
        q, scales, np.shape(leaf), jnp.asarray(leaf).dtype, pad, chunk
    )


def quantize_params(
    params: Any,
    mode: str,
    *,
    chunk_elems: int = 128,
    stochastic: bool = False,
    min_size: int = 1024,
    seed: int = 0,
) -> Any:
    """Quantize a param pytree for serving.

    ``mode``: ``"none"`` returns ``params`` untouched; ``"bf16"`` casts
    every float leaf to bfloat16 (2x); ``"int8"`` replaces quantizable
    leaves (ndim >= 2, size >= ``min_size``) with
    :class:`QuantizedTensor` (~3.9x on those leaves).  ``stochastic=True``
    uses the PR-2 unbiased stochastic rounding (one fold_in key per leaf);
    the default round-to-nearest is lower-variance for a one-shot cast.
    """
    if mode == "none":
        return params
    if mode == "bf16":
        return jax.tree_util.tree_map(
            lambda l: (
                l.astype(jnp.bfloat16)
                if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                else l
            ),
            params,
        )
    if mode != "int8":
        raise ValueError(f"unknown quantization mode {mode!r}")
    leaves, treedef = jax.tree_util.tree_flatten(params)
    base = jax.random.PRNGKey(seed)
    out = []
    for i, leaf in enumerate(leaves):
        if _is_quantizable(leaf, min_size):
            out.append(
                _quantize_leaf(
                    leaf, chunk_elems, stochastic, jax.random.fold_in(base, i)
                )
            )
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def dequantize_params(qparams: Any) -> Any:
    """In-program inverse: rebuild the dense param tree (quantized leaves
    dequantize to their original shape/dtype; bf16 leaves upcast to f32 so
    downstream matmul accumulation matches the unquantized path's dtype)."""
    return jax.tree_util.tree_map(
        lambda l: (
            l.dequantize()
            if isinstance(l, QuantizedTensor)
            else (
                l.astype(jnp.float32)
                if hasattr(l, "dtype") and l.dtype == jnp.bfloat16
                else l
            )
        ),
        qparams,
        is_leaf=lambda l: isinstance(l, QuantizedTensor),
    )


def quantization_error(
    params: Any, qparams: Any, eps: float = 1e-12
) -> Dict[str, Dict[str, float]]:
    """Per-leaf dequantization error of a quantized param tree, computed
    ONCE at quantize time (ISSUE 12 signal family 3b).

    For every :class:`QuantizedTensor` leaf the int8 round trip is
    compared against its fp source: ``abs_err_max`` is the worst absolute
    element error, ``rel_rms`` the rms error relative to the source rms —
    the scale-free "how much of this layer's signal did int8 eat" number
    the per-layer quality attribution ranks by.  Keys are ``"a/b/c"``
    leaf-path strings (``telemetry.numerics.leaf_path_names`` order), so
    ``telemetry.numerics.quant_error_by_group`` folds them straight into
    module groups.  Unquantized leaves are omitted.
    """
    import jax.tree_util as jtu

    # the join keys MUST be the numerics module's leaf-path rendering —
    # quant_error_by_group matches them against leaf_path_names(params)
    # verbatim, so reusing the one implementation is the contract
    from stoke_tpu.telemetry.numerics import leaf_path_names

    is_q = lambda l: isinstance(l, QuantizedTensor)  # noqa: E731
    paths = leaf_path_names(params)
    src = jtu.tree_leaves(params)
    qleaves = jtu.tree_leaves(qparams, is_leaf=is_q)
    if len(src) != len(qleaves):
        raise ValueError(
            f"quantization_error: params has {len(src)} leaves but "
            f"qparams has {len(qleaves)} — pass the SAME tree the "
            f"quantizer consumed"
        )
    out: Dict[str, Dict[str, float]] = {}
    for key, leaf, q in zip(paths, src, qleaves):
        if not isinstance(q, QuantizedTensor):
            continue
        orig = np.asarray(leaf, np.float64)
        deq = np.asarray(q.dequantize(), np.float64)
        err = deq - orig
        rms_src = float(np.sqrt(np.mean(orig ** 2)))
        out[key] = {
            "abs_err_max": float(np.max(np.abs(err))),
            "rel_rms": float(
                np.sqrt(np.mean(err ** 2)) / (rms_src + eps)
            ),
        }
    return out


def param_bytes(tree: Any) -> int:
    """HBM bytes of a (possibly quantized) param tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
        tree, is_leaf=lambda l: isinstance(l, QuantizedTensor)
    ):
        if isinstance(leaf, QuantizedTensor):
            total += leaf.nbytes
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def compression_stats(params: Any, qparams: Any) -> Dict[str, float]:
    """``{param_bytes_fp, param_bytes_quant, compression}`` — the serve
    telemetry gauge + JSONL fields and the >= 3.5x acceptance read these."""
    fp = param_bytes(params)
    q = param_bytes(qparams)
    return {
        "param_bytes_fp": float(fp),
        "param_bytes_quant": float(q),
        "compression": float(fp) / float(q) if q else 1.0,
    }

"""Serve SLO observatory (ISSUE 16): per-request deadlines, violation
attribution, and goodput-under-SLO accounting.

ROADMAP item 1 wants SLO-aware admission and priority preemption, but the
scheduler cannot act on SLOs it cannot see: the serving stack reports
aggregate TTFT/TPOT percentiles (PR 9) and per-request spans (PR 10) with
no notion of a deadline, a priority class, or which phase of a request's
life burned its budget.  This module is the measurement substrate that
admission controller will consume — built first, so the control policy
lands on proven signals:

- :class:`RequestSLO` — per-request deadline metadata (priority class +
  TTFT/TPOT targets), validated at ``submit()`` like ``SamplingParams``
  and never mid-decode.  Targets left ``None`` resolve from the
  ``ServeConfig.slo_ttft_target_s`` / ``slo_tpot_target_s`` defaults.
- :class:`SLOTracker` — per-priority-class TTFT/TPOT attainment
  fractions, goodput-under-SLO tokens/s (the arXiv:2605.25645 measuring
  stick: only tokens whose request met its deadline count), deadline-
  headroom gauges for in-flight requests, a per-class queue-ETA
  forecaster over running admission-wait histograms, and **violation
  attribution** that re-walks each finished request's PR-10 span
  timeline (``serve/admission`` → ``serve/prefill`` /
  ``serve/prefill_chunk`` → ``serve/decode``) into queue-wait /
  prefill-blocked / decode-contention buckets that provably sum to the
  request's measured end-to-end latency.

Everything here is purely host-side bookkeeping: the tracker never
enters a dispatch argument list, so the compiled serve programs are
bit-identical with and without SLOs, and an engine that never sees an
SLO-tagged request emits zero new JSONL fields (the ``serve/slo_*``
block is conditional — the ISSUE 14 rebalance-fields discipline).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional

from stoke_tpu.serving.telemetry import LATENCY_BUCKETS, _Reservoir

#: span names whose wall belongs to the prefill phase of a request's
#: timeline (the PR-10 request track)
_PREFILL_SPANS = ("serve/prefill", "serve/prefill_chunk")

#: finished-request attributions kept per tracker (oldest evicted) — the
#: bounded-ring discipline every other host-side store here follows
_MAX_ATTRIBUTIONS = 4096


@dataclass(frozen=True)
class RequestSLO:
    """Per-request service-level objective (validated at ``submit()``).

    Attributes:
        priority: the request's priority class name — the key every
            per-class attainment/goodput/queue-ETA series is bucketed
            under (e.g. ``"interactive"`` vs ``"batch"``).  Classes are a
            small closed set chosen by the caller; the tracker's gauge
            cardinality follows it.
        ttft_target_s: time-to-first-token deadline in seconds (arrival →
            first generated token, queue time included).  ``None`` =
            resolve from ``ServeConfig.slo_ttft_target_s``.
        tpot_target_s: time-per-output-token target in seconds (mean over
            the decode tokens).  ``None`` = resolve from
            ``ServeConfig.slo_tpot_target_s``.
    """

    priority: str = "default"
    ttft_target_s: Optional[float] = None
    tpot_target_s: Optional[float] = None


def validate_request_slo(slo: RequestSLO) -> None:
    """Reject an impossible SLO at submit time, not mid-decode (the
    ``SamplingParams`` contract)."""
    if not isinstance(slo.priority, str) or not slo.priority:
        raise ValueError(
            f"RequestSLO.priority must be a non-empty class name, got "
            f"{slo.priority!r}"
        )
    for field in ("ttft_target_s", "tpot_target_s"):
        v = getattr(slo, field)
        if v is not None and not v > 0.0:
            raise ValueError(
                f"RequestSLO.{field} must be > 0 when set, got {v} "
                f"(None = resolve from the ServeConfig default)"
            )


def resolve_request_slo(
    slo: RequestSLO,
    ttft_default: Optional[float],
    tpot_default: Optional[float],
) -> RequestSLO:
    """Validate ``slo`` and fill its unset targets from the ServeConfig
    defaults; a deadline-free SLO (no target anywhere) is rejected —
    nothing about it could ever be attained or violated."""
    validate_request_slo(slo)
    resolved = replace(
        slo,
        ttft_target_s=(
            slo.ttft_target_s
            if slo.ttft_target_s is not None
            else ttft_default
        ),
        tpot_target_s=(
            slo.tpot_target_s
            if slo.tpot_target_s is not None
            else tpot_default
        ),
    )
    if resolved.ttft_target_s is None and resolved.tpot_target_s is None:
        raise ValueError(
            "RequestSLO carries no deadline: set ttft_target_s/"
            "tpot_target_s on the RequestSLO or configure "
            "ServeConfig.slo_ttft_target_s / slo_tpot_target_s defaults "
            "(an SLO with no target can never be attained or violated)"
        )
    return resolved


def attribute_request(
    req, spans: List[Any], dropped: int
) -> Dict[str, Any]:
    """Re-walk one finished request's span timeline into latency buckets.

    The three buckets — queue-wait (arrival → admission), prefill-blocked
    (admission → first token: the request's own prefill dispatches plus
    the time it sat blocked behind co-batched work), decode-contention
    (first token → finish: the shared batch decode interval) — come from
    the request's lifecycle timestamps, so they sum to the measured
    end-to-end latency by construction.  The PR-10 spans refine them:
    ``prefill_active_s`` / ``decode_active_s`` are the wall the request's
    OWN ``serve/prefill``/``serve/prefill_chunk``/``serve/decode`` spans
    dispatched (the remainder of each bucket is contention), and the
    ``serve/admission`` span cross-checks the queue bucket.

    ``span_coverage`` is honest about the ring: ``"full"`` only when the
    recorder dropped nothing and every expected span of this request is
    present; ``"partial"`` when spans were evicted or missing (a
    truncated ring must not masquerade as a complete attribution);
    ``"none"`` when no recorder was active (the timestamp buckets still
    hold — only the active/contention split is unavailable).
    """
    queue_wait = max(req.admit_ts - req.arrival_ts, 0.0)
    prefill_blocked = max(req.first_token_ts - req.admit_ts, 0.0)
    decode_contention = max(req.finish_ts - req.first_token_ts, 0.0)
    out: Dict[str, Any] = {
        "rid": req.rid,
        "priority": req.slo.priority if req.slo is not None else None,
        "queue_wait_s": queue_wait,
        "prefill_blocked_s": prefill_blocked,
        "decode_contention_s": decode_contention,
        "e2e_s": queue_wait + prefill_blocked + decode_contention,
        "tokens": len(req.tokens),
        "prefill_active_s": None,
        "decode_active_s": None,
    }
    if not spans:
        out["span_coverage"] = "none"
        out["partial"] = True
        return out
    admission = [s for s in spans if s.name == "serve/admission"]
    prefills = [s for s in spans if s.name in _PREFILL_SPANS]
    decodes = [s for s in spans if s.name == "serve/decode"]
    out["prefill_active_s"] = sum(s.dur_s for s in prefills)
    out["decode_active_s"] = sum(s.dur_s for s in decodes)
    # decode slices exist only when the request decoded past its TTFT
    # token; a cap-1/eos-at-prefill request legitimately has none
    expect_decode = len(req.tokens) >= 2
    complete = (
        dropped == 0
        and bool(admission)
        and bool(prefills)
        and (bool(decodes) or not expect_decode)
    )
    out["span_coverage"] = "full" if complete else "partial"
    out["partial"] = not complete
    return out


class _ClassStats:
    """Running per-priority-class accounting (host-side, lock-free: the
    engine loop is single-threaded)."""

    __slots__ = (
        "requests", "finished", "ttft_ok", "tpot_ok", "attained",
        "violated", "goodput_tokens", "tokens", "waits",
    )

    def __init__(self):
        self.requests = 0
        self.finished = 0
        self.ttft_ok = 0
        self.tpot_ok = 0
        self.attained = 0
        self.violated = 0
        self.goodput_tokens = 0
        self.tokens = 0
        self.waits = _Reservoir()

    def queue_eta_s(self) -> Optional[float]:
        """The class's queue-ETA forecast: the median of its running
        admission-wait histogram — the signal ROADMAP item 1(b)'s
        preempt-and-requeue admission will consume."""
        return self.waits.percentile(0.50)


class SLOTracker:
    """Per-priority-class SLO accounting over one engine's lifetime.

    Fed by the engine at submit / admit / finish; purely host-side (never
    enters a dispatch), and inert until the first SLO-tagged request
    arrives — an SLO-free engine registers no ``serve/slo_*`` instruments
    and contributes zero JSONL fields (:meth:`event_fields` returns
    ``{}``).
    """

    def __init__(self, registry):
        self.registry = registry
        self.by_class: Dict[str, _ClassStats] = {}
        self.attributions: Dict[int, Dict[str, Any]] = {}
        self.partial_attributions = 0
        self._inflight: Dict[int, Any] = {}
        self._instruments = None
        self._class_gauges: Dict[str, Dict[str, Any]] = {}
        self._t0: Optional[float] = None
        # model-FLOPs-per-token from the cost observatory (ISSUE 18):
        # None without ServeConfig.cost_cards, so the TFLOP-goodput
        # column stays absent and SLO-only records remain byte-identical
        # to pre-ISSUE-18 ones
        self._flops_per_token: Optional[float] = None

    # ----------------------------- state ------------------------------- #

    @property
    def active(self) -> bool:
        """True once any SLO-tagged request has been submitted — the
        gate on every ``serve/slo_*`` surface (default-OFF contract)."""
        return self._t0 is not None

    def _totals(self) -> _ClassStats:
        total = _ClassStats()
        for st in self.by_class.values():
            total.requests += st.requests
            total.finished += st.finished
            total.ttft_ok += st.ttft_ok
            total.tpot_ok += st.tpot_ok
            total.attained += st.attained
            total.violated += st.violated
            total.goodput_tokens += st.goodput_tokens
            total.tokens += st.tokens
        return total

    def goodput_tokens_per_s(self, now: Optional[float] = None):
        """Goodput under SLO: tokens of ATTAINED requests per second of
        SLO-tracked wall clock (first SLO submit → now)."""
        if self._t0 is None:
            return None
        now = time.perf_counter() if now is None else now
        wall = max(now - self._t0, 1e-9)
        return self._totals().goodput_tokens / wall

    def set_flops_per_token(self, v: Optional[float]) -> None:
        """Install the cost observatory's model-FLOPs-per-token (ISSUE
        18; engine gauge cadence) — arms the SLO-aware TFLOP-goodput
        column in :meth:`event_fields` / :meth:`summary`."""
        self._flops_per_token = v

    def goodput_tflops_per_s(self, now: Optional[float] = None):
        """SLO-aware TFLOP goodput: model TFLOPs of tokens whose request
        MET its deadline, per second of SLO-tracked wall clock — the
        utilization-denominated goodput the cost observatory arms (None
        without ``ServeConfig.cost_cards`` or before any tokens)."""
        if self._flops_per_token is None:
            return None
        gp = self.goodput_tokens_per_s(now)
        if gp is None:
            return None
        return gp * self._flops_per_token / 1e12

    # ------------------------------ feeds ------------------------------ #

    def _ensure_instruments(self) -> None:
        if self._instruments is not None or self.registry is None:
            return
        reg = self.registry
        self._instruments = {
            "requests": reg.counter(
                "serve/slo_requests_total",
                help="SLO-tagged requests submitted",
            ),
            "attained": reg.counter(
                "serve/slo_attained_total",
                help="finished requests that met every set SLO target",
            ),
            "violated": reg.counter(
                "serve/slo_violated_total",
                help="finished requests that missed a set SLO target",
            ),
            "partial": reg.counter(
                "serve/slo_partial_attributions_total",
                help="violation attributions degraded by a truncated or "
                "inactive span ring (never vacuously attributed)",
            ),
            "wait": reg.histogram(
                "serve/slo_admission_wait_s",
                help="admission wait of SLO-tagged requests (the "
                "queue-ETA forecaster's raw signal)",
                buckets=LATENCY_BUCKETS,
            ),
            "ttft_attainment": reg.gauge(
                "serve/slo_ttft_attainment",
                help="fraction of finished SLO requests meeting their "
                "TTFT target",
            ),
            "tpot_attainment": reg.gauge(
                "serve/slo_tpot_attainment",
                help="fraction of finished SLO requests meeting their "
                "TPOT target",
            ),
            "goodput": reg.gauge(
                "serve/slo_goodput_tokens_per_s",
                help="tokens/s from requests that met their SLO "
                "(goodput under SLO)",
            ),
            "headroom": reg.gauge(
                "serve/slo_headroom_min_s",
                help="min TTFT deadline headroom over in-flight "
                "requests still awaiting their first token (negative = "
                "already busted)",
            ),
            "queue_eta": reg.gauge(
                "serve/slo_queue_eta_s",
                help="median admission wait over all SLO classes (the "
                "queue-ETA forecast)",
            ),
        }

    def _class_gauge(self, cls: str, name: str):
        gauges = self._class_gauges.setdefault(cls, {})
        g = gauges.get(name)
        if g is None and self.registry is not None:
            g = self.registry.gauge(f"serve/slo/{cls}/{name}")
            gauges[name] = g
        return g

    def on_submit(self, req) -> None:
        """Register one SLO-tagged request (its ``slo`` is already
        resolved + validated by the engine)."""
        if self._t0 is None:
            self._t0 = time.perf_counter()
        self._ensure_instruments()
        cls = req.slo.priority
        st = self.by_class.setdefault(cls, _ClassStats())
        st.requests += 1
        self._inflight[req.rid] = req
        if self._instruments is not None:
            self._instruments["requests"].inc()

    def on_admit(self, req) -> None:
        """Record the admission wait into the class's running histogram
        (the queue-ETA forecaster's raw signal)."""
        if req.slo is None or req.rid not in self._inflight:
            return
        wait = max(req.admit_ts - req.arrival_ts, 0.0)
        self.by_class[req.slo.priority].waits.add(wait)
        if self._instruments is not None:
            self._instruments["wait"].observe(wait)

    def on_finish(self, req, spans: List[Any], dropped: int) -> Dict[str, Any]:
        """Finalize one SLO-tagged request: attainment vs its resolved
        targets, goodput accounting, and the span-walked violation
        attribution (marked partial when the ring dropped spans)."""
        self._inflight.pop(req.rid, None)
        slo = req.slo
        st = self.by_class.setdefault(slo.priority, _ClassStats())
        st.finished += 1
        st.tokens += len(req.tokens)
        ttft_ok = (
            True
            if slo.ttft_target_s is None
            else (req.ttft_s is not None and req.ttft_s <= slo.ttft_target_s)
        )
        # a single-token request has no decode tokens: nothing to violate
        tpot = req.tpot_s
        tpot_ok = (
            True
            if slo.tpot_target_s is None or tpot is None
            else tpot <= slo.tpot_target_s
        )
        attained = ttft_ok and tpot_ok
        st.ttft_ok += int(ttft_ok)
        st.tpot_ok += int(tpot_ok)
        if attained:
            st.attained += 1
            st.goodput_tokens += len(req.tokens)
        else:
            st.violated += 1
        attribution = attribute_request(req, spans, dropped)
        attribution.update(
            ttft_s=req.ttft_s, tpot_s=tpot, ttft_ok=ttft_ok,
            tpot_ok=tpot_ok, attained=attained,
        )
        if attribution["partial"]:
            self.partial_attributions += 1
        if len(self.attributions) >= _MAX_ATTRIBUTIONS:
            self.attributions.pop(next(iter(self.attributions)))
        self.attributions[req.rid] = attribution
        if self._instruments is not None:
            key = "attained" if attained else "violated"
            self._instruments[key].inc()
            if attribution["partial"]:
                self._instruments["partial"].inc()
        return attribution

    # ----------------------------- gauges ------------------------------ #

    def headroom_min_s(self, now: Optional[float] = None):
        """Min TTFT deadline headroom over in-flight SLO requests still
        awaiting their first token — the preempt-and-requeue admission
        signal.  Negative means a deadline is already busted; ``None``
        when nothing with a TTFT target is awaiting its first token."""
        now = time.perf_counter() if now is None else now
        headrooms = [
            req.slo.ttft_target_s - (now - req.arrival_ts)
            for req in self._inflight.values()
            if req.first_token_ts is None
            and req.slo.ttft_target_s is not None
        ]
        return min(headrooms) if headrooms else None

    def queue_eta_s(self) -> Optional[float]:
        """Median admission wait pooled over every class (per-class
        forecasts live in :meth:`summary` / the per-class gauges)."""
        pooled = _Reservoir()
        for st in self.by_class.values():
            for v in st.waits._sorted:
                pooled.add(v)
        return pooled.percentile(0.50)

    def refresh_gauges(self, now: Optional[float] = None) -> None:
        """Publish the registry gauges (engine gauge-refresh cadence)."""
        if not self.active or self._instruments is None:
            return
        now = time.perf_counter() if now is None else now
        total = self._totals()
        ins = self._instruments
        if total.finished:
            ins["ttft_attainment"].set(total.ttft_ok / total.finished)
            ins["tpot_attainment"].set(total.tpot_ok / total.finished)
        gp = self.goodput_tokens_per_s(now)
        if gp is not None:
            ins["goodput"].set(gp)
        tf = self.goodput_tflops_per_s(now)
        if tf is not None:
            # registered lazily: the series exists only when the cost
            # observatory armed a per-token cost (ISSUE 18 default-OFF)
            self.registry.gauge(
                "serve/slo_goodput_tflops_per_s",
                help="TFLOPs/s from requests that met their SLO",
            ).set(tf)
        hr = self.headroom_min_s(now)
        if hr is not None:
            ins["headroom"].set(hr)
        eta = self.queue_eta_s()
        if eta is not None:
            ins["queue_eta"].set(eta)
        for cls, st in self.by_class.items():
            if st.finished:
                self._class_gauge(cls, "ttft_attainment").set(
                    st.ttft_ok / st.finished
                )
                self._class_gauge(cls, "tpot_attainment").set(
                    st.tpot_ok / st.finished
                )
                self._class_gauge(cls, "attainment").set(
                    st.attained / st.finished
                )
            eta = st.queue_eta_s()
            if eta is not None:
                self._class_gauge(cls, "queue_eta_s").set(eta)

    # --------------------------- JSONL fields --------------------------- #

    def event_fields(self) -> Dict[str, Any]:
        """The conditional ``serve/slo_*`` block of one JSONL serve
        record — ``{}`` until the first SLO-tagged request, so an
        SLO-free engine's records carry ZERO new fields (the ISSUE 14
        rebalance-fields discipline; ``build_step_event`` honors the
        omission)."""
        if not self.active:
            return {}
        now = time.perf_counter()
        total = self._totals()
        out: Dict[str, Any] = {
            "serve/slo_requests": float(total.requests),
            "serve/slo_finished": float(total.finished),
            "serve/slo_violations": float(total.violated),
            "serve/slo_ttft_attainment": (
                total.ttft_ok / total.finished if total.finished else None
            ),
            "serve/slo_tpot_attainment": (
                total.tpot_ok / total.finished if total.finished else None
            ),
            "serve/slo_attainment": (
                total.attained / total.finished if total.finished else None
            ),
            "serve/slo_goodput_tokens_per_s": self.goodput_tokens_per_s(now),
            "serve/slo_queue_eta_s": self.queue_eta_s(),
            "serve/slo_headroom_min_s": self.headroom_min_s(now),
            "serve/slo_partial_attributions": float(
                self.partial_attributions
            ),
        }
        if self._flops_per_token is not None:
            # TFLOP-goodput column (ISSUE 18): rides only when the cost
            # observatory armed a per-token cost, so an SLO-only engine's
            # records stay byte-identical to pre-ISSUE-18 ones
            out["serve/slo_goodput_tflops_per_s"] = (
                self.goodput_tflops_per_s(now)
            )
        return out

    # ----------------------------- summary ----------------------------- #

    def summary(self) -> Dict[str, Any]:
        """The SLO block of ``ServingEngine.summary()`` (and through it
        ``Stoke.serve()`` results): overall + per-class attainment,
        goodput under SLO, queue-ETA forecasts, and attribution
        partiality."""
        if not self.active:
            return {"active": False}
        total = self._totals()
        out: Dict[str, Any] = {
            "active": True,
            "requests": total.requests,
            "finished": total.finished,
            "attained": total.attained,
            "violated": total.violated,
            "ttft_attainment": (
                total.ttft_ok / total.finished if total.finished else None
            ),
            "tpot_attainment": (
                total.tpot_ok / total.finished if total.finished else None
            ),
            "attainment": (
                total.attained / total.finished if total.finished else None
            ),
            "goodput_tokens_per_s": self.goodput_tokens_per_s(),
            "queue_eta_s": self.queue_eta_s(),
            "headroom_min_s": self.headroom_min_s(),
            "partial_attributions": self.partial_attributions,
            "by_class": {
                cls: {
                    "requests": st.requests,
                    "finished": st.finished,
                    "attained": st.attained,
                    "violated": st.violated,
                    "ttft_attainment": (
                        st.ttft_ok / st.finished if st.finished else None
                    ),
                    "tpot_attainment": (
                        st.tpot_ok / st.finished if st.finished else None
                    ),
                    "attainment": (
                        st.attained / st.finished if st.finished else None
                    ),
                    "goodput_tokens": st.goodput_tokens,
                    "queue_eta_s": st.queue_eta_s(),
                }
                for cls, st in sorted(self.by_class.items())
            },
        }
        if self._flops_per_token is not None:
            out["goodput_tflops_per_s"] = self.goodput_tflops_per_s()
        return out

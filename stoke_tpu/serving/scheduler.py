"""Continuous-batching scheduler: mid-flight admission into fixed slots.

ISSUE 9 pillar 2.  Static batching drains to stragglers — a batch is held
open until its LONGEST request finishes, so short requests pay long
requests' latency and the decode batch empties toward 1.  Continuous
batching (Orca lineage; the discipline the Gemma-on-TPU comparison,
arXiv:2605.25645, identifies as the serving-throughput lever) keeps the
decode batch full instead: requests admit the moment a slot AND their
worst-case KV-block budget are free, finished sequences evict immediately,
and their freed blocks refill the pool for the next admission.

All host-side bookkeeping — the device never sees the queue.  Prompt
padding runs through ``NativeBatcher.gather_pad`` (the GIL-free C++ ragged
gather+pad used by the training loader path), so request packing rides the
same native host runtime as training input assembly.

Slot invariants the compiled decode program relies on:

- every slot always has a block-table row (inactive rows are all
  ``SCRATCH_BLOCK``) and a position/token/context entry — decode runs the
  FULL fixed ``max_seqs`` batch every step, no active-mask branching;
- a live slot's blocks are disjoint from every other slot's, so in-batch
  page writes never collide;
- admission reserves ``ceil((prompt_len + max_new_tokens) / block_size)``
  blocks up front, so a mid-flight decode step can never fail on an empty
  pool.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from stoke_tpu.native import NativeBatcher
from stoke_tpu.serving.kv_cache import SCRATCH_BLOCK, BlockAllocator
from stoke_tpu.serving.sampling import SamplingParams
from stoke_tpu.serving.slo import RequestSLO
from stoke_tpu.serving.speculative import propose_draft


@dataclass
class Request:
    """One inference request and its lifecycle timestamps.

    ``tokens`` accumulates the generated ids (the first one comes from
    prefill — its wall time IS the TTFT); ``first_token_ts - arrival_ts``
    and the per-token deltas after it feed the TTFT/TPOT histograms.
    ``params``/``seed`` are the resolved sampling knobs (ISSUE 13): the
    engine resolves defaults at submit, so the scheduler only carries
    them.  ``slo`` is the resolved per-request SLO (ISSUE 16), same
    contract: targets already filled from the ServeConfig defaults, the
    scheduler never interprets it.
    """

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_id: Optional[int] = None
    params: SamplingParams = field(default_factory=SamplingParams)
    seed: int = 0
    slo: Optional[RequestSLO] = None
    arrival_ts: float = field(default_factory=time.perf_counter)
    admit_ts: Optional[float] = None
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    tokens: List[int] = field(default_factory=list)

    @property
    def finished(self) -> bool:
        return self.finish_ts is not None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.arrival_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time-per-output-token over the decode tokens (excludes the
        prefill token the TTFT already accounts)."""
        if self.finish_ts is None or len(self.tokens) < 2:
            return None
        return (self.finish_ts - self.first_token_ts) / (len(self.tokens) - 1)


@dataclass
class _Slot:
    request: Optional[Request] = None
    blocks: List[int] = field(default_factory=list)
    context_len: int = 0       # cached tokens (prompt + committed decode)
    next_token: int = 0        # token the next decode step feeds
    # chunked prefill (ISSUE 13): prompt tokens already written to the
    # cache; None = prefill complete (the slot decodes).  While a slot is
    # prefilling it occupies capacity but is excluded from decode_batch —
    # its rows run against the scratch table like an inactive slot, so
    # in-flight decode writes can never clobber its half-written prompt.
    prefill_pos: Optional[int] = None


class Scheduler:
    """Continuous-batching request scheduler over a block allocator."""

    def __init__(
        self,
        max_seqs: int,
        allocator: BlockAllocator,
        max_blocks_per_seq: int,
        *,
        max_seq_len: int,
        default_max_new_tokens: int,
        eos_id: Optional[int] = None,
        pad_multiple: int = 64,
        prefill_chunk_tokens: Optional[int] = None,
        sampling_seed_base: int = 0,
        batcher: Optional[NativeBatcher] = None,
    ):
        self.max_seqs = int(max_seqs)
        self.allocator = allocator
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_seq_len = int(max_seq_len)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.eos_id = eos_id
        self.pad_multiple = int(pad_multiple)
        self.prefill_chunk_tokens = (
            None if prefill_chunk_tokens is None else int(prefill_chunk_tokens)
        )
        self.sampling_seed_base = int(sampling_seed_base)
        self.batcher = batcher or NativeBatcher()
        self.queue: Deque[Request] = deque()
        self.slots: List[_Slot] = [_Slot() for _ in range(max_seqs)]
        # fixed-shape decode-side state the engine snapshots every step
        self.block_tables = np.full(
            (max_seqs, max_blocks_per_seq), SCRATCH_BLOCK, np.int32
        )
        self.finished: Dict[int, Request] = {}
        self._next_rid = 0
        self.preempt_denials = 0  # admissions deferred on an empty pool

    # ----------------------------- intake ------------------------------ #

    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        params: Optional[SamplingParams] = None,
        slo: Optional[RequestSLO] = None,
    ) -> int:
        """Enqueue one request; returns its id.  Requests whose worst case
        cannot fit ``max_seq_len`` are rejected here — a cap the paged
        pool could never honor must fail at submit, not mid-decode."""
        prompt = np.ascontiguousarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        cap = (
            self.default_max_new_tokens
            if max_new_tokens is None
            else int(max_new_tokens)
        )
        if cap < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {cap}")
        if prompt.size + cap > self.max_seq_len:
            raise ValueError(
                f"request needs {prompt.size} prompt + {cap} output tokens "
                f"> max_seq_len={self.max_seq_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        params = params if params is not None else SamplingParams()
        # seed resolution lives HERE, beside rid assignment: an explicit
        # per-request seed wins, else the deterministic per-request
        # default sampling_seed_base + rid — so whole runs replay from
        # the config and the derivation can never desync from the rid
        seed = (
            params.seed
            if params.seed is not None
            else self.sampling_seed_base + rid
        )
        self.queue.append(
            Request(
                rid=rid,
                prompt=prompt,
                max_new_tokens=cap,
                eos_id=self.eos_id if eos_id is None else eos_id,
                params=params,
                seed=int(seed),
                slo=slo,
            )
        )
        return rid

    # ---------------------------- admission ---------------------------- #

    @property
    def active(self) -> int:
        return sum(1 for s in self.slots if s.request is not None)

    @property
    def decoding(self) -> int:
        """Slots with a fully-prefilled request — the live decode batch
        (a chunk-prefilling slot occupies capacity but does not decode)."""
        return sum(
            1
            for s in self.slots
            if s.request is not None and s.prefill_pos is None
        )

    @property
    def has_prefilling(self) -> bool:
        return any(s.prefill_pos is not None for s in self.slots)

    @property
    def queued(self) -> int:
        return len(self.queue)

    @property
    def has_work(self) -> bool:
        return self.active > 0 or self.queued > 0

    @property
    def batch_fill(self) -> float:
        return self.active / max(self.max_seqs, 1)

    def admit(self) -> List[Tuple[int, Request, Optional[np.ndarray], int]]:
        """Admit queued requests (FIFO) while a slot and their block
        budget are free.  Returns ``[(slot, request, padded_prompt,
        prompt_len), ...]`` for the engine to prefill; the padded prompt
        comes from the native ``gather_pad`` path (zero-pad to the
        ``pad_multiple`` bucket that keys the compiled prefill program).

        Chunked prefill (ISSUE 13): when ``prefill_chunk_tokens`` is set
        and the prompt is longer, the slot is admitted in the PREFILLING
        state instead (``padded_prompt`` is None) — the engine pulls
        fixed-size chunks via :meth:`next_chunk` across later iterations,
        interleaved with decode steps, so one long prompt cannot stall
        the in-flight batch."""
        admitted = []
        for i, slot in enumerate(self.slots):
            if not self.queue:
                break
            if slot.request is not None:
                continue
            req = self.queue[0]
            need = self.allocator.blocks_for(
                req.prompt.size + req.max_new_tokens
            )
            blocks = self.allocator.alloc(need)
            if blocks is None:
                # head-of-line blocking by design: admitting a smaller
                # later request over the head would starve long prompts
                self.preempt_denials += 1
                break
            self.queue.popleft()
            req.admit_ts = time.perf_counter()
            slot.request = req
            slot.blocks = blocks
            slot.context_len = int(req.prompt.size)
            self.block_tables[i, :] = SCRATCH_BLOCK
            self.block_tables[i, : len(blocks)] = blocks
            chunk = self.prefill_chunk_tokens
            if chunk is not None and req.prompt.size > chunk:
                slot.prefill_pos = 0
                admitted.append((i, req, None, int(req.prompt.size)))
                continue
            padded, _mask = self.batcher.gather_pad(
                req.prompt,
                np.zeros(1, np.int64),
                np.array([req.prompt.size], np.int32),
                [0],
                pad_multiple=self.pad_multiple,
            )
            admitted.append((i, req, padded, int(req.prompt.size)))
        return admitted

    # ------------------------- chunked prefill -------------------------- #

    def next_chunk(self):
        """The next prompt chunk to prefill, or None.  One chunk per
        engine iteration keeps every iteration's prefill work bounded by
        ``prefill_chunk_tokens`` — the TPOT-flatness guarantee.  The
        OLDEST-admitted prefilling request is serviced first (FIFO over
        admit_ts, not slot index): a later long prompt recycling a lower
        slot must never starve one already mid-prefill.  Returns
        ``(slot, request, tokens [C], positions [C], is_final,
        logit_idx)``: tokens zero-padded to the fixed chunk length (ONE
        compiled chunk program), positions the GLOBAL prompt positions
        (padding rows clamped — their writes steer to scratch, their
        outputs are discarded), ``logit_idx`` the in-chunk row of the
        last prompt token (meaningful only when ``is_final``)."""
        C = self.prefill_chunk_tokens
        prefilling = [
            (s.request.admit_ts, i, s)
            for i, s in enumerate(self.slots)
            if s.prefill_pos is not None
        ]
        if not prefilling:
            return None
        _, i, s = min(prefilling)
        req = s.request
        plen = int(req.prompt.size)
        start = s.prefill_pos
        toks = np.zeros(C, np.int32)
        n = min(C, plen - start)
        toks[:n] = req.prompt[start : start + n]
        positions = np.minimum(
            start + np.arange(C, dtype=np.int32), self.max_seq_len - 1
        )
        is_final = start + C >= plen
        logit_idx = plen - 1 - start if is_final else 0
        return i, req, toks, positions, is_final, logit_idx

    def note_chunk(self, slot: int) -> None:
        """One chunk dispatched for ``slot``: advance the prefill cursor;
        the final chunk completes prefill (the engine then records the
        sampled first token via :meth:`note_prefill_token`, arming
        decode)."""
        s = self.slots[slot]
        s.prefill_pos += self.prefill_chunk_tokens
        if s.prefill_pos >= s.request.prompt.size:
            s.prefill_pos = None

    def next_chunks(self):
        """Packed chunk batch (ISSUE 17): ONE dispatch services every
        prefilling slot's next chunk, shaped ``[max_seqs, C]`` like the
        verify program (fixed batch, per-row positions, scratch-steered
        idle rows) instead of :meth:`next_chunk`'s one-slot-per-iteration
        ``[1, C]``.  Per-iteration prefill work is still bounded — C
        tokens per ROW, and rows were already paying the fixed dispatch
        cost as dead decode slots.  Returns ``None`` when nothing is
        prefilling, else ``(tokens [B, C], positions [B, C], tables
        [B, MB], lengths [B], logit_idx [B], rows)`` — ``lengths`` the
        per-row prompt length (the chunk-mode write predicate; idle rows
        0 so every write steers to scratch), ``rows`` a list of
        ``(slot, request, is_final)`` for the serviced slots."""
        C = self.prefill_chunk_tokens
        B = self.max_seqs
        if not self.has_prefilling:
            return None
        tokens = np.zeros((B, C), np.int32)
        positions = np.tile(np.arange(C, dtype=np.int32), (B, 1))
        lengths = np.zeros(B, np.int32)
        logit_idx = np.zeros(B, np.int32)
        tables = self.block_tables.copy()
        rows = []
        for i, s in enumerate(self.slots):
            if s.prefill_pos is None:
                # idle or decoding row: all-scratch table (a decoding
                # slot's real cache must be unreachable from this
                # dispatch's padding writes), zero length, discarded out
                tables[i, :] = SCRATCH_BLOCK
                continue
            req = s.request
            plen = int(req.prompt.size)
            start = s.prefill_pos
            n = min(C, plen - start)
            tokens[i, :n] = req.prompt[start : start + n]
            positions[i, :] = np.minimum(
                start + np.arange(C, dtype=np.int32), self.max_seq_len - 1
            )
            lengths[i] = plen
            is_final = start + C >= plen
            logit_idx[i] = plen - 1 - start if is_final else 0
            rows.append((i, req, is_final))
        return tokens, positions, tables, lengths, logit_idx, rows

    # ------------------------ speculative decode ------------------------ #

    def verify_batch(self, k: int, *, ngram_max: int, ngram_min: int):
        """Fixed-shape speculative verify inputs (ISSUE 17): each decoding
        slot's pending token plus up to ``k`` drafts from the host-side
        prompt-lookup drafter, as S = k+1 query rows.

        Drafts are truncated to ``remaining - 1`` (cap minus the pending
        token) so a fully-accepted dispatch can never overshoot the
        request's token budget or its admission-reserved blocks.  Idle
        and still-prefilling rows ride along scratch-steered exactly like
        :meth:`decode_batch`'s — zero write budget, all-scratch tables,
        outputs discarded.

        Returns ``(tokens [B, S], positions [B, S], tables [B, MB],
        lengths [B], draft_lens [B])`` — ``lengths`` the verify write
        budget (context + draft + 1), ``draft_lens`` the per-slot valid
        draft counts the accept rule masks with.
        """
        B = self.max_seqs
        S = k + 1
        tokens = np.zeros((B, S), np.int32)
        positions = np.tile(np.arange(S, dtype=np.int32), (B, 1))
        lengths = np.zeros(B, np.int32)
        draft_lens = np.zeros(B, np.int32)
        tables = self.block_tables.copy()
        for i, s in enumerate(self.slots):
            if s.request is None:
                continue
            if s.prefill_pos is not None:
                tables[i, :] = SCRATCH_BLOCK
                continue
            req = s.request
            remaining = req.max_new_tokens - len(req.tokens)
            budget = max(0, min(k, remaining - 1))
            draft = propose_draft(
                np.concatenate([req.prompt, np.asarray(req.tokens, np.int32)]),
                budget,
                ngram_max=ngram_max,
                ngram_min=ngram_min,
            )[:budget]
            tokens[i, 0] = s.next_token
            if draft:
                tokens[i, 1 : 1 + len(draft)] = draft
            positions[i, :] = np.minimum(
                s.context_len + np.arange(S, dtype=np.int32),
                self.max_seq_len - 1,
            )
            lengths[i] = s.context_len + len(draft) + 1
            draft_lens[i] = len(draft)
        return tokens, positions, tables, lengths, draft_lens

    def commit_verify(
        self, targets: np.ndarray, n_emit: np.ndarray, now: float
    ) -> Tuple[np.ndarray, int]:
        """Fold one verify dispatch's outputs into the slots: each live
        slot emits its first ``n_emit[i]`` target tokens (the accepted
        run plus the correction/bonus draw), stopping early at eos —
        eviction frees the whole slot, so over-accepted cache rows past
        an eos die with it.  Returns ``(committed [B], accepted)`` —
        per-slot tokens actually committed (0 for idle rows) and the
        total draft tokens that became output (``committed - 1`` per
        live slot); with :meth:`verify_batch`'s ``draft_lens`` these
        feed the ``serve/spec_*`` counters."""
        committed = np.zeros(self.max_seqs, np.int32)
        accepted = 0
        for i, s in enumerate(self.slots):
            if s.request is None or s.prefill_pos is not None:
                continue
            req = s.request
            for j in range(int(n_emit[i])):
                tok = int(targets[i, j])
                s.context_len += 1  # query row j's K/V is now cached
                req.tokens.append(tok)
                s.next_token = tok
                committed[i] += 1
                if self._done(req):
                    self._finish(i, now)
                    break
            accepted += max(int(committed[i]) - 1, 0)
        return committed, accepted

    # --------------------------- decode state -------------------------- #

    def decode_batch(self):
        """Fixed-shape decode inputs: ``(tokens [B], positions [B],
        block_tables [B, MB], context_lens [B])``.  Inactive slots feed
        token 0 at position 0 against an all-scratch table; slots still
        chunk-prefilling get the SAME treatment (their real table is
        swapped for scratch here) so the decode step's position-0 write
        can never clobber their half-written prompt K/V."""
        B = self.max_seqs
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        context = np.ones(B, np.int32)  # inactive: attend self-only
        tables = self.block_tables.copy()
        for i, s in enumerate(self.slots):
            if s.request is None:
                continue
            if s.prefill_pos is not None:
                tables[i, :] = SCRATCH_BLOCK
                continue
            tokens[i] = s.next_token
            positions[i] = s.context_len
            context[i] = s.context_len + 1
        return tokens, positions, tables, context

    def sampling_batch(self):
        """Fixed-shape per-slot sampling knobs aligned with
        :meth:`decode_batch`: ``(temperature [B] f32, top_k [B] i32,
        top_p [B] f32)`` — inactive/prefilling slots greedy-encoded."""
        B = self.max_seqs
        temps = np.zeros(B, np.float32)
        ks = np.zeros(B, np.int32)
        ps = np.ones(B, np.float32)
        for i, s in enumerate(self.slots):
            if s.request is None or s.prefill_pos is not None:
                continue
            temps[i], ks[i], ps[i] = s.request.params.as_arrays()
        return temps, ks, ps

    # --------------------------- commit/evict --------------------------- #

    def note_prefill_token(self, slot: int, token: int, now: float) -> None:
        """Record the prefill-produced first token (the TTFT point) and
        arm the slot for decode (or finish immediately at cap 1/eos)."""
        s = self.slots[slot]
        req = s.request
        req.first_token_ts = now
        req.tokens.append(int(token))
        s.next_token = int(token)
        if self._done(req):
            self._finish(slot, now)

    def commit_decode(self, next_tokens: np.ndarray, now: float) -> int:
        """Fold one decode step's outputs into the slots; evict finished
        requests (blocks freed back to the pool).  Returns the number of
        LIVE tokens committed (inactive-slot outputs are discarded)."""
        live = 0
        for i, s in enumerate(self.slots):
            if s.request is None or s.prefill_pos is not None:
                continue
            tok = int(next_tokens[i])
            s.context_len += 1  # the token we just fed is now cached
            s.request.tokens.append(tok)
            s.next_token = tok
            live += 1
            if self._done(s.request):
                self._finish(i, now)
        return live

    def _done(self, req: Request) -> bool:
        if len(req.tokens) >= req.max_new_tokens:
            return True
        return req.eos_id is not None and req.tokens[-1] == req.eos_id

    def _finish(self, slot: int, now: float) -> None:
        s = self.slots[slot]
        s.request.finish_ts = now
        self.finished[s.request.rid] = s.request
        self.allocator.free(s.blocks)
        self.slots[slot] = _Slot()
        self.block_tables[slot, :] = SCRATCH_BLOCK

"""Serving stack (ISSUE 9): continuous-batching inference with paged KV.

The inference vertical behind ``Stoke.serve()``:

- :mod:`~stoke_tpu.serving.kv_cache` — block-pool paged KV-cache, the
  per-request block tables, and the GPT attention hook;
- :mod:`~stoke_tpu.serving.scheduler` — continuous batching (mid-flight
  admission, eviction, block refill) over the native request packer;
- :mod:`~stoke_tpu.serving.quant` — int8/bf16 weight store reusing the
  PR-2 stochastic-rounding quantizer, matmul-side dequant;
- :mod:`~stoke_tpu.serving.sampling` — temperature / top-k / top-p
  sampling with per-request seeded key streams (ISSUE 13), plus the
  speculative accept/reject layer (ISSUE 17);
- :mod:`~stoke_tpu.serving.speculative` — the host-side n-gram /
  prompt-lookup drafter feeding the k-token verify program (ISSUE 17);
- :mod:`~stoke_tpu.serving.telemetry` — TTFT/TPOT histograms + p50/p99
  gauges, capacity gauges, queue/prefill/decode goodput buckets;
- :mod:`~stoke_tpu.serving.slo` — per-request deadlines + priority
  classes: attainment fractions, goodput-under-SLO, queue-ETA
  forecasts, span-walked violation attribution (ISSUE 16);
- :mod:`~stoke_tpu.serving.engine` — the prefill/decode-split engine
  wiring it all to the compiled programs and the PR-6 AOT ledger.

See docs/serving.md for the architecture tour and sizing guidance.
"""

from stoke_tpu.serving.engine import ServingEngine
from stoke_tpu.serving.kv_cache import (
    SCRATCH_BLOCK,
    BlockAllocator,
    PagedAttentionHook,
    PagedKVCache,
)
from stoke_tpu.serving.quant import (
    QuantizedTensor,
    compression_stats,
    dequantize_params,
    param_bytes,
    quantize_params,
)
from stoke_tpu.serving.sampling import (
    SamplingParams,
    accept_drafts,
    sample_tokens,
    validate_sampling_params,
)
from stoke_tpu.serving.scheduler import Request, Scheduler
from stoke_tpu.serving.speculative import propose_draft
from stoke_tpu.serving.slo import (
    RequestSLO,
    SLOTracker,
    validate_request_slo,
)
from stoke_tpu.serving.telemetry import ServeMetrics

__all__ = [
    "SamplingParams",
    "accept_drafts",
    "propose_draft",
    "sample_tokens",
    "validate_sampling_params",
    "RequestSLO",
    "SLOTracker",
    "validate_request_slo",
    "ServingEngine",
    "PagedKVCache",
    "PagedAttentionHook",
    "BlockAllocator",
    "SCRATCH_BLOCK",
    "Scheduler",
    "Request",
    "ServeMetrics",
    "QuantizedTensor",
    "quantize_params",
    "dequantize_params",
    "param_bytes",
    "compression_stats",
]

"""Sampling beyond greedy: temperature / top-k / top-p with per-request
seeds (ISSUE 13 pillar 3).

Greedy argmax was a design choice, not a limitation — the PR-9
continuous-batching acceptance (staggered admission produces token
streams identical to sequential generation) leans on decode determinism.
Sampling keeps every piece of that determinism except the final draw:

- **Device-side sampling**: :func:`sample_tokens` runs INSIDE the compiled
  prefill/decode programs on the pre-sampling logits (temperature scale →
  top-k mask → top-p nucleus mask → Gumbel-max draw), so the host never
  sees probabilities and the decode dispatch count is unchanged.
- **Per-request typed PRNG keys**: each request owns a key stream seeded
  at submit (explicit ``SamplingParams.seed``, else a deterministic
  per-request default).  The key state is *engine state threaded through
  the dispatch* exactly like the KV pages: the program wraps the raw key
  data to typed keys (``jax.random.wrap_key_data``), splits once per
  emitted token, samples with the subkey, and returns the advanced key
  data.  One split per token means a request's draw sequence depends only
  on its own seed and token index — never on co-batched requests — so
  seeded runs are reproducible and staggered == sequential extends to
  sampled streams.
- **Counterfactual parity**: ``temperature == 0`` routes to the exact raw
  argmax (``jnp.where``, not a small-temperature limit), so temperature→0
  reproduces greedy streams BIT-exactly; and because sampling happens
  after the logits, the pre-sampling logits of a staggered batch bit-match
  sequential generation (the acceptance check that replaces greedy stream
  equality when streams are stochastic).

Per-request knobs travel as fixed-shape ``[B]`` arrays (0 temperature =
greedy, 0 top_k = disabled, 1.0 top_p = disabled) so the decode program
shape never changes with the request mix.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

#: wire encoding of "knob disabled" in the fixed-shape per-slot arrays
TOP_K_OFF = 0
TOP_P_OFF = 1.0


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (validated at ``submit()``).

    Attributes:
        temperature: softmax temperature; ``0.0`` is EXACT greedy (the raw
            argmax, not a limit — the determinism the batching acceptance
            tests lean on).
        top_k: keep only the k highest logits before drawing (``None`` =
            off; ``1`` degenerates to greedy whatever the temperature).
        top_p: nucleus sampling — keep the smallest prefix of the sorted
            distribution whose mass reaches ``top_p`` (``None`` = off;
            the most-probable token is always kept).
        seed: PRNG seed of this request's draw stream (``None`` = the
            engine derives a deterministic per-request default from
            ``ServeConfig.sampling_seed`` and the request id).
    """

    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None

    @property
    def is_greedy(self) -> bool:
        """True when the draw is the raw argmax (temperature 0)."""
        return self.temperature == 0.0

    def as_arrays(self) -> Tuple[float, int, float]:
        """The ``(temperature, top_k, top_p)`` wire triple (disabled knobs
        encoded as ``TOP_K_OFF``/``TOP_P_OFF``)."""
        return (
            float(self.temperature),
            TOP_K_OFF if self.top_k is None else int(self.top_k),
            TOP_P_OFF if self.top_p is None else float(self.top_p),
        )


def validate_sampling_params(p: SamplingParams) -> None:
    """Reject impossible knobs at submit time, not mid-decode."""
    if p.temperature < 0.0:
        raise ValueError(
            f"SamplingParams.temperature must be >= 0, got {p.temperature}"
        )
    if p.top_k is not None and p.top_k < 1:
        raise ValueError(
            f"SamplingParams.top_k must be >= 1 when set, got {p.top_k}"
        )
    if p.top_p is not None and not (0.0 < p.top_p <= 1.0):
        raise ValueError(
            f"SamplingParams.top_p must be in (0, 1] when set, got {p.top_p}"
        )


def initial_key_data(seed: int) -> np.ndarray:
    """Raw key data of a fresh typed key for ``seed`` — the per-slot key
    state the engine threads through its dispatches."""
    return np.asarray(jax.random.key_data(jax.random.key(int(seed))))


def split_key_data(key_data):
    """Advance a ``[B, ...]`` key-data batch one step INSIDE a compiled
    program: wrap to typed keys, split each once, return
    ``(carry_key_data, draw_keys)`` — the carry becomes the next step's
    state, the typed draw keys feed :func:`sample_tokens`."""
    keys = jax.random.wrap_key_data(key_data)
    pairs = jax.vmap(lambda k: jax.random.split(k))(keys)
    carry, sub = pairs[:, 0], pairs[:, 1]
    return jax.vmap(jax.random.key_data)(carry), sub


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """Batched temperature / top-k / top-p sampling (device-side).

    Args:
        logits: ``[B, V]`` pre-sampling logits.
        keys: ``[B]`` typed PRNG keys (one fresh subkey per slot per
            token — see :func:`split_key_data`).
        temperature: ``[B] f32`` — 0 selects the EXACT raw argmax.
        top_k: ``[B] i32`` — ``TOP_K_OFF`` (0) disables.
        top_p: ``[B] f32`` — ``TOP_P_OFF`` (1.0) disables.

    Returns ``[B] int32`` sampled token ids.
    """
    logits = logits.astype(jnp.float32)
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    scaled = logits / t
    # top-k: mask below the k-th largest scaled logit (k = V when off)
    k_eff = jnp.where(
        top_k > 0, jnp.clip(top_k, 1, V), V
    ).astype(jnp.int32)
    desc = -jnp.sort(-scaled, axis=-1)
    kth = jnp.take_along_axis(desc, (k_eff - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= kth, scaled, _NEG_INF)
    # top-p: keep the smallest sorted prefix whose mass reaches p; the
    # `cum - p_i < p` predicate always keeps the most-probable token.
    # Softmax is order-preserving, so the sorted probabilities come from
    # the ALREADY-sorted logits (top-k tail masked positionally) — one
    # O(V log V) sort per step — and the cutoff maps back through the
    # LOGIT at the nucleus boundary: ``desc`` is a bitwise permutation of
    # the kept ``masked`` values, so `masked >= thr_logit` is exact (a
    # probability-space comparison against a separately-summed softmax
    # can drop the boundary token on ulp-level rounding)
    rank = jax.lax.broadcasted_iota(jnp.int32, (B, V), 1)
    pdesc = jax.nn.softmax(
        jnp.where(rank < k_eff[:, None], desc, _NEG_INF), axis=-1
    )
    csum = jnp.cumsum(pdesc, axis=-1)
    p_lim = jnp.clip(top_p.astype(jnp.float32), 0.0, 1.0)[:, None]
    keep_n = jnp.maximum(jnp.sum((csum - pdesc) < p_lim, axis=-1), 1)
    thr_logit = jnp.take_along_axis(desc, (keep_n - 1)[:, None], axis=-1)
    final = jnp.where(masked >= thr_logit, masked, _NEG_INF)
    # Gumbel-max draw: argmax(log-weights + gumbel) ~ categorical
    g = jax.vmap(lambda k: jax.random.gumbel(k, (V,), jnp.float32))(keys)
    sampled = jnp.argmax(
        jnp.where(final > _NEG_INF * 0.5, final + g, _NEG_INF), axis=-1
    ).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def speculative_sample_tokens(logits, key_data, temperature, top_k, top_p):
    """Draw the S sequential target tokens a verify dispatch compares its
    drafts against (ISSUE 17).

    The verify program scores S = k+1 query positions per request in one
    forward; the accept rule needs the token the NON-speculative engine
    *would* have drawn at each of those positions — i.e. S sequential
    draws from the same per-request key stream, each with the subkey the
    plain decode loop would have used.  A ``lax.scan`` over the S
    position axis performs exactly that: split once per position, draw
    with the subkey, stack every intermediate key state so the caller
    can rewind to "after n_emit splits" (:func:`select_key_data`) once
    acceptance is known.  Temperature 0 rides :func:`sample_tokens`'s
    exact-argmax route, so greedy verify draws are the raw argmax —
    key splits still advance (and are then discarded by the greedy
    engine-identity: greedy streams ignore the key anyway).

    Args:
        logits: ``[B, S, V]`` verify-program logits (position s predicts
            the token AFTER query s).
        key_data: ``[B, ...]`` raw per-slot key state (pre-draw).
        temperature / top_k / top_p: ``[B]`` wire-encoded knobs, shared
            by all S draws of a request (they are per-request, not
            per-token).

    Returns ``(targets, key_stack)``: ``targets[B, S] int32`` — the
    model's true draw at each position; ``key_stack[S, B, ...]`` — the
    per-slot key state after each split (``key_stack[i]`` = state after
    ``i + 1`` splits, i.e. after ``i + 1`` tokens have been drawn).
    """
    S = logits.shape[1]

    def step(kd, logit_s):
        kd_next, sub = split_key_data(kd)
        tok = sample_tokens(logit_s, sub, temperature, top_k, top_p)
        return kd_next, (tok, kd_next)

    _, (targets, key_stack) = jax.lax.scan(
        step, key_data, jnp.moveaxis(logits, 1, 0), length=S
    )
    return jnp.moveaxis(targets, 0, 1), key_stack


def accept_drafts(drafts, draft_lens, targets):
    """Leading-exact-match acceptance over a verify batch.

    Draft token ``drafts[b, i]`` is accepted iff it equals the model's
    true sequential draw ``targets[b, i]`` AND every earlier draft
    position was accepted (a rejection truncates the tail — later drafts
    were conditioned on the rejected token's continuation).  Exact-match
    verification keeps the emitted stream BIT-identical to the
    non-speculative engine for every sampling mode: each emitted token
    is ``targets[b, i]``, which was drawn from the true model
    distribution with the correct sequential subkey — the draft only
    decides how many of those draws one dispatch gets to keep.

    Args:
        drafts: ``[B, K] int32`` proposed tokens (garbage past
            ``draft_lens``).
        draft_lens: ``[B] int32`` valid draft tokens per slot (0..K).
        targets: ``[B, S] int32`` with S >= K+1 — the sequential true
            draws from :func:`speculative_sample_tokens`.

    Returns ``n_emit [B] int32`` — tokens emitted this dispatch, in
    ``1..K+1``: the accepted run plus the correction (or bonus) token
    ``targets[b, n_emit-1]``.
    """
    B, K = drafts.shape
    i = jnp.arange(K, dtype=jnp.int32)[None, :]
    ok = (drafts == targets[:, :K]) & (i < draft_lens[:, None])
    accepted = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=-1), axis=-1)
    return (accepted + 1).astype(jnp.int32)


def select_key_data(key_stack, n_emit):
    """Rewind the scan's key states to "after ``n_emit`` splits" — the
    key state the non-speculative engine would hold after emitting the
    same tokens, so acceptance never desynchronizes a request's draw
    stream (one split per EMITTED token, never per scored position).

    Args:
        key_stack: ``[S, B, ...]`` from :func:`speculative_sample_tokens`
            (index i = state after i+1 splits).
        n_emit: ``[B] int32`` in ``1..S``.

    Returns ``[B, ...]`` key data to write back as the slot's state.
    """
    idx = (n_emit.astype(jnp.int32) - 1).reshape(
        (-1,) + (1,) * (key_stack.ndim - 2)
    )
    return jnp.take_along_axis(
        jnp.moveaxis(key_stack, 0, 1), idx[:, None], axis=1
    )[:, 0]

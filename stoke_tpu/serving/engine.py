"""Continuous-batching serving engine: prefill/decode split over paged KV.

ISSUE 9 pillar 3 and the piece that wires the other three together.  Two
compiled programs, deliberately split (the Gemma-on-TPU comparison's
serving shape, arXiv:2605.25645):

- **prefill** — one request at a time, prompt padded up to the
  ``prefill_pad_multiple`` bucket (each bucket is ONE compiled program, so
  program count is bounded), causal attention through the configured
  kernel (dense or the Pallas flash kernel), every prompt K/V written into
  the request's blocks, and the first generated token sampled from the
  last prompt position — the TTFT point.
- **decode** — ALL ``max_seqs`` slots every step, single fresh token per
  slot, cache-read attention over gathered blocks
  (``ops.flash_attention.paged_decode_attention``).  Inactive slots run
  against the scratch block and their outputs are discarded, so the
  program shape never changes and XLA compiles it exactly once.

Both programs register with the PR-6 compile-cache program ledger when a
``CompileConfig`` is attached (``compile_cache.executable`` — warm starts
load from the persistent XLA cache and book reclaimed seconds), dispatch
through plain ``jax.jit`` (page buffers donated off-CPU, so cache updates
are in-place in HBM), and read weights through the ISSUE 9 quantized
store (``serving/quant.py``; dequant fused matmul-side by XLA).

Sampling defaults to greedy argmax — deterministic by design: the
continuous-batching acceptance (staggered admission produces token
streams identical to sequential generation) is only testable under a
deterministic sampler, and the decode program's fixed batch shape makes
per-slot results independent of co-batched requests.  Since ISSUE 13
``ServeConfig(sampling=True)`` compiles sampling-aware program variants
instead (temperature / top-k / top-p drawn in-program from per-request
seeded key streams — ``serving/sampling.py``); the greedy engine's
programs stay bit-identical to pre-fast-path.  The same ISSUE adds the
serve fast path's other two pieces: ``decode_kernel="pallas"`` routes
decode attention through the streaming Pallas kernel
(``ops.flash_attention.paged_decode_attention_pallas``), and
``prefill_chunk_tokens`` bounds per-iteration prefill work so one long
prompt cannot stall the in-flight decode batch (chunks interleave with
decode steps; ``serve/prefill_chunk`` spans on the request timeline).

Speculative decoding (ISSUE 17, ``ServeConfig.speculative_k``): decode at
low batch is dispatch-bound — one query token per request per dispatch —
so the engine grows a **verify** program: the host-side prompt-lookup
drafter (``serving/speculative.py``) proposes up to k tokens per request
from history it already owns, the verify dispatch scores all k+1
positions in one forward (chunk-attention semantics over the paged
cache), the accept rule keeps the leading exact-match run, and rejected
positions' K/V roll back out of the pool before the dispatch returns.
Exact-match acceptance makes emitted streams BIT-identical to the
non-speculative engine in every sampling mode (each emitted token is the
true model draw with the correct sequential subkey — the draft only
decides how many draws one dispatch keeps).  The same multi-token-query
shape packs all prefilling slots' chunks into one dispatch
(``serve_prefill_chunk_packed``).  ``speculative_k=None`` engines compile
the PR-13 programs verbatim.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.configs import ServeConfig
from stoke_tpu.models.bert import BERT_SIZES
from stoke_tpu.models.gpt import GPT
from stoke_tpu.serving.kv_cache import (
    BlockAllocator,
    PagedAttentionHook,
    PagedKVCache,
)
from stoke_tpu.serving.quant import (
    compression_stats,
    dequantize_params,
    quantize_params,
)
from stoke_tpu.serving.sampling import (
    SamplingParams,
    accept_drafts,
    initial_key_data,
    sample_tokens,
    select_key_data,
    speculative_sample_tokens,
    split_key_data,
    validate_sampling_params,
)
from stoke_tpu.serving.scheduler import Request, Scheduler
from stoke_tpu.serving.slo import (
    RequestSLO,
    SLOTracker,
    resolve_request_slo,
)
from stoke_tpu.serving.telemetry import ServeMetrics
from stoke_tpu.telemetry.registry import MetricsRegistry
from stoke_tpu.telemetry.tracing import (
    dropped_total,
    request_spans,
    trace_add,
    trace_point,
    trace_span,
    tracing_active,
)

_KV_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class ServingEngine:
    """Continuous-batching inference engine over one GPT model.

    Built by :meth:`stoke_tpu.facade.Stoke.serve` (which supplies the
    trained params, telemetry pipeline, and compile cache) or standalone
    in tests/scripts.

    Args:
        model: a :class:`~stoke_tpu.models.gpt.GPT` module (dense FFN,
            ``chunked_head=False``).
        params: the model's ``params`` pytree (NOT the variables dict).
        cfg: :class:`~stoke_tpu.configs.ServeConfig`.
        registry: metrics registry for the ``serve/*`` instruments
            (defaults to ``telemetry.registry`` or a private one).
        telemetry: optional :class:`~stoke_tpu.telemetry.Telemetry` —
            when enabled, serve records land in its JSONL/Prometheus
            sinks with the ``serve/*`` field block.
        compile_cache: optional PR-6 :class:`~stoke_tpu.compile_cache
            .CompileCache` — prefill/decode programs register with its
            HLO-keyed ledger for warm starts.
        kv_sharding: optional sharding for the page pool (mesh-placed
            serving; default = wherever ``jnp.zeros`` lands).
        attribution: :class:`~stoke_tpu.configs.AttributionConfig`
            supplying the hardware peaks (``peak_tflops`` /
            ``peak_hbm_gbps``) the ISSUE 18 cost observatory rooflines
            against — required when ``cfg.cost_cards`` is on (the facade
            passes the run's config; standalone engines construct one).
        memory: optional :class:`~stoke_tpu.configs.MemoryConfig`
            (ISSUE 19) — arms the HBM capacity observatory: the engine
            registers its own subsystems (quantized weights, KV page
            pool), runs the serve-side OOM pre-flight at construction,
            and forecasts ``serve/mem_headroom_bytes`` (free-pool bytes
            minus the queue's worst-case block demand) every gauge
            refresh.  None (the default) constructs nothing.
    """

    def __init__(
        self,
        model: GPT,
        params: Any,
        cfg: ServeConfig,
        *,
        registry: Optional[MetricsRegistry] = None,
        telemetry=None,
        compile_cache=None,
        kv_sharding=None,
        attribution=None,
        memory=None,
    ):
        if not isinstance(model, GPT):
            raise TypeError(
                f"ServingEngine serves GPT models; got {type(model).__name__} "
                f"(the paged-cache decode forward lives in models/gpt.py)"
            )
        if model.chunked_head:
            raise ValueError(
                "ServingEngine needs logits from the forward; construct the "
                "serving GPT with chunked_head=False (params are identical)"
            )
        if model.moe_num_experts > 0:
            raise NotImplementedError(
                "ServingEngine supports dense-FFN GPT only (no MoE)"
            )
        if cfg.max_seq_len > model.max_len:
            raise ValueError(
                f"ServeConfig.max_seq_len={cfg.max_seq_len} exceeds the "
                f"model's max_len={model.max_len}"
            )
        if (
            cfg.prefill_chunk_tokens is not None
            and cfg.prefill_chunk_tokens % cfg.prefill_pad_multiple
        ):
            raise ValueError(
                f"prefill_chunk_tokens={cfg.prefill_chunk_tokens} must be "
                f"a multiple of prefill_pad_multiple="
                f"{cfg.prefill_pad_multiple} (the bucket discipline that "
                f"bounds compiled-program count; same rule the status "
                f"layer enforces)"
            )
        if cfg.speculative_k is not None and not cfg.sampling:
            raise ValueError(
                "ServeConfig.speculative_k needs sampling=True — the "
                "verify program rides the key-threaded sampling programs "
                "(temperature=0.0 keeps exact greedy streams); set "
                "sampling=True or drop speculative_k"
            )
        if cfg.cost_cards and attribution is None:
            raise ValueError(
                "ServeConfig.cost_cards needs the hardware peaks an "
                "AttributionConfig carries (peak_tflops / peak_hbm_gbps) "
                "to roofline against — pass attribution= to the engine "
                "(Stoke.serve() supplies the run's AttributionConfig)"
            )
        if _round_up(cfg.max_seq_len, cfg.prefill_pad_multiple) > model.max_len:
            raise ValueError(
                f"prefill padding bucket round_up(max_seq_len="
                f"{cfg.max_seq_len}, {cfg.prefill_pad_multiple}) exceeds the "
                f"model's max_len={model.max_len} — a full-length prompt "
                f"would pad past the position table; shrink max_seq_len or "
                f"prefill_pad_multiple"
            )
        self.model = model
        self.cfg = cfg
        self._telemetry = telemetry
        self._compile_cache = compile_cache
        self.metrics = ServeMetrics(
            registry
            if registry is not None
            else (
                telemetry.registry
                if telemetry is not None
                else MetricsRegistry()
            )
        )
        # SLO observatory (ISSUE 16): purely host-side — never enters a
        # dispatch argument list, so the compiled programs are identical
        # with and without it; inert (zero instruments, zero JSONL
        # fields) until the first SLO-tagged request arrives
        self.slo = SLOTracker(self.metrics.registry)

        size = BERT_SIZES[model.size_name]
        self._heads = size.heads
        self._head_dim = size.hidden // size.heads

        # --- weight store (pillar 4): quantize once at load time ---
        self.qparams = quantize_params(
            params,
            cfg.quant,
            chunk_elems=cfg.quant_chunk_elems,
            stochastic=cfg.quant_stochastic,
            min_size=cfg.quant_min_size,
        )
        self.quant_stats = compression_stats(params, self.qparams)
        self.metrics.quant_compression.set(self.quant_stats["compression"])
        # per-layer dequant-error attribution (ISSUE 12): computed ONCE at
        # quantize time — which module int8 hurt most bounds the serving
        # quality story, so it rides the registry (numerics/* gauges), the
        # engine surface (bench --serve quant_err columns), and
        # stats()["quant_errors"]
        self.quant_errors: Dict[str, Dict[str, float]] = {}
        self.quant_errors_by_group: Dict[str, Dict[str, float]] = {}
        self.quant_err_layer: Optional[str] = None
        self.quant_err_max: Optional[float] = None
        if cfg.quant == "int8":
            from stoke_tpu.serving.quant import quantization_error
            from stoke_tpu.telemetry.numerics import (
                leaf_path_names,
                max_quant_error,
                module_groups,
                quant_error_by_group,
            )

            self.quant_errors = quantization_error(params, self.qparams)
            self.quant_errors_by_group = quant_error_by_group(
                self.quant_errors,
                module_groups(params),
                leaf_path_names(params),
            )
            self.quant_err_layer, self.quant_err_max = max_quant_error(
                self.quant_errors_by_group
            )
            # gauge publication respects the ISSUE 12 default-OFF
            # contract: on a SHARED telemetry pipeline the numerics/*
            # series exist only when a NumericsConfig attached a monitor
            # (Stoke.serve() installs the table on it, which publishes);
            # a standalone engine's own registry publishes directly
            if telemetry is None:
                reg = self.metrics.registry
                for group, err in self.quant_errors_by_group.items():
                    reg.gauge(f"numerics/{group}/quant_err_rel_rms").set(
                        err["rel_rms"]
                    )

        # --- paged KV pool (pillar 1) ---
        max_blocks_per_seq = -(-cfg.max_seq_len // cfg.kv_block_size)
        self._max_blocks_per_seq = max_blocks_per_seq
        num_blocks = (
            cfg.kv_blocks
            if cfg.kv_blocks is not None
            else cfg.max_seqs * max_blocks_per_seq + 1  # +1 scratch
        )
        self.cache = PagedKVCache(
            size.num_layers,
            num_blocks,
            cfg.kv_block_size,
            self._heads,
            self._head_dim,
            dtype=_KV_DTYPES[cfg.kv_dtype],
            sharding=kv_sharding,
        )
        self.allocator = BlockAllocator(num_blocks, cfg.kv_block_size)

        # --- continuous-batching scheduler (pillar 2) ---
        self.scheduler = Scheduler(
            cfg.max_seqs,
            self.allocator,
            max_blocks_per_seq,
            max_seq_len=cfg.max_seq_len,
            default_max_new_tokens=cfg.max_new_tokens,
            eos_id=cfg.eos_id,
            pad_multiple=cfg.prefill_pad_multiple,
            prefill_chunk_tokens=cfg.prefill_chunk_tokens,
            sampling_seed_base=cfg.sampling_seed,
        )

        # --- serve fast path (ISSUE 13): decode kernel + sampling state ---
        # pallas decode off-TPU auto-falls-back to the interpreter (the
        # CPU parity mode the tests pin); a REAL serve config declaring a
        # CPU device is rejected upstream by the status layer instead
        self._decode_interpret = (
            jax.default_backend() != "tpu"
            if cfg.decode_kernel == "pallas"
            else None
        )
        self._sampling = bool(cfg.sampling)
        # config-level default knobs (requests may override per-submit);
        # greedy when sampling is off — those engines never consult them
        self._default_sampling = (
            SamplingParams(
                temperature=cfg.temperature,
                top_k=cfg.top_k,
                top_p=cfg.top_p,
            )
            if self._sampling
            else SamplingParams()
        )
        if self._sampling:
            validate_sampling_params(self._default_sampling)
        # per-slot PRNG key state, threaded through the sampling-mode
        # dispatches like the KV pages (wrapped to TYPED keys in-program,
        # split once per emitted token, advanced data written back) —
        # maintained whenever any program consumes it
        kd = initial_key_data(0)
        self._key_data = np.zeros(
            (cfg.max_seqs,) + kd.shape, kd.dtype
        )
        # counterfactual-parity hook (tests): when True, every sampling-
        # mode dispatch's PRE-sampling logits are fetched and recorded
        # per request id — the bit-match check staggered-vs-sequential
        # sampling leans on (greedy streams can no longer assert it)
        self.capture_logits = False
        self.captured_logits: Dict[int, List[np.ndarray]] = {}

        # --- compiled programs (pillar 3) ---
        # donation keeps the page pool in-place in HBM; the CPU backend
        # has no donation (jax warns and copies), so only donate off-CPU
        donate = (1, 2) if jax.default_backend() != "cpu" else ()
        if self._sampling:
            self._prefill_jit = jax.jit(
                self._prefill_sampling_fn, donate_argnums=donate
            )
            self._decode_jit = jax.jit(
                self._decode_sampling_fn, donate_argnums=donate
            )
        else:
            # greedy programs are the PRE-ISSUE-13 ones verbatim: with
            # decode_kernel="reference" their HLO and token streams are
            # bit-identical to the pre-fast-path engine
            self._prefill_jit = jax.jit(
                self._prefill_fn, donate_argnums=donate
            )
            self._decode_jit = jax.jit(self._decode_fn, donate_argnums=donate)
        self._chunk_jit = (
            jax.jit(self._chunk_fn, donate_argnums=donate)
            if cfg.prefill_chunk_tokens is not None
            else None
        )
        # speculative decoding (ISSUE 17): the verify program replaces the
        # per-token decode program, and chunk packing replaces the
        # one-chunk-per-iteration schedule with the same multi-token-query
        # program shape.  Both are construction-time choices gated on
        # speculative_k — a speculative_k=None engine compiles the PR-13
        # programs verbatim (HLO bit-identical, the default-OFF contract
        # audit_specs lowering asserts).
        self._speculative_k = cfg.speculative_k
        self._verify_jit = (
            jax.jit(self._verify_fn, donate_argnums=donate)
            if cfg.speculative_k is not None
            else None
        )
        self._packed_chunk_jit = (
            jax.jit(self._packed_chunk_fn, donate_argnums=donate)
            if (
                cfg.speculative_k is not None
                and cfg.prefill_chunk_tokens is not None
            )
            else None
        )
        if cfg.speculative_k is not None:
            self.metrics.enable_speculative()

        # program-audit ledger (ISSUE 15): one abstract spec per
        # (program, shape signature), recorded at the dispatch funnel so
        # Stoke.audit() can statically check the serve programs exactly
        # like the step programs — donation per the tuple jit actually
        # received (empty on CPU, where pages are copied, not donated)
        self._donate = donate
        self._audit_specs: list = []
        self._audit_seen: set = set()

        # serve roofline observatory (ISSUE 18): host-side cost cards
        # over the dispatch funnel — never enters an argument list, so
        # the compiled serve programs are HLO bit-identical with and
        # without it (the audit_specs lowering test pins this); absent
        # (None) entirely when cost_cards is off, so an unconfigured
        # engine registers zero serve/cost series and its JSONL records
        # carry zero new fields
        self._cost = None
        if cfg.cost_cards:
            from stoke_tpu.serving.roofline import ServeCostObservatory

            self._cost = ServeCostObservatory(
                self.metrics,
                attribution.peak_tflops,
                attribution.peak_hbm_gbps,
            )
            if self._verify_jit is not None:
                # a speculative engine never dispatches plain decode:
                # lower it at the decode-batch shapes (abstract args
                # only) so the verify program's intensity uplift has its
                # counterfactual leg
                self._cost.set_decode_baseline(
                    self._decode_jit, self._decode_baseline_args()
                )

        # HBM capacity observatory (ISSUE 19): same host-side discipline
        # as the cost cards — never enters an argument list, so the
        # compiled serve programs stay HLO bit-identical with and without
        # it.  The engine registers the two subsystems it owns (the
        # quantized weight store and the KV page pool) and runs the
        # serve-side OOM pre-flight HERE, before the first request can
        # allocate a block.
        self._memory = None
        if memory is not None:
            from stoke_tpu.telemetry.memory import (
                MemoryObservatory,
                tree_resident_bytes,
            )

            self._memory = MemoryObservatory(memory, self.metrics.registry)
            self._memory.set_component(
                "params", lambda: tree_resident_bytes(self.qparams)
            )
            self._memory.set_component(
                "kv_cache", lambda: self.cache.nbytes
            )
            self._memory.preflight("serve")

        self._iterations = 0
        self._last_emit_iter = 0
        self._t_start = time.perf_counter()

    # ------------------------------------------------------------------ #
    # compiled program bodies
    # ------------------------------------------------------------------ #

    def _apply(self, params, tokens, positions, hook, decode: bool):
        return self.model.apply(
            {"params": params},
            tokens,
            train=False,
            positions=positions,
            decode=decode,
            kv_cache=hook,
        )

    def _make_hook(self, k_pages, v_pages, tables, positions, mode, lengths):
        """The per-trace cache hook with this engine's kernel selection —
        with the default ``decode_kernel="reference"`` the constructed
        graph is op-for-op the pre-ISSUE-13 one."""
        return PagedAttentionHook(
            k_pages, v_pages, tables, positions,
            mode=mode, lengths=lengths,
            attention_impl=self.cfg.attention,
            decode_impl=self.cfg.decode_kernel,
            decode_pages_per_block=self.cfg.decode_pages_per_block,
            decode_block_h=self.cfg.decode_block_h,
            decode_interpret=self._decode_interpret,
            verify_pages_per_block=self.cfg.verify_pages_per_block,
            verify_block_h=self.cfg.verify_block_h,
        )

    def _prefill_fn(self, qparams, k_pages, v_pages, tokens, block_row,
                    prompt_len):
        """tokens [1, P] padded prompt; block_row [1, MB]; prompt_len [1].
        Returns (first generated token [1], updated pages)."""
        params = dequantize_params(qparams)
        P = tokens.shape[1]
        positions = jnp.arange(P, dtype=jnp.int32)[None, :]
        hook = self._make_hook(
            k_pages, v_pages, block_row, positions, "prefill", prompt_len
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        last = logits[0, prompt_len[0] - 1]
        return (
            jnp.argmax(last, axis=-1).astype(jnp.int32)[None],
            hook.k_pages,
            hook.v_pages,
        )

    def _decode_fn(self, qparams, k_pages, v_pages, tokens, positions,
                   block_tables, context_lens):
        """tokens/positions [B]; block_tables [B, MB]; context_lens [B].
        Returns (next tokens [B], updated pages)."""
        params = dequantize_params(qparams)
        hook = self._make_hook(
            k_pages, v_pages, block_tables, positions[:, None], "decode",
            context_lens,
        )
        logits = self._apply(
            params, tokens[:, None], positions[:, None], hook, decode=True
        )
        return (
            jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32),
            hook.k_pages,
            hook.v_pages,
        )

    # --- sampling-mode programs (ISSUE 13): same forward, the draw added
    # in-program on the pre-sampling logits; key state threaded like the
    # pages.  Compiled INSTEAD of the greedy bodies only when
    # ``ServeConfig.sampling`` is set, so the default engine's programs
    # stay bit-identical to pre-fast-path. ---

    def _prefill_sampling_fn(self, qparams, k_pages, v_pages, tokens,
                             block_row, prompt_len, key_data, temp, top_k,
                             top_p):
        """Sampling prefill: returns (token [1], advanced key data,
        pre-sampling logits row [1, V], updated pages)."""
        params = dequantize_params(qparams)
        P = tokens.shape[1]
        positions = jnp.arange(P, dtype=jnp.int32)[None, :]
        hook = self._make_hook(
            k_pages, v_pages, block_row, positions, "prefill", prompt_len
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        row = logits[0, prompt_len[0] - 1][None, :]
        key_out, sub = split_key_data(key_data)
        tok = sample_tokens(row, sub, temp, top_k, top_p)
        return tok, key_out, row, hook.k_pages, hook.v_pages

    def _decode_sampling_fn(self, qparams, k_pages, v_pages, tokens,
                            positions, block_tables, context_lens, key_data,
                            temps, top_ks, top_ps):
        """Sampling decode: returns (tokens [B], advanced key data,
        pre-sampling logits [B, V], updated pages)."""
        params = dequantize_params(qparams)
        hook = self._make_hook(
            k_pages, v_pages, block_tables, positions[:, None], "decode",
            context_lens,
        )
        logits = self._apply(
            params, tokens[:, None], positions[:, None], hook, decode=True
        )[:, -1, :]
        key_out, sub = split_key_data(key_data)
        tok = sample_tokens(logits, sub, temps, top_ks, top_ps)
        return tok, key_out, logits, hook.k_pages, hook.v_pages

    def _chunk_fn(self, qparams, k_pages, v_pages, tokens, positions,
                  block_row, prompt_len, logit_idx, key_data, temp, top_k,
                  top_p):
        """ONE chunked-prefill step (ISSUE 13): tokens [1, C] at GLOBAL
        positions [1, C]; writes the chunk's K/V into the request's
        blocks and attends over everything cached so far (causal by
        global position).  Samples from the ``logit_idx`` row — the last
        prompt token's — which only the FINAL chunk's caller consumes
        (greedy encodes as temperature 0, so one program serves both
        modes; the chunk shape is fixed, so the compile-cache ledger
        registers it once)."""
        params = dequantize_params(qparams)
        hook = self._make_hook(
            k_pages, v_pages, block_row, positions, "chunk", prompt_len
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        row = logits[0, logit_idx[0]][None, :]
        key_out, sub = split_key_data(key_data)
        tok = sample_tokens(row, sub, temp, top_k, top_p)
        return tok, key_out, row, hook.k_pages, hook.v_pages

    # --- speculative programs (ISSUE 17): fixed-shape k-token verify and
    # packed chunked prefill — both the multi-token-query shape the chunk
    # program pinned, compiled only when ``speculative_k`` is set. ---

    def _verify_fn(self, qparams, k_pages, v_pages, tokens, positions,
                   block_tables, lengths, draft_lens, key_data, temps,
                   top_ks, top_ps):
        """ONE speculative verify step (ISSUE 17): tokens ``[B, S]`` =
        each slot's pending token + up to k drafts at GLOBAL positions
        ``[B, S]``; scores all S positions in one forward, draws the S
        sequential target tokens from each slot's key stream, accepts
        the leading exact-match run, rolls rejected positions' K/V back
        out of the cache (scratch-steered restore — rejected drafts
        never dirty the pool across dispatches), and rewinds each slot's
        key state to one split per EMITTED token.  Returns ``(targets
        [B, S], n_emit [B], key data [B, ...], pre-sampling logits
        [B, S, V], updated pages)``."""
        params = dequantize_params(qparams)
        hook = self._make_hook(
            k_pages, v_pages, block_tables, positions, "verify", lengths
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        targets, key_stack = speculative_sample_tokens(
            logits, key_data, temps, top_ks, top_ps
        )
        n_emit = accept_drafts(tokens[:, 1:], draft_lens, targets)
        hook.rollback(n_emit)
        key_out = select_key_data(key_stack, n_emit)
        return targets, n_emit, key_out, logits, hook.k_pages, hook.v_pages

    def _packed_chunk_fn(self, qparams, k_pages, v_pages, tokens, positions,
                         block_tables, lengths, logit_idx, key_data, temps,
                         top_ks, top_ps):
        """Packed chunked prefill (ISSUE 17): every prefilling slot's next
        chunk rides ONE dispatch — tokens ``[B, C]`` at global positions
        ``[B, C]`` against the full slot batch's tables (idle rows
        scratch-steered, outputs discarded), the same multi-token-query
        shape as :meth:`_verify_fn`.  Samples every row at its own
        ``logit_idx`` (only final-chunk rows' draws are consumed; their
        callers also take the key writeback, preserving one split per
        emitted token).  Returns ``(tokens [B], advanced key data,
        pre-sampling logit rows [B, V], updated pages)``."""
        params = dequantize_params(qparams)
        hook = self._make_hook(
            k_pages, v_pages, block_tables, positions, "chunk", lengths
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        rows = jnp.take_along_axis(
            logits, logit_idx[:, None, None], axis=1
        )[:, 0]  # [B, V]
        key_out, sub = split_key_data(key_data)
        tok = sample_tokens(rows, sub, temps, top_ks, top_ps)
        return tok, key_out, rows, hook.k_pages, hook.v_pages

    # ------------------------------------------------------------------ #
    # program-signature dispatch (PR-6 AOT ledger registration)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _sig(args) -> tuple:
        return tuple(
            (tuple(l.shape), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(args)
            if hasattr(l, "shape")
        )

    def _note_audit(self, program: str, fn, args: tuple) -> None:
        """Record one abstract ProgramSpec per PROGRAM for the ISSUE 15
        auditor (the StepEngine._note_audit contract: shapes/dtypes/
        shardings only, pre-donation).  Keyed by program NAME alone —
        pad buckets share one program body, and auditing one
        representative keeps the steady-state decode loop's cost at a
        single set lookup (no per-token tree walk)."""
        if program in self._audit_seen:
            return
        self._audit_seen.add(program)
        from stoke_tpu.analysis.program import ProgramSpec, abstractify_args

        avals, weak = abstractify_args(args)
        self._audit_specs.append(
            ProgramSpec(
                program=program,
                fn=fn,
                abstract_args=avals,
                donate_argnums=self._donate,
                weak_leaves=weak,
                source="serve",
            )
        )

    def audit_specs(self) -> list:
        """The recorded serve-program specs (ISSUE 15; consumed by
        ``Stoke.audit(serve=engine)`` or a standalone
        ``audit_program_specs`` call)."""
        return list(self._audit_specs)

    def _decode_baseline_args(self) -> tuple:
        """Abstract (ShapeDtypeStruct) argument tuple for ONE plain-decode
        dispatch at this engine's fixed batch shapes — what the roofline
        observatory lowers on a speculative engine (which never dispatches
        plain decode) so the verify program's arithmetic-intensity uplift
        keeps its counterfactual leg.  Lowering-only: no arrays are
        materialized and nothing executes."""
        abstract = lambda leaf: jax.ShapeDtypeStruct(  # noqa: E731
            leaf.shape, leaf.dtype
        )
        B = self.cfg.max_seqs
        i32 = jnp.int32
        args = (
            jax.tree_util.tree_map(abstract, self.qparams),
            abstract(self.cache.k_pages),
            abstract(self.cache.v_pages),
            jax.ShapeDtypeStruct((B,), i32),  # tokens
            jax.ShapeDtypeStruct((B,), i32),  # positions
            jax.ShapeDtypeStruct((B, self._max_blocks_per_seq), i32),
            jax.ShapeDtypeStruct((B,), i32),  # context_lens
        )
        if self._sampling:
            args += (
                abstract(jnp.asarray(self._key_data)),
                jax.ShapeDtypeStruct((B,), jnp.float32),  # temps
                jax.ShapeDtypeStruct((B,), i32),  # top_ks
                jax.ShapeDtypeStruct((B,), jnp.float32),  # top_ps
            )
        return args

    def _dispatch(self, program: str, fn, args: tuple):
        """Route one dispatch through the compile cache's program ledger
        (same contract as ``StepEngine._aot_call``): first dispatch per
        (program, shape signature) checks the HLO-keyed ledger — warm
        starts resolve to an already-built fn and book reclaimed compile
        seconds — and every dispatch runs plain ``jax.jit`` semantics."""
        self._note_audit(program, fn, args)
        if self._cost is not None:
            self._cost.note_dispatch(program, fn, args, self._sig(args))
        if self._memory is not None:
            self._memory.note_program(program, fn, args, self._sig(args))
        cc = self._compile_cache
        if cc is not None:
            fn = cc.executable(program, (program, self._sig(args)), fn, args)
        return fn(*args)

    # ------------------------------------------------------------------ #
    # request intake
    # ------------------------------------------------------------------ #

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        sampling: Optional[SamplingParams] = None,
        slo: Optional[RequestSLO] = None,
    ) -> int:
        """Enqueue one request (mid-flight is the point); returns its id.

        ``sampling`` (ISSUE 13) carries per-request temperature / top-k /
        top-p / seed — validated here, never mid-decode — and requires
        ``ServeConfig.sampling=True`` (the sampling-aware programs are a
        construction-time choice; the default greedy engine's programs
        are bit-identical to pre-fast-path).  Without it the request uses
        the config's default knobs; a request without an explicit seed
        gets the deterministic per-request default
        ``sampling_seed + rid``, so whole runs replay from the config.

        ``slo`` (ISSUE 16) carries the request's priority class and
        TTFT/TPOT deadlines — same contract: validated here, never
        mid-decode, unset targets resolved from the
        ``ServeConfig.slo_ttft_target_s`` / ``slo_tpot_target_s``
        defaults.  Purely host-side accounting; the compiled programs
        never see it.
        """
        if sampling is not None:
            if not self._sampling:
                raise ValueError(
                    "per-request SamplingParams need ServeConfig."
                    "sampling=True (the sampling-aware decode programs "
                    "are compiled at engine construction; docs/serving.md)"
                )
            validate_sampling_params(sampling)
            params = sampling
        else:
            params = self._default_sampling
        if slo is not None:
            slo = resolve_request_slo(
                slo, self.cfg.slo_ttft_target_s, self.cfg.slo_tpot_target_s
            )
        # the scheduler resolves the seed beside the rid it assigns
        # (explicit params.seed wins, else sampling_seed + rid)
        rid = self.scheduler.submit(
            prompt, max_new_tokens, eos_id, params=params, slo=slo
        )
        self.metrics.requests.inc()
        if slo is not None:
            # the queue tail IS the request just enqueued (single-threaded
            # intake; the scheduler appends before returning the rid)
            self.slo.on_submit(self.scheduler.queue[-1])
        return rid

    def result(self, rid: int) -> Optional[Request]:
        return self.scheduler.finished.get(rid)

    # ------------------------------------------------------------------ #
    # the engine loop
    # ------------------------------------------------------------------ #

    def _sampling_scalar_args(self, params: SamplingParams, slot: int):
        """The per-request sampling tail of a prefill/chunk dispatch:
        (key_data [1, ...], temperature [1], top_k [1], top_p [1])."""
        t, k, p = params.as_arrays()
        return (
            jnp.asarray(self._key_data[slot : slot + 1]),
            jnp.array([t], jnp.float32),
            jnp.array([k], jnp.int32),
            jnp.array([p], jnp.float32),
        )

    def _emit_first_token(self, slot, req, tok_host, now):
        """Shared bookkeeping for the TTFT token, whether it came from the
        one-shot prefill program or the final prefill chunk."""
        m = self.metrics
        self.scheduler.note_prefill_token(slot, tok_host, now)
        m.tokens_out.inc()
        if not req.params.is_greedy:
            m.sampled_tokens.inc()
        m.observe_ttft(req.ttft_s)
        if req.finished:
            self._finish(req)

    def _prefill_one(self, slot, req, padded, plen) -> None:
        """Unchunked prefill: one program over the bucket-padded prompt
        (the pre-ISSUE-13 path, sampling-aware when enabled)."""
        sched, m = self.scheduler, self.metrics
        t0 = time.perf_counter()
        with trace_span("serve/prefill", track="serve",
                        request_id=req.rid,
                        attrs={"padded_len": int(padded.shape[1])}):
            args = (
                self.qparams,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(padded),
                jnp.asarray(sched.block_tables[slot : slot + 1]),
                jnp.array([plen], jnp.int32),
            )
            if self._sampling:
                args += self._sampling_scalar_args(req.params, slot)
                tok, key_out, row, k_pages, v_pages = self._dispatch(
                    "serve_prefill", self._prefill_jit, args
                )
                self._key_data[slot] = np.asarray(key_out)[0]
                if self.capture_logits:
                    self.captured_logits.setdefault(req.rid, []).append(
                        np.asarray(row)[0].copy()
                    )
            else:
                tok, k_pages, v_pages = self._dispatch(
                    "serve_prefill", self._prefill_jit, args
                )
            self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
            tok_host = int(np.asarray(tok)[0])  # sync: the TTFT point
        now = time.perf_counter()
        m.prefills.inc()
        m.prefill_s.inc(now - t0)
        self._emit_first_token(slot, req, tok_host, now)

    def _run_chunk(self, slot, req, toks, positions, is_final,
                   logit_idx) -> None:
        """One chunked-prefill step (ISSUE 13): dispatch the fixed-shape
        chunk program for ``slot``; the final chunk produces the TTFT
        token.  Only the final chunk syncs to host and advances the
        request's key stream — one split per emitted token, the same
        recurrence as unchunked prefill."""
        sched, m = self.scheduler, self.metrics
        t0 = time.perf_counter()
        with trace_span(
            "serve/prefill_chunk", track="serve", request_id=req.rid,
            attrs={
                "start": int(positions[0]),
                "chunk": int(toks.shape[0]),
                "final": bool(is_final),
            },
        ):
            args = (
                self.qparams,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(toks[None, :]),
                jnp.asarray(positions[None, :]),
                jnp.asarray(sched.block_tables[slot : slot + 1]),
                jnp.array([int(req.prompt.size)], jnp.int32),
                jnp.array([logit_idx], jnp.int32),
            ) + self._sampling_scalar_args(req.params, slot)
            tok, key_out, row, k_pages, v_pages = self._dispatch(
                "serve_prefill_chunk", self._chunk_jit, args
            )
            self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
            # EVERY chunk syncs (one [1] token fetch): dispatch is async,
            # and without the sync the chunk's compute would be charged to
            # the NEXT decode step's fetch — the serve/prefill_chunk spans
            # and the prefill goodput bucket must own their real wall
            tok_host = int(np.asarray(tok)[0])
        now = time.perf_counter()
        m.prefill_chunks.inc()
        m.prefill_s.inc(now - t0)
        sched.note_chunk(slot)
        if is_final:
            self._key_data[slot] = np.asarray(key_out)[0]
            if self.capture_logits:
                self.captured_logits.setdefault(req.rid, []).append(
                    np.asarray(row)[0].copy()
                )
            self._emit_first_token(slot, req, tok_host, now)

    def _run_packed_chunks(self, tokens, positions, tables, lengths,
                           logit_idx, rows) -> None:
        """One PACKED chunked-prefill step (ISSUE 17): every prefilling
        slot's next chunk rides one fixed-shape ``[B, C]`` dispatch.
        Final-chunk rows produce their TTFT tokens and take the key
        writeback; every serviced row advances its prefill cursor."""
        sched, m = self.scheduler, self.metrics
        B = self.cfg.max_seqs
        temps = np.zeros(B, np.float32)
        ks = np.zeros(B, np.int32)
        ps = np.ones(B, np.float32)
        for i, req, _is_final in rows:
            temps[i], ks[i], ps[i] = req.params.as_arrays()
        t0 = time.perf_counter()
        with trace_span(
            "serve/prefill_chunk_packed", track="serve",
            attrs={"packed": len(rows), "chunk": int(tokens.shape[1])},
        ):
            args = (
                self.qparams,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(tables),
                jnp.asarray(lengths),
                jnp.asarray(logit_idx),
                jnp.asarray(self._key_data),
                jnp.asarray(temps),
                jnp.asarray(ks),
                jnp.asarray(ps),
            )
            tok, key_out, logit_rows, k_pages, v_pages = self._dispatch(
                "serve_prefill_chunk_packed", self._packed_chunk_jit, args
            )
            self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
            # sync for the same reason the single-chunk path does: the
            # chunk compute must be charged to the prefill bucket, not
            # the next dispatch's fetch
            tok_host = np.asarray(tok)
        now = time.perf_counter()
        m.prefill_chunks.inc()  # dispatches, not serviced rows
        m.prefill_s.inc(now - t0)
        kd = np.asarray(key_out)
        larr = np.asarray(logit_rows) if self.capture_logits else None
        for i, req, is_final in rows:
            if tracing_active():
                # per-request slice of the shared packed interval — the
                # SLO attribution walk keys on the serve/prefill_chunk
                # span name; count_self=False since the packed span above
                # owns the wall once
                trace_add(
                    "serve/prefill_chunk", t0, now, track="serve",
                    request_id=req.rid, count_self=False,
                )
            sched.note_chunk(i)
            if is_final:
                self._key_data[i] = kd[i]
                if larr is not None:
                    self.captured_logits.setdefault(req.rid, []).append(
                        larr[i].copy()
                    )
                self._emit_first_token(i, req, int(tok_host[i]), now)

    def _step_verify(self) -> None:
        """One speculative decode step (ISSUE 17): draft host-side,
        verify all draft positions in one dispatch, commit the accepted
        run + the correction/bonus token.  Replaces the per-token decode
        dispatch — ``decode_steps`` still counts dispatches, so
        tokens_out / decode_steps IS accepted-tokens-per-dispatch."""
        sched, m = self.scheduler, self.metrics
        k = self._speculative_k
        decode_rows = [
            i
            for i, s in enumerate(sched.slots)
            if s.request is not None and s.prefill_pos is None
        ]
        live_rids = (
            [sched.slots[i].request.rid for i in decode_rows]
            if tracing_active()
            else None
        )
        t0 = time.perf_counter()
        with trace_span("serve/verify_step", track="serve",
                        attrs={"active": sched.decoding, "k": k}):
            tokens, positions, tables, lengths, draft_lens = (
                sched.verify_batch(
                    k,
                    ngram_max=self.cfg.speculative_ngram_max,
                    ngram_min=self.cfg.speculative_ngram_min,
                )
            )
            temps, tks, tps = sched.sampling_batch()
            args = (
                self.qparams,
                self.cache.k_pages,
                self.cache.v_pages,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(tables),
                jnp.asarray(lengths),
                jnp.asarray(draft_lens),
                jnp.asarray(self._key_data),
                jnp.asarray(temps),
                jnp.asarray(tks),
                jnp.asarray(tps),
            )
            targets, n_emit, key_out, logits, k_pages, v_pages = (
                self._dispatch("serve_verify", self._verify_jit, args)
            )
            self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
            targets_host = np.asarray(targets)  # sync: tokens stream out
            n_emit_host = np.asarray(n_emit)
            kd = np.asarray(key_out)
            for i in decode_rows:
                self._key_data[i] = kd[i]
            if self.capture_logits:
                larr = np.asarray(logits)
                for i in decode_rows:
                    rid = sched.slots[i].request.rid
                    # one pre-sampling logits row per EMITTED token, so
                    # speculative captures align 1:1 with the
                    # non-speculative engine's per-step captures
                    for j in range(int(n_emit_host[i])):
                        self.captured_logits.setdefault(rid, []).append(
                            larr[i, j].copy()
                        )
        now = time.perf_counter()
        if live_rids:
            for rid in live_rids:
                trace_add("serve/decode", t0, now, track="serve",
                          request_id=rid, count_self=False)
        m.decode_steps.inc()
        m.decode_s.inc(now - t0)
        # greedy-ness per row, read BEFORE commit evicts finished slots
        greedy_row = {
            i: sched.slots[i].request.params.is_greedy for i in decode_rows
        }
        was_finished = set(sched.finished)
        committed, accepted = sched.commit_verify(
            targets_host, n_emit_host, now
        )
        m.tokens_out.inc(int(committed.sum()))
        m.spec_draft_tokens.inc(int(draft_lens.sum()))
        m.spec_accepted_tokens.inc(accepted)
        n_sampled = sum(
            int(committed[i]) for i in decode_rows if not greedy_row[i]
        )
        if n_sampled:
            m.sampled_tokens.inc(n_sampled)
        for rid in set(sched.finished) - was_finished:
            self._finish(sched.finished[rid])

    def step(self) -> bool:
        """One engine iteration: admit arrivals (short prompts prefill
        whole; long ones enter the chunked-prefill state), run at most ONE
        prefill chunk, then one decode step over the fully-prefilled slot
        batch.  Bounding per-iteration prefill work by the chunk size is
        what keeps in-flight TPOT flat while a long prompt admits.
        Returns True while work remains."""
        sched = self.scheduler
        m = self.metrics

        for slot, req, padded, plen in sched.admit():
            if tracing_active():
                # the request timeline's first span: arrival → admission
                # (the queue wait) on the request's own track row
                # count_self=False: the queue wait overlaps other
                # requests' prefill/decode spans, which own that wall
                trace_add(
                    "serve/admission", req.arrival_ts, req.admit_ts,
                    track="serve", request_id=req.rid,
                    attrs={"prompt_len": plen}, count_self=False,
                )
            if req.slo is not None:
                self.slo.on_admit(req)
            if self._sampling or self._chunk_jit is not None:
                self._key_data[slot] = initial_key_data(req.seed)
            if padded is None:
                continue  # chunked admission: chunks run below
            self._prefill_one(slot, req, padded, plen)

        if self._packed_chunk_jit is not None:
            nxt = sched.next_chunks()
            if nxt is not None:
                self._run_packed_chunks(*nxt)
        else:
            nxt = sched.next_chunk()
            if nxt is not None:
                self._run_chunk(*nxt)

        if sched.decoding > 0 and self._verify_jit is not None:
            self._step_verify()
        elif sched.decoding > 0:
            # rows in the decode batch (fully-prefilled slots) BEFORE the
            # commit evicts any — each gets a per-request decode-slice
            # span below, and sampling key writebacks target exactly them
            decode_rows = [
                i
                for i, s in enumerate(sched.slots)
                if s.request is not None and s.prefill_pos is None
            ]
            live_rids = (
                [sched.slots[i].request.rid for i in decode_rows]
                if tracing_active()
                else None
            )
            t0 = time.perf_counter()
            with trace_span("serve/decode_step", track="serve",
                            attrs={"active": sched.decoding}):
                tokens, positions, tables, context = sched.decode_batch()
                args = (
                    self.qparams,
                    self.cache.k_pages,
                    self.cache.v_pages,
                    jnp.asarray(tokens),
                    jnp.asarray(positions),
                    jnp.asarray(tables),
                    jnp.asarray(context),
                )
                if self._sampling:
                    temps, ks, ps = sched.sampling_batch()
                    args += (
                        jnp.asarray(self._key_data),
                        jnp.asarray(temps),
                        jnp.asarray(ks),
                        jnp.asarray(ps),
                    )
                    next_tok, key_out, logits, k_pages, v_pages = (
                        self._dispatch(
                            "serve_decode", self._decode_jit, args
                        )
                    )
                else:
                    next_tok, k_pages, v_pages = self._dispatch(
                        "serve_decode", self._decode_jit, args
                    )
                self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
                next_host = np.asarray(next_tok)  # sync: tokens stream out
                if self._sampling:
                    # advance ONLY the decoding slots' key streams: a
                    # request's draw sequence depends on its own seed and
                    # token count, never on who else rode the batch
                    kd = np.asarray(key_out)
                    for i in decode_rows:
                        self._key_data[i] = kd[i]
                    if self.capture_logits:
                        larr = np.asarray(logits)
                        for i in decode_rows:
                            rid = sched.slots[i].request.rid
                            self.captured_logits.setdefault(rid, []).append(
                                larr[i].copy()
                            )
            now = time.perf_counter()
            if live_rids:
                # per-request decode slices: every live request's timeline
                # row shows the batch decode interval it rode (the TPOT
                # structure the histograms only summarize).
                # count_self=False: all slices share ONE interval the
                # serve/decode_step span above already owns — charging
                # each would multiply-count the window by batch depth
                for rid in live_rids:
                    trace_add("serve/decode", t0, now, track="serve",
                              request_id=rid, count_self=False)
            m.decode_steps.inc()
            m.decode_s.inc(now - t0)
            n_sampled = sum(
                1
                for i in decode_rows
                if not sched.slots[i].request.params.is_greedy
            )
            was_finished = set(sched.finished)
            live = sched.commit_decode(next_host, now)
            m.tokens_out.inc(live)
            if n_sampled:
                m.sampled_tokens.inc(n_sampled)
            for rid in set(sched.finished) - was_finished:
                self._finish(sched.finished[rid])

        self._iterations += 1
        self._refresh_gauges()
        if (
            self._iterations - self._last_emit_iter
            >= self.cfg.log_every_n_steps
        ):
            self.emit_record()
        return sched.has_work

    def run(self, max_steps: Optional[int] = None) -> int:
        """Drive :meth:`step` until drained (or ``max_steps``); emits a
        final telemetry record.  Returns iterations run."""
        n = 0
        while self.scheduler.has_work:
            self.step()
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        if self._iterations != self._last_emit_iter:
            # final record on drain — unless the last step() just emitted
            # at the cadence (a duplicate step key would confuse readers)
            self.emit_record()
        return n

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> List[List[int]]:
        """Convenience batch API: submit all, drain, return token lists in
        prompt order (the continuous batcher still interleaves them)."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [list(self.scheduler.finished[r].tokens) for r in rids]

    # ------------------------------------------------------------------ #
    # accounting
    # ------------------------------------------------------------------ #

    def _finish(self, req: Request) -> None:
        # eviction marker closes the request's trace timeline (its blocks
        # are already back in the pool — scheduler._finish freed them)
        trace_point(
            "serve/evict", track="serve", request_id=req.rid,
            attrs={"tokens": len(req.tokens)},
        )
        m = self.metrics
        m.completed.inc()
        tpot = req.tpot_s
        if tpot is not None:
            m.observe_tpot(tpot)
        if req.slo is not None:
            # finalize attainment + re-walk the request's span timeline
            # into the violation-attribution buckets (ISSUE 16); a ring
            # that dropped spans marks the attribution partial
            self.slo.on_finish(
                req, request_spans(req.rid), dropped_total()
            )
        if self._telemetry is not None:
            self._telemetry.add_tokens(len(req.tokens))

    def _refresh_gauges(self) -> None:
        m, sched = self.metrics, self.scheduler
        m.queue_depth.set(sched.queued)
        m.active_seqs.set(sched.active)
        m.batch_fill.set(sched.batch_fill)
        m.kv_blocks_used.set(self.allocator.used_blocks)
        m.kv_occupancy.set(self.allocator.occupancy)
        # sums-to-wall: queue/idle is the wall clock neither program used
        wall = time.perf_counter() - self._t_start
        target = max(
            0.0, wall - m.prefill_s.value - m.decode_s.value
        )
        if target > m.queue_s.value:
            m.queue_s.inc(target - m.queue_s.value)
        if self._cost is not None:
            # roofline gauges first, then hand the SLO tracker the current
            # model-FLOPs-per-token so its TFLOP-goodput column tracks the
            # same analytic cost the cards carry
            self._cost.refresh_gauges()
            self.slo.set_flops_per_token(self._cost.flops_per_token())
        self.slo.refresh_gauges()
        if self._memory is not None:
            self._memory.note_serve_headroom(self._mem_headroom_bytes())
            self._memory.refresh_gauges()

    def _mem_headroom_bytes(self) -> float:
        """KV-pool headroom forecast (ISSUE 19): free-pool bytes minus
        the worst-case blocks-to-completion still owed to in-flight work.
        Admission reserves every ACTIVE request's full worst-case budget
        up front (the allocator contract), so the outstanding demand is
        the QUEUE's: each queued request will claim
        ``blocks_for(prompt + max_new_tokens)`` at admission.  Negative
        headroom forecasts that the queue cannot be admitted against the
        current pool — the bursty-admission signal."""
        alloc = self.allocator
        queued_blocks = sum(
            alloc.blocks_for(req.prompt.size + req.max_new_tokens)
            for req in self.scheduler.queue
        )
        bytes_per_block = self.cache.nbytes / max(alloc.num_blocks, 1)
        return (alloc.free_blocks - queued_blocks) * bytes_per_block

    def emit_record(self) -> Optional[dict]:
        """Write one JSONL serve record through the telemetry pipeline
        (None when no enabled Telemetry is attached; the registry gauges
        update regardless)."""
        self._refresh_gauges()
        window = max(1, self._iterations - self._last_emit_iter)
        self._last_emit_iter = self._iterations
        if self._telemetry is None or not self._telemetry.enabled:
            return None
        # the serve/slo_* and serve/cost_* blocks are conditional: {} /
        # absent until armed, so an engine without SLO-tagged requests or
        # cost cards emits records with zero new fields (build_step_event
        # honors the omission)
        return self._telemetry.record_step(
            step=self._iterations,
            window_steps=window,
            serve={
                **self.metrics.event_fields(),
                **self.slo.event_fields(),
                **(
                    self._cost.event_fields()
                    if self._cost is not None
                    else {}
                ),
                **(
                    self._memory.serve_event_fields()
                    if self._memory is not None
                    else {}
                ),
            },
            # the serve record's mem/* ledger is THIS engine's (quantized
            # weights + KV pool), not the train facade's — record_step
            # falls back to the pipeline's observatory only when None
            memory=self._memory,
        )

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def summary(self) -> Dict[str, Any]:
        m = self.metrics
        m.refresh_percentiles()
        return {
            "iterations": self._iterations,
            "requests": m.requests.value,
            "completed": m.completed.value,
            "tokens_out": m.tokens_out.value,
            "prefills": m.prefills.value,
            "decode_steps": m.decode_steps.value,
            "kv_blocks_used": self.allocator.used_blocks,
            "kv_block_occupancy": self.allocator.occupancy,
            "quant": dict(self.quant_stats),
            # per-layer dequant-error attribution (ISSUE 12): which module
            # bounds int8 quality, and by how much
            "quant_errors_by_group": {
                g: dict(e) for g, e in self.quant_errors_by_group.items()
            },
            "quant_err_layer": self.quant_err_layer,
            "quant_err_max": self.quant_err_max,
            "kv_cache_bytes": self.cache.nbytes,
            **m.latency_percentiles(),
            "goodput_s": {
                "queue": m.queue_s.value,
                "prefill": m.prefill_s.value,
                "decode": m.decode_s.value,
            },
            # SLO observatory (ISSUE 16): {"active": False} until an
            # SLO-tagged request arrives, else per-class attainment,
            # goodput-under-SLO, and queue-ETA forecasts
            "slo": self.slo.summary(),
            # roofline observatory (ISSUE 18): {"active": False} without
            # ServeConfig.cost_cards, else per-program cost cards, the
            # decode roofline (attainable vs achieved TPOT, bound class),
            # MFU / HBM-bandwidth utilization, and the verify-over-decode
            # intensity uplift
            "cost": (
                self._cost.summary()
                if self._cost is not None
                else {"active": False}
            ),
            # HBM capacity observatory (ISSUE 19): {"active": False}
            # without a MemoryConfig, else the subsystem ledger, the
            # serve OOM pre-flight verdict, per-program memory cards,
            # and the KV headroom forecast
            "memory": (
                self._memory.summary()
                if self._memory is not None
                else {"active": False}
            ),
        }

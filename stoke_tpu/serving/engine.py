"""Continuous-batching serving engine: prefill/decode split over paged KV.

ISSUE 9 pillar 3 and the piece that wires the other three together.  Two
compiled programs, deliberately split (the Gemma-on-TPU comparison's
serving shape, arXiv:2605.25645):

- **prefill** — one request at a time, prompt padded up to the
  ``prefill_pad_multiple`` bucket (each bucket is ONE compiled program, so
  program count is bounded), causal attention through the configured
  kernel (dense or the Pallas flash kernel), every prompt K/V written into
  the request's blocks, and the first generated token sampled from the
  last prompt position — the TTFT point.
- **decode** — ALL ``max_seqs`` slots every step, single fresh token per
  slot, cache-read attention over gathered blocks
  (``ops.flash_attention.paged_decode_attention``).  Inactive slots run
  against the scratch block and their outputs are discarded, so the
  program shape never changes and XLA compiles it exactly once.

Both programs register with the PR-6 compile-cache program ledger when a
``CompileConfig`` is attached (``compile_cache.executable`` — warm starts
load from the persistent XLA cache and book reclaimed seconds), dispatch
through plain ``jax.jit`` (page buffers donated off-CPU, so cache updates
are in-place in HBM), and read weights through the ISSUE 9 quantized
store (``serving/quant.py``; dequant fused matmul-side by XLA).

Sampling is greedy argmax — deterministic by design: the
continuous-batching acceptance (staggered admission produces token
streams identical to sequential generation) is only testable under a
deterministic sampler, and the decode program's fixed batch shape makes
per-slot results independent of co-batched requests.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from stoke_tpu.configs import ServeConfig
from stoke_tpu.models.bert import BERT_SIZES
from stoke_tpu.models.gpt import GPT
from stoke_tpu.serving.kv_cache import (
    BlockAllocator,
    PagedAttentionHook,
    PagedKVCache,
)
from stoke_tpu.serving.quant import (
    compression_stats,
    dequantize_params,
    quantize_params,
)
from stoke_tpu.serving.scheduler import Request, Scheduler
from stoke_tpu.serving.telemetry import ServeMetrics
from stoke_tpu.telemetry.registry import MetricsRegistry
from stoke_tpu.telemetry.tracing import (
    trace_add,
    trace_point,
    trace_span,
    tracing_active,
)

_KV_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


class ServingEngine:
    """Continuous-batching inference engine over one GPT model.

    Built by :meth:`stoke_tpu.facade.Stoke.serve` (which supplies the
    trained params, telemetry pipeline, and compile cache) or standalone
    in tests/scripts.

    Args:
        model: a :class:`~stoke_tpu.models.gpt.GPT` module (dense FFN,
            ``chunked_head=False``).
        params: the model's ``params`` pytree (NOT the variables dict).
        cfg: :class:`~stoke_tpu.configs.ServeConfig`.
        registry: metrics registry for the ``serve/*`` instruments
            (defaults to ``telemetry.registry`` or a private one).
        telemetry: optional :class:`~stoke_tpu.telemetry.Telemetry` —
            when enabled, serve records land in its JSONL/Prometheus
            sinks with the ``serve/*`` field block.
        compile_cache: optional PR-6 :class:`~stoke_tpu.compile_cache
            .CompileCache` — prefill/decode programs register with its
            HLO-keyed ledger for warm starts.
        kv_sharding: optional sharding for the page pool (mesh-placed
            serving; default = wherever ``jnp.zeros`` lands).
    """

    def __init__(
        self,
        model: GPT,
        params: Any,
        cfg: ServeConfig,
        *,
        registry: Optional[MetricsRegistry] = None,
        telemetry=None,
        compile_cache=None,
        kv_sharding=None,
    ):
        if not isinstance(model, GPT):
            raise TypeError(
                f"ServingEngine serves GPT models; got {type(model).__name__} "
                f"(the paged-cache decode forward lives in models/gpt.py)"
            )
        if model.chunked_head:
            raise ValueError(
                "ServingEngine needs logits from the forward; construct the "
                "serving GPT with chunked_head=False (params are identical)"
            )
        if model.moe_num_experts > 0:
            raise NotImplementedError(
                "ServingEngine supports dense-FFN GPT only (no MoE)"
            )
        if cfg.max_seq_len > model.max_len:
            raise ValueError(
                f"ServeConfig.max_seq_len={cfg.max_seq_len} exceeds the "
                f"model's max_len={model.max_len}"
            )
        if _round_up(cfg.max_seq_len, cfg.prefill_pad_multiple) > model.max_len:
            raise ValueError(
                f"prefill padding bucket round_up(max_seq_len="
                f"{cfg.max_seq_len}, {cfg.prefill_pad_multiple}) exceeds the "
                f"model's max_len={model.max_len} — a full-length prompt "
                f"would pad past the position table; shrink max_seq_len or "
                f"prefill_pad_multiple"
            )
        self.model = model
        self.cfg = cfg
        self._telemetry = telemetry
        self._compile_cache = compile_cache
        self.metrics = ServeMetrics(
            registry
            if registry is not None
            else (
                telemetry.registry
                if telemetry is not None
                else MetricsRegistry()
            )
        )

        size = BERT_SIZES[model.size_name]
        self._heads = size.heads
        self._head_dim = size.hidden // size.heads

        # --- weight store (pillar 4): quantize once at load time ---
        self.qparams = quantize_params(
            params,
            cfg.quant,
            chunk_elems=cfg.quant_chunk_elems,
            stochastic=cfg.quant_stochastic,
            min_size=cfg.quant_min_size,
        )
        self.quant_stats = compression_stats(params, self.qparams)
        self.metrics.quant_compression.set(self.quant_stats["compression"])
        # per-layer dequant-error attribution (ISSUE 12): computed ONCE at
        # quantize time — which module int8 hurt most bounds the serving
        # quality story, so it rides the registry (numerics/* gauges), the
        # engine surface (bench --serve quant_err columns), and
        # stats()["quant_errors"]
        self.quant_errors: Dict[str, Dict[str, float]] = {}
        self.quant_errors_by_group: Dict[str, Dict[str, float]] = {}
        self.quant_err_layer: Optional[str] = None
        self.quant_err_max: Optional[float] = None
        if cfg.quant == "int8":
            from stoke_tpu.serving.quant import quantization_error
            from stoke_tpu.telemetry.numerics import (
                leaf_path_names,
                max_quant_error,
                module_groups,
                quant_error_by_group,
            )

            self.quant_errors = quantization_error(params, self.qparams)
            self.quant_errors_by_group = quant_error_by_group(
                self.quant_errors,
                module_groups(params),
                leaf_path_names(params),
            )
            self.quant_err_layer, self.quant_err_max = max_quant_error(
                self.quant_errors_by_group
            )
            # gauge publication respects the ISSUE 12 default-OFF
            # contract: on a SHARED telemetry pipeline the numerics/*
            # series exist only when a NumericsConfig attached a monitor
            # (Stoke.serve() installs the table on it, which publishes);
            # a standalone engine's own registry publishes directly
            if telemetry is None:
                reg = self.metrics.registry
                for group, err in self.quant_errors_by_group.items():
                    reg.gauge(f"numerics/{group}/quant_err_rel_rms").set(
                        err["rel_rms"]
                    )

        # --- paged KV pool (pillar 1) ---
        max_blocks_per_seq = -(-cfg.max_seq_len // cfg.kv_block_size)
        num_blocks = (
            cfg.kv_blocks
            if cfg.kv_blocks is not None
            else cfg.max_seqs * max_blocks_per_seq + 1  # +1 scratch
        )
        self.cache = PagedKVCache(
            size.num_layers,
            num_blocks,
            cfg.kv_block_size,
            self._heads,
            self._head_dim,
            dtype=_KV_DTYPES[cfg.kv_dtype],
            sharding=kv_sharding,
        )
        self.allocator = BlockAllocator(num_blocks, cfg.kv_block_size)

        # --- continuous-batching scheduler (pillar 2) ---
        self.scheduler = Scheduler(
            cfg.max_seqs,
            self.allocator,
            max_blocks_per_seq,
            max_seq_len=cfg.max_seq_len,
            default_max_new_tokens=cfg.max_new_tokens,
            eos_id=cfg.eos_id,
            pad_multiple=cfg.prefill_pad_multiple,
        )

        # --- compiled programs (pillar 3) ---
        # donation keeps the page pool in-place in HBM; the CPU backend
        # has no donation (jax warns and copies), so only donate off-CPU
        donate = (1, 2) if jax.default_backend() != "cpu" else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=donate)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=donate)

        self._iterations = 0
        self._last_emit_iter = 0
        self._t_start = time.perf_counter()

    # ------------------------------------------------------------------ #
    # compiled program bodies
    # ------------------------------------------------------------------ #

    def _apply(self, params, tokens, positions, hook, decode: bool):
        return self.model.apply(
            {"params": params},
            tokens,
            train=False,
            positions=positions,
            decode=decode,
            kv_cache=hook,
        )

    def _prefill_fn(self, qparams, k_pages, v_pages, tokens, block_row,
                    prompt_len):
        """tokens [1, P] padded prompt; block_row [1, MB]; prompt_len [1].
        Returns (first generated token [1], updated pages)."""
        params = dequantize_params(qparams)
        P = tokens.shape[1]
        positions = jnp.arange(P, dtype=jnp.int32)[None, :]
        hook = PagedAttentionHook(
            k_pages, v_pages, block_row, positions,
            mode="prefill", lengths=prompt_len,
            attention_impl=self.cfg.attention,
        )
        logits = self._apply(params, tokens, positions, hook, decode=False)
        last = logits[0, prompt_len[0] - 1]
        return (
            jnp.argmax(last, axis=-1).astype(jnp.int32)[None],
            hook.k_pages,
            hook.v_pages,
        )

    def _decode_fn(self, qparams, k_pages, v_pages, tokens, positions,
                   block_tables, context_lens):
        """tokens/positions [B]; block_tables [B, MB]; context_lens [B].
        Returns (next tokens [B], updated pages)."""
        params = dequantize_params(qparams)
        hook = PagedAttentionHook(
            k_pages, v_pages, block_tables, positions[:, None],
            mode="decode", lengths=context_lens,
            attention_impl=self.cfg.attention,
        )
        logits = self._apply(
            params, tokens[:, None], positions[:, None], hook, decode=True
        )
        return (
            jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32),
            hook.k_pages,
            hook.v_pages,
        )

    # ------------------------------------------------------------------ #
    # program-signature dispatch (PR-6 AOT ledger registration)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _sig(args) -> tuple:
        return tuple(
            (tuple(l.shape), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(args)
            if hasattr(l, "shape")
        )

    def _dispatch(self, program: str, fn, args: tuple):
        """Route one dispatch through the compile cache's program ledger
        (same contract as ``StepEngine._aot_call``): first dispatch per
        (program, shape signature) checks the HLO-keyed ledger — warm
        starts resolve to an already-built fn and book reclaimed compile
        seconds — and every dispatch runs plain ``jax.jit`` semantics."""
        cc = self._compile_cache
        if cc is not None:
            fn = cc.executable(program, (program, self._sig(args)), fn, args)
        return fn(*args)

    # ------------------------------------------------------------------ #
    # request intake
    # ------------------------------------------------------------------ #

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
    ) -> int:
        """Enqueue one request (mid-flight is the point); returns its id."""
        rid = self.scheduler.submit(prompt, max_new_tokens, eos_id)
        self.metrics.requests.inc()
        return rid

    def result(self, rid: int) -> Optional[Request]:
        return self.scheduler.finished.get(rid)

    # ------------------------------------------------------------------ #
    # the engine loop
    # ------------------------------------------------------------------ #

    def step(self) -> bool:
        """One engine iteration: admit + prefill arrivals, then one decode
        step over the full slot batch.  Returns True while work remains."""
        sched = self.scheduler
        m = self.metrics

        for slot, req, padded, plen in sched.admit():
            if tracing_active():
                # the request timeline's first span: arrival → admission
                # (the queue wait) on the request's own track row
                # count_self=False: the queue wait overlaps other
                # requests' prefill/decode spans, which own that wall
                trace_add(
                    "serve/admission", req.arrival_ts, req.admit_ts,
                    track="serve", request_id=req.rid,
                    attrs={"prompt_len": plen}, count_self=False,
                )
            t0 = time.perf_counter()
            with trace_span("serve/prefill", track="serve",
                            request_id=req.rid,
                            attrs={"padded_len": int(padded.shape[1])}):
                tok, k_pages, v_pages = self._dispatch(
                    "serve_prefill",
                    self._prefill_jit,
                    (
                        self.qparams,
                        self.cache.k_pages,
                        self.cache.v_pages,
                        jnp.asarray(padded),
                        jnp.asarray(sched.block_tables[slot : slot + 1]),
                        jnp.array([plen], jnp.int32),
                    ),
                )
                self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
                tok_host = int(np.asarray(tok)[0])  # sync: the TTFT point
            now = time.perf_counter()
            m.prefills.inc()
            m.prefill_s.inc(now - t0)
            sched.note_prefill_token(slot, tok_host, now)
            m.tokens_out.inc()
            m.observe_ttft(req.ttft_s)
            if req.finished:
                self._finish(req)

        if sched.active > 0:
            # the live slots' request ids BEFORE the commit evicts any —
            # each gets a per-request decode-slice span below
            live_rids = (
                [
                    s.request.rid
                    for s in sched.slots
                    if s.request is not None
                ]
                if tracing_active()
                else None
            )
            t0 = time.perf_counter()
            with trace_span("serve/decode_step", track="serve",
                            attrs={"active": sched.active}):
                tokens, positions, tables, context = sched.decode_batch()
                next_tok, k_pages, v_pages = self._dispatch(
                    "serve_decode",
                    self._decode_jit,
                    (
                        self.qparams,
                        self.cache.k_pages,
                        self.cache.v_pages,
                        jnp.asarray(tokens),
                        jnp.asarray(positions),
                        jnp.asarray(tables),
                        jnp.asarray(context),
                    ),
                )
                self.cache.k_pages, self.cache.v_pages = k_pages, v_pages
                next_host = np.asarray(next_tok)  # sync: tokens stream out
            now = time.perf_counter()
            if live_rids:
                # per-request decode slices: every live request's timeline
                # row shows the batch decode interval it rode (the TPOT
                # structure the histograms only summarize).
                # count_self=False: all slices share ONE interval the
                # serve/decode_step span above already owns — charging
                # each would multiply-count the window by batch depth
                for rid in live_rids:
                    trace_add("serve/decode", t0, now, track="serve",
                              request_id=rid, count_self=False)
            m.decode_steps.inc()
            m.decode_s.inc(now - t0)
            was_finished = set(sched.finished)
            live = sched.commit_decode(next_host, now)
            m.tokens_out.inc(live)
            for rid in set(sched.finished) - was_finished:
                self._finish(sched.finished[rid])

        self._iterations += 1
        self._refresh_gauges()
        if (
            self._iterations - self._last_emit_iter
            >= self.cfg.log_every_n_steps
        ):
            self.emit_record()
        return sched.has_work

    def run(self, max_steps: Optional[int] = None) -> int:
        """Drive :meth:`step` until drained (or ``max_steps``); emits a
        final telemetry record.  Returns iterations run."""
        n = 0
        while self.scheduler.has_work:
            self.step()
            n += 1
            if max_steps is not None and n >= max_steps:
                break
        if self._iterations != self._last_emit_iter:
            # final record on drain — unless the last step() just emitted
            # at the cadence (a duplicate step key would confuse readers)
            self.emit_record()
        return n

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> List[List[int]]:
        """Convenience batch API: submit all, drain, return token lists in
        prompt order (the continuous batcher still interleaves them)."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run()
        return [list(self.scheduler.finished[r].tokens) for r in rids]

    # ------------------------------------------------------------------ #
    # accounting
    # ------------------------------------------------------------------ #

    def _finish(self, req: Request) -> None:
        # eviction marker closes the request's trace timeline (its blocks
        # are already back in the pool — scheduler._finish freed them)
        trace_point(
            "serve/evict", track="serve", request_id=req.rid,
            attrs={"tokens": len(req.tokens)},
        )
        m = self.metrics
        m.completed.inc()
        tpot = req.tpot_s
        if tpot is not None:
            m.observe_tpot(tpot)
        if self._telemetry is not None:
            self._telemetry.add_tokens(len(req.tokens))

    def _refresh_gauges(self) -> None:
        m, sched = self.metrics, self.scheduler
        m.queue_depth.set(sched.queued)
        m.active_seqs.set(sched.active)
        m.batch_fill.set(sched.batch_fill)
        m.kv_blocks_used.set(self.allocator.used_blocks)
        m.kv_occupancy.set(self.allocator.occupancy)
        # sums-to-wall: queue/idle is the wall clock neither program used
        wall = time.perf_counter() - self._t_start
        target = max(
            0.0, wall - m.prefill_s.value - m.decode_s.value
        )
        if target > m.queue_s.value:
            m.queue_s.inc(target - m.queue_s.value)

    def emit_record(self) -> Optional[dict]:
        """Write one JSONL serve record through the telemetry pipeline
        (None when no enabled Telemetry is attached; the registry gauges
        update regardless)."""
        self._refresh_gauges()
        window = max(1, self._iterations - self._last_emit_iter)
        self._last_emit_iter = self._iterations
        if self._telemetry is None or not self._telemetry.enabled:
            return None
        return self._telemetry.record_step(
            step=self._iterations,
            window_steps=window,
            serve=self.metrics.event_fields(),
        )

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def summary(self) -> Dict[str, Any]:
        m = self.metrics
        m.refresh_percentiles()
        return {
            "iterations": self._iterations,
            "requests": m.requests.value,
            "completed": m.completed.value,
            "tokens_out": m.tokens_out.value,
            "prefills": m.prefills.value,
            "decode_steps": m.decode_steps.value,
            "kv_blocks_used": self.allocator.used_blocks,
            "kv_block_occupancy": self.allocator.occupancy,
            "quant": dict(self.quant_stats),
            # per-layer dequant-error attribution (ISSUE 12): which module
            # bounds int8 quality, and by how much
            "quant_errors_by_group": {
                g: dict(e) for g, e in self.quant_errors_by_group.items()
            },
            "quant_err_layer": self.quant_err_layer,
            "quant_err_max": self.quant_err_max,
            "kv_cache_bytes": self.cache.nbytes,
            **m.latency_percentiles(),
            "goodput_s": {
                "queue": m.queue_s.value,
                "prefill": m.prefill_s.value,
                "decode": m.decode_s.value,
            },
        }

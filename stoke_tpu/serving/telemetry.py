"""Per-request serving telemetry riding the PR-1 metrics registry.

TTFT (time-to-first-token: arrival -> first generated token, queue time
included) and TPOT (time-per-output-token over the decode tokens) are THE
serving SLOs; alongside them ride the capacity gauges (queue depth,
KV-block occupancy, batch fill) and a goodput split of the serve
wall-clock into queue/idle vs prefill vs decode — same sums-to-wall
contract as the PR-4 training goodput ledger.

Everything lands in the shared :class:`~stoke_tpu.telemetry.registry
.MetricsRegistry` (so the Prometheus exposition and flight-recorder
snapshots pick it up for free) under ``serve/*`` names; the JSONL step
events gain the nullable ``serve/*`` field block (events.py), populated
only when a serving engine emits — training records never carry them.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional

from stoke_tpu.telemetry.registry import MetricsRegistry

#: speculative-decoding JSONL fields (ISSUE 17) — emitted only by engines
#: with ``speculative_k`` set (the default-OFF contract: non-speculative
#: records carry zero new fields).  Pinned append-only by the
#: ``analysis/manifests/wire_formats.json`` manifest.
SPEC_FIELDS = (
    "serve/spec_draft_tokens",
    "serve/spec_accepted_tokens",
)

#: sample cap for the exact-percentile reservoirs (beyond it the oldest
#: samples age out; p50/p99 then describe the trailing window)
_MAX_SAMPLES = 8192

#: sub-second latency buckets for the TTFT/TPOT histograms (the default
#: registry ladder starts at 1ms and tops out at 60s — fine here too, but
#: serving wants finer sub-100ms resolution)
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class _Reservoir:
    """Sorted trailing-window sample store for exact percentiles (the
    registry Histogram keeps cumulative buckets for Prometheus; the p50/p99
    gauges want exact order statistics)."""

    def __init__(self, cap: int = _MAX_SAMPLES):
        self._sorted: List[float] = []
        self._fifo: List[float] = []
        self._cap = cap

    def add(self, v: float) -> None:
        v = float(v)
        if len(self._fifo) >= self._cap:
            old = self._fifo.pop(0)
            idx = bisect.bisect_left(self._sorted, old)
            self._sorted.pop(idx)
        self._fifo.append(v)
        bisect.insort(self._sorted, v)

    def percentile(self, p: float) -> Optional[float]:
        if not self._sorted:
            return None
        idx = min(
            len(self._sorted) - 1, int(round(p * (len(self._sorted) - 1)))
        )
        return self._sorted[idx]


class ServeMetrics:
    """Serving-side instrument bundle over one registry."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.ttft = registry.histogram(
            "serve/ttft_s",
            help="time to first token (arrival -> prefill token)",
            buckets=LATENCY_BUCKETS,
        )
        self.tpot = registry.histogram(
            "serve/tpot_s",
            help="time per output token (decode tokens)",
            buckets=LATENCY_BUCKETS,
        )
        self._ttft_samples = _Reservoir()
        self._tpot_samples = _Reservoir()
        self.requests = registry.counter(
            "serve/requests_total", help="requests submitted"
        )
        self.completed = registry.counter(
            "serve/completed_total", help="requests completed"
        )
        self.tokens_out = registry.counter(
            "serve/tokens_out_total", help="generated tokens"
        )
        self.prefills = registry.counter(
            "serve/prefills_total", help="prefill program dispatches"
        )
        self.prefill_chunks = registry.counter(
            "serve/prefill_chunks_total",
            help="chunked-prefill program dispatches (ISSUE 13)",
        )
        self.decode_steps = registry.counter(
            "serve/decode_steps_total", help="decode program dispatches"
        )
        self.sampled_tokens = registry.counter(
            "serve/sampled_tokens_total",
            help="tokens drawn through the sampling path "
            "(temperature > 0; greedy tokens excluded)",
        )
        # goodput buckets (sums-to-wall: queue = wall - prefill - decode)
        self.prefill_s = registry.counter(
            "serve/goodput_prefill_s_total",
            help="serve wall seconds spent in prefill dispatch",
        )
        self.decode_s = registry.counter(
            "serve/goodput_decode_s_total",
            help="serve wall seconds spent in decode dispatch",
        )
        self.queue_s = registry.counter(
            "serve/goodput_queue_s_total",
            help="serve wall seconds spent queued/idle (wall - prefill - decode)",
        )
        self.queue_depth = registry.gauge(
            "serve/queue_depth", help="requests waiting for a slot"
        )
        self.active_seqs = registry.gauge(
            "serve/active_seqs", help="occupied decode slots"
        )
        self.batch_fill = registry.gauge(
            "serve/batch_fill", help="active_seqs / max_seqs"
        )
        self.kv_blocks_used = registry.gauge(
            "serve/kv_blocks_used", help="KV blocks owned by live requests"
        )
        self.kv_occupancy = registry.gauge(
            "serve/kv_block_occupancy",
            help="owned / allocatable KV blocks",
        )
        self.quant_compression = registry.gauge(
            "serve/quant_compression",
            help="param bytes fp / param bytes as-served",
        )
        self._p = {
            "ttft_p50": registry.gauge("serve/ttft_p50_s"),
            "ttft_p99": registry.gauge("serve/ttft_p99_s"),
            "tpot_p50": registry.gauge("serve/tpot_p50_s"),
            "tpot_p99": registry.gauge("serve/tpot_p99_s"),
        }
        # speculative counters (ISSUE 17): created by enable_speculative()
        # so a non-speculative engine's registry (and JSONL records) carry
        # zero speculative series
        self.spec_active = False
        self.spec_draft_tokens = None
        self.spec_accepted_tokens = None
        # cost-card counters (ISSUE 18): created by enable_cost() so an
        # engine without ServeConfig.cost_cards registers zero serve/cost
        # series (same default-OFF contract as the speculative block)
        self.cost_active = False
        self.cost_flops = None
        self.cost_bytes = None

    def enable_speculative(self) -> None:
        """Arm the speculative-decoding instruments (ISSUE 17) — called at
        engine construction when ``ServeConfig.speculative_k`` is set.
        ``accepted / drafted`` is the acceptance rate;
        ``tokens_out / decode_steps`` the accepted-tokens-per-dispatch
        the bench arm reports."""
        if self.spec_active:
            return
        self.spec_active = True
        self.spec_draft_tokens = self.registry.counter(
            "serve/spec_draft_tokens_total",
            help="draft tokens scored by verify dispatches (ISSUE 17)",
        )
        self.spec_accepted_tokens = self.registry.counter(
            "serve/spec_accepted_tokens_total",
            help="draft tokens accepted into the output stream (ISSUE 17)",
        )

    def enable_cost(self) -> None:
        """Arm the per-dispatch cost counters (ISSUE 18) — called by the
        :class:`~stoke_tpu.serving.roofline.ServeCostObservatory` an
        engine with ``ServeConfig.cost_cards`` constructs.  The counters
        are the SAME registry series the observatory's ``CostCardCache``
        (``counter_prefix="serve/cost"``) accumulates into — registry
        instruments are cached by name — so ``cost_flops.value`` is the
        analytic-FLOPs-dispatched total the recombination tests pin."""
        if self.cost_active:
            return
        self.cost_active = True
        self.cost_flops = self.registry.counter(
            "serve/cost/flops_total",
            help="analytic FLOPs dispatched",
        )
        self.cost_bytes = self.registry.counter(
            "serve/cost/bytes_total",
            help="analytic bytes accessed by dispatches",
        )

    # ------------------------------ feeds ------------------------------ #

    def reset_latency_reservoirs(self) -> None:
        """Drop the exact-percentile sample windows (the cumulative
        registry histograms are untouched).  For benches that warm the
        compiled programs first: p50/p99 should describe steady-state
        latency, not the warm pass's compile-dominated first requests."""
        self._ttft_samples = _Reservoir()
        self._tpot_samples = _Reservoir()

    def observe_ttft(self, seconds: float) -> None:
        self.ttft.observe(seconds)
        self._ttft_samples.add(seconds)

    def observe_tpot(self, seconds: float) -> None:
        self.tpot.observe(seconds)
        self._tpot_samples.add(seconds)

    def latency_percentiles(self) -> Dict[str, Optional[float]]:
        """Exact order statistics of the trailing reservoirs — the public
        accessor the engine summary and the bench arm read (the
        reservoirs themselves are an implementation detail)."""
        return {
            "ttft_p50_s": self._ttft_samples.percentile(0.50),
            "ttft_p99_s": self._ttft_samples.percentile(0.99),
            "tpot_p50_s": self._tpot_samples.percentile(0.50),
            "tpot_p99_s": self._tpot_samples.percentile(0.99),
        }

    def refresh_percentiles(self) -> None:
        for name, v in self.latency_percentiles().items():
            if v is not None:
                self._p[name[: -len("_s")]].set(v)

    # --------------------------- JSONL fields --------------------------- #

    def event_fields(self) -> Dict[str, object]:
        """The ``serve/*`` block of one JSONL step event.  The goodput
        counters already sum to the serve wall clock — the engine derives
        the queue bucket as ``wall - prefill - decode`` when it refreshes
        gauges (``ServingEngine._refresh_gauges``), so this is a pure
        registry read."""
        self.refresh_percentiles()
        pct = self.latency_percentiles()
        out = {
            "serve/requests": self.requests.value,
            "serve/completed": self.completed.value,
            "serve/tokens_out": self.tokens_out.value,
            "serve/queue_depth": self.queue_depth.value,
            "serve/active_seqs": self.active_seqs.value,
            "serve/batch_fill": self.batch_fill.value,
            "serve/kv_blocks_used": self.kv_blocks_used.value,
            "serve/kv_block_occupancy": self.kv_occupancy.value,
            "serve/ttft_p50_s": pct["ttft_p50_s"],
            "serve/ttft_p99_s": pct["ttft_p99_s"],
            "serve/tpot_p50_s": pct["tpot_p50_s"],
            "serve/tpot_p99_s": pct["tpot_p99_s"],
            "serve/goodput_queue_s": self.queue_s.value,
            "serve/goodput_prefill_s": self.prefill_s.value,
            "serve/goodput_decode_s": self.decode_s.value,
            "serve/prefill_chunks": self.prefill_chunks.value,
            "serve/sampled_tokens": self.sampled_tokens.value,
            "serve/quant_compression": (
                self.quant_compression.value
                if self.quant_compression.has_value
                else None
            ),
        }
        if self.spec_active:
            # speculative block (ISSUE 17): absent — not null — without a
            # speculative config, like the serve/slo_* block
            out["serve/spec_draft_tokens"] = self.spec_draft_tokens.value
            out["serve/spec_accepted_tokens"] = (
                self.spec_accepted_tokens.value
            )
        return out

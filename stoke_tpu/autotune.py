"""Telemetry-driven autotuner (ISSUE 6 tentpole, search half).

PRs 1/3/4/5 built the observability to *explain* slowness (CostCards, MFU
gauges, roofline bound classification, the goodput ledger); this module
*acts* on it: a trial-driver search loop over the knobs the framework
already exposes —

- ``xla_flags``: extra ``XLA_FLAGS`` for the measurement (compute-side
  compiler knobs; ``bench.py --xla-flags`` pass-through),
- ``batch`` / ``steps_per_dispatch``: the throughput levers
  ``scripts/profile_capture.py``'s sweeps measure one at a time,
- ``flash_block_q`` / ``flash_block_k``: the Pallas flash-attention
  blocking (``ops/flash_attention.py``),
- ``comm_dtype``: the gradient-transport wire format (ISSUE 2),
- ``decode_pages_per_block`` / ``decode_block_h``: the Pallas
  paged-decode kernel's blocking (ISSUE 13 serve fast path;
  ``--workload serve_decode``),

— scoring each trial on the attribution vertical's own metrics (per-window
MFU x goodput fraction, throughput as the fallback) and **pruning the
search with the bound classification**: a memory-bound baseline does not
sweep compute flags, a host-bound one sweeps dispatch amortization first.

This module is deliberately **jax-free**: the search loop, knob catalog,
pruning, scoring, and ledger persistence are pure host-side logic, so the
``scripts/autotune.py`` driver can orchestrate subprocess trials without
ever importing jax in the parent (the XLA_FLAGS-before-import discipline
``scripts/profile_capture.py`` established — flags are fixed at backend
init, so every trial must be its own process).

Winners persist in the BENCH ledger (``BENCH_RESULTS.json``) under
``autotune/<metric>`` with full provenance (config key, flags, measured
MFU/goodput, trial count) so ``bench.py --tuned`` can replay them.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: knob name -> which resource it primarily moves.  The pruning logic keys
#: on this: a bound classification names the scarce resource, and knobs
#: that cannot relieve it are not worth trial budget.
KNOB_KIND: Dict[str, str] = {
    "xla_flags": "compute",
    "batch": "memory",
    "steps_per_dispatch": "host",
    "flash_block_q": "memory",
    "flash_block_k": "memory",
    "comm_dtype": "comm",
    # ISSUE 13 serve fast path: the Pallas paged-decode kernel's block
    # knobs (KV pages streamed HBM→VMEM per kernel step / heads per grid
    # cell) — decode attention is HBM-bandwidth-bound, so both are
    # memory-kind; swept by `scripts/autotune.py --workload serve_decode`
    "decode_pages_per_block": "memory",
    "decode_block_h": "memory",
    # ISSUE 17 speculative decode: the Pallas k-token verify kernel's
    # block knobs (same HBM→VMEM streaming loop as the decode kernel,
    # S=k+1 query rows per sequence) — memory-kind for the same reason;
    # swept by `scripts/autotune.py --workload serve_decode` when the
    # sweep runs its speculative variant
    "verify_pages_per_block": "memory",
    "verify_block_h": "memory",
}

#: bound classification -> knob kinds worth sweeping, in priority order.
#: Derived from the roofline semantics of stoke_tpu.telemetry.attribution:
#: - memory-bound: compiler compute flags cannot help (ISSUE 6: "memory-
#:   bound => don't sweep compute flags"); blocking/batch shape the HBM
#:   traffic, and dispatch amortization is cheap to try.
#: - compute-bound: compiler flags and batch (MXU tiling) first.
#: - comm-bound: wire format first, then compute flags (overlap).
#: - host-bound: dispatch amortization dominates everything.
#: - None (no attribution data): sweep everything.
BOUND_KNOB_KINDS: Dict[Optional[str], Tuple[str, ...]] = {
    "memory": ("memory", "host"),
    "compute": ("compute", "memory", "host"),
    "comm": ("comm", "compute", "host"),
    "host": ("host", "compute", "memory", "comm"),
    None: ("compute", "memory", "host", "comm"),
}

#: TPU-side XLA flag candidates for the compute sweep (each a full
#: XLA_FLAGS fragment; "" = baseline).  Curated from the profile_capture
#: A/B arms BENCH_NOTES queued behind the round-4 evidence.
TPU_XLA_FLAG_CANDIDATES: Tuple[str, ...] = (
    "",
    "--xla_tpu_enable_experimental_fusion_cost_model=true",
    "--xla_tpu_scoped_vmem_limit_kib=16384",
    "--xla_enable_async_collective_permute=true",
)


@dataclass(frozen=True)
class TrialSpec:
    """One point in the knob space.  ``None`` means "leave the workload's
    default" — only non-default knobs enter the config key, so a spec's
    identity is exactly what it overrides."""

    xla_flags: str = ""
    batch: Optional[int] = None
    steps_per_dispatch: Optional[int] = None
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    comm_dtype: Optional[str] = None
    decode_pages_per_block: Optional[int] = None
    decode_block_h: Optional[int] = None
    verify_pages_per_block: Optional[int] = None
    verify_block_h: Optional[int] = None

    def config_key(self) -> str:
        """Canonical, process-stable identity of this configuration (the
        provenance key the ledger winner records)."""
        parts = []
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None or v == "":
                continue
            parts.append(f"{f.name}={v}")
        return "|".join(parts) or "baseline"

    def to_dict(self) -> Dict[str, Any]:
        return {
            f.name: getattr(self, f.name) for f in dataclasses.fields(self)
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialSpec":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (d or {}).items() if k in names})

    def with_knob(self, knob: str, value) -> "TrialSpec":
        return dataclasses.replace(self, **{knob: value})


@dataclass
class TrialResult:
    """One measured trial.  ``mfu``/``goodput_fraction``/``bound`` come
    from the attribution vertical (None when the trial ran without it);
    ``value`` is the workload throughput (imgs/sec, tokens/sec, ...)."""

    spec: TrialSpec
    value: float = 0.0
    unit: str = "imgs/sec/chip"
    mfu: Optional[float] = None
    goodput_fraction: Optional[float] = None
    bound: Optional[str] = None
    wall_s: Optional[float] = None
    ok: bool = True
    error: Optional[str] = None

    def score(self, basis: Optional[str] = None) -> float:
        """Trial ordering: under the ``"mfu"`` basis, MFU weighted by
        the goodput fraction (per-window MFU already folds in wasted
        wall clock, but a trial that spends its windows compiling or
        starving must not win on a lucky productive window); under
        ``"value"``, raw throughput.  ``basis=None`` uses the trial's
        own basis (MFU when measured).  Failed trials sort below
        everything.  The two bases are incomparable units (MFU in 0..1,
        throughput in thousands) — :func:`greedy_search` fixes ONE basis
        per sweep and passes it here, so a trial that cannot report the
        sweep's basis is disqualified (-inf) instead of silently
        competing in the wrong unit."""
        if not self.ok:
            return -math.inf
        b = basis or ("mfu" if self.mfu is not None else "value")
        if b == "mfu":
            if self.mfu is None:
                return -math.inf  # incomparable: history, never winner
            g = (
                self.goodput_fraction
                if self.goodput_fraction is not None
                else 1.0
            )
            return self.mfu * g
        return self.value

    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["spec"] = self.spec.to_dict()
        out["config_key"] = self.spec.config_key()
        out["score"] = None if not self.ok else self.score()
        return out


def knobs_for_bound(
    bound: Optional[str],
    space: Dict[str, Sequence[Any]],
) -> List[str]:
    """Prune + order the knob space by the baseline's bound
    classification (pure function — unit-tested on synthetic bounds).

    Returns the knob names worth sweeping, highest-leverage first: knobs
    whose kind is not in ``BOUND_KNOB_KINDS[bound]`` are dropped (e.g.
    compute flags under a memory bound), the rest sort by their kind's
    priority for that bound.  Unknown bounds degrade to the unpruned
    ordering — never silently to an empty sweep.
    """
    kinds = BOUND_KNOB_KINDS.get(bound, BOUND_KNOB_KINDS[None])
    rank = {k: i for i, k in enumerate(kinds)}
    out = [
        k for k in space
        if KNOB_KIND.get(k, "compute") in rank
    ]
    out.sort(key=lambda k: rank[KNOB_KIND.get(k, "compute")])
    return out


@dataclass
class SearchOutcome:
    best: TrialResult
    history: List[TrialResult] = field(default_factory=list)
    pruned_knobs: List[str] = field(default_factory=list)
    trials: int = 0


def greedy_search(
    measure: Callable[[TrialSpec], TrialResult],
    base: TrialSpec,
    space: Dict[str, Sequence[Any]],
    *,
    max_trials: int = 16,
    log: Optional[Callable[[str], None]] = None,
) -> SearchOutcome:
    """Bound-pruned greedy coordinate search.

    1. Measure the baseline; its ``bound`` classification prunes + orders
       the knob space (:func:`knobs_for_bound`).
    2. Sweep each surviving knob in priority order, one candidate value
       per trial, carrying the best spec found so far (coordinate
       ascent); duplicate configurations (by config key) are never
       re-measured.
    3. Stop at ``max_trials`` total measurements (baseline included).

    ``measure`` may return ``ok=False`` results (a failed trial is
    recorded in history but can never become the winner) — trial failure
    is data, not an exception.

    Scoring basis is fixed ONCE per sweep, by the first ok trial: MFU x
    goodput when it reported an MFU, raw throughput otherwise.  Under
    the MFU basis a trial whose attribution data went missing scores as
    disqualified rather than falling back to throughput — the two bases
    are incomparable units, and a lost-telemetry trial scoring thousands
    against honest 0..1 scores would always "win".
    """
    say = log or (lambda _msg: None)
    basis: Optional[str] = None

    def _note_basis(r: TrialResult) -> None:
        nonlocal basis
        if basis is None and r.ok:
            basis = "mfu" if r.mfu is not None else "value"

    def _score(r: TrialResult) -> float:
        return r.score(basis)

    best = measure(base)
    history = [best]
    seen = {base.config_key()}
    _note_basis(best)
    bound = best.bound
    knobs = knobs_for_bound(bound, space)
    pruned = [k for k in space if k not in knobs]
    say(
        f"baseline score={_score(best):.6g} basis={basis or 'n/a'} "
        f"bound={bound or 'n/a'} sweep={knobs} pruned={pruned}"
    )
    for knob in knobs:
        for value in space[knob]:
            if len(history) >= max_trials:
                say(f"trial budget exhausted ({max_trials})")
                return SearchOutcome(best, history, pruned, len(history))
            cand = (best.spec if best.ok else base).with_knob(knob, value)
            key = cand.config_key()
            if key in seen:
                continue
            seen.add(key)
            res = measure(cand)
            history.append(res)
            _note_basis(res)
            say(
                f"trial {len(history)}/{max_trials} {key!r}: "
                + (
                    f"score={_score(res):.6g}"
                    if res.ok
                    else f"FAILED ({res.error})"
                )
            )
            if _score(res) > _score(best):
                best = res
                say(f"  -> new best")
    return SearchOutcome(best, history, pruned, len(history))


# --------------------------------------------------------------------------- #
# BENCH ledger persistence (winners with provenance)
# --------------------------------------------------------------------------- #


def winner_metric(base_metric: str) -> str:
    """Ledger key the winner for ``base_metric`` persists under (distinct
    namespace: a tuned-search winner is provenance for replay, never a
    substitute for the exact-configuration headline record)."""
    return f"autotune/{base_metric}"


def load_ledger(path: str) -> Dict[str, Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def persist_winner(
    path: str,
    base_metric: str,
    outcome: SearchOutcome,
    *,
    backend: str = "unknown",
    source: str = "scripts/autotune.py",
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Record a search winner in the BENCH ledger with full provenance.

    The record carries everything ``bench.py --tuned`` needs to replay it
    (the spec and its config key) and everything a reviewer needs to
    trust it (measured value/MFU/goodput, trial count, pruned knobs,
    date, backend).  Atomic write (tmp + rename), merging with whatever
    else the ledger holds.
    """
    best = outcome.best
    record = {
        "value": round(float(best.value), 1),
        "unit": best.unit,
        "mfu": None if best.mfu is None else round(best.mfu, 6),
        "goodput_fraction": (
            None
            if best.goodput_fraction is None
            else round(best.goodput_fraction, 4)
        ),
        "bound": best.bound,
        "config_key": best.spec.config_key(),
        "spec": best.spec.to_dict(),
        "trials": outcome.trials,
        "pruned_knobs": list(outcome.pruned_knobs),
        "date": time.strftime("%Y-%m-%d"),
        "source": source,
        "backend": backend,
        **(extra or {}),
    }
    ledger = load_ledger(path)
    ledger[winner_metric(base_metric)] = record
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return record


def read_winner(path: str, base_metric: str) -> Optional[Dict[str, Any]]:
    """The persisted winner for ``base_metric`` (None when no search has
    run); the ``bench.py --tuned`` lookup."""
    return load_ledger(path).get(winner_metric(base_metric))

"""Data layer: device-placing DataLoader + BucketedDistributedSampler.

TPU-native re-design of the reference data side-car (stoke/data.py:24-516):

- :class:`StokeDataLoader` (reference data.py:24-108): wraps a host-side
  loader (torch's, when available — it is the best multi-worker host loader
  and carries zero CUDA dependency on CPU) and yields batches already placed
  in device HBM, *sharded over the mesh data axis*, with one-batch lookahead
  so the host→HBM transfer of batch N+1 overlaps the compute of batch N
  (SURVEY.md §3.3: host loader + double-buffered ``device_put`` replaces
  per-rank ``.cuda()`` pushes).

- :class:`BucketedDistributedSampler` (reference data.py:111-516): buckets a
  pre-sorted index list (e.g. by sequence length) so each batch draws
  similar-length samples, minimizing padding waste.  Re-implemented from the
  reference's documented semantics with the same invariants (per-epoch seeded
  in-bucket shuffle, stride-aligned padding of the short final slice,
  round-robin replica slicing, optional residual "overlap" batches, identical
  ``__len__``).  In this framework a "replica" is a *loading process* (host),
  not a device: one process feeds a contiguous slice of the logically-global
  batch to all its local devices.
"""

from __future__ import annotations

import itertools
import math
import warnings
from collections import deque
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

#: one-time flag for the threaded-fallback semantics warning (ADVICE r4)
_WARNED_THREADED = False


# --------------------------------------------------------------------------- #
# Array-backed dataset with native batching
# --------------------------------------------------------------------------- #


class ArrayDataset:
    """Dataset backed by whole numpy arrays (first axis = samples).

    When a ``StokeDataLoader`` receives one of these, it bypasses the
    per-sample ``__getitem__`` + collate path entirely: each batch is
    assembled by the native thread-pool (`stoke_tpu.native.NativeBatcher`)
    as one GIL-free row-gather per array — the input-pipeline hot path the
    reference delegates to torch's C++ DataLoader workers (SURVEY.md §2.6).

    Args:
        *arrays: equal-length numpy arrays (e.g. images, labels).
    """

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("ArrayDataset needs at least one array")
        self.arrays = tuple(np.ascontiguousarray(a) for a in arrays)
        n = len(self.arrays[0])
        if any(len(a) != n for a in self.arrays):
            raise ValueError("all arrays must share the sample axis length")

    def __len__(self):
        return len(self.arrays[0])

    def __getitem__(self, i):
        row = tuple(a[i] for a in self.arrays)
        return row if len(row) > 1 else row[0]


class _NativeLoaderBase:
    """Sampler/shuffle/drop_last machinery shared by the native fast-path
    loaders; subclasses implement ``_assemble(idx)``."""

    def __init__(self, dataset, batch_size: int, shuffle: bool = False,
                 sampler=None, drop_last: bool = False, seed: int = 0,
                 **_unused):
        from stoke_tpu.native import NativeBatcher

        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.sampler = sampler
        self.drop_last = drop_last
        self._epoch_seed = seed
        self._batcher = NativeBatcher()

    def __len__(self):
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def _assemble(self, idx: np.ndarray):
        raise NotImplementedError

    def __iter__(self):
        if self.sampler is not None:
            order = np.fromiter(iter(self.sampler), np.int64)
        else:
            order = np.arange(len(self.dataset), dtype=np.int64)
            if self.shuffle:
                rng = np.random.default_rng(self._epoch_seed)
                self._epoch_seed += 1
                rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            idx = order[start : start + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                break
            yield self._assemble(idx)


class _NativeArrayLoader(_NativeLoaderBase):
    """ArrayDataset fast path: one GIL-free row-gather per array."""

    def _assemble(self, idx):
        batch = tuple(
            self._batcher.gather_rows(a, idx) for a in self.dataset.arrays
        )
        return batch if len(batch) > 1 else batch[0]


class RaggedSequenceDataset:
    """Variable-length token sequences in one contiguous ragged buffer, with
    native batch assembly.

    The BERT/bucketed-sampler pipeline's hot path is "gather sampled
    sequences + pad to the batch max + build the attention mask"; with this
    dataset a ``StokeDataLoader`` does all three in one GIL-free native call
    (``NativeBatcher.gather_pad``).  Pairs naturally with
    ``BucketedDistributedSampler`` (use :meth:`sorted_idx`).

    Args:
        sequences: list of 1-D int token arrays.
        labels: optional per-sequence labels.
        pad_multiple: pad batch max-length up to a multiple (bounds XLA
            recompilation and satisfies flash/ring divisibility).
    """

    def __init__(self, sequences, labels=None, pad_multiple: int = 32):
        self.lengths = np.asarray([len(s) for s in sequences], np.int32)
        self.offsets = np.concatenate(
            [[0], np.cumsum(self.lengths[:-1], dtype=np.int64)]
        ).astype(np.int64)
        self.ragged = (
            np.concatenate([np.asarray(s, np.int32) for s in sequences])
            if len(sequences)
            else np.zeros((0,), np.int32)
        )
        self.labels = None if labels is None else np.asarray(labels)
        self.pad_multiple = int(pad_multiple)

    def __len__(self):
        return len(self.lengths)

    def __getitem__(self, i):
        s = self.ragged[self.offsets[i] : self.offsets[i] + self.lengths[i]]
        return (s, self.labels[i]) if self.labels is not None else s

    def sorted_idx(self):
        """Indices sorted by length — feed to BucketedDistributedSampler."""
        return list(np.argsort(self.lengths, kind="stable"))


class _NativeRaggedLoader(_NativeLoaderBase):
    """RaggedSequenceDataset fast path: native gather+pad+mask in one call,
    yielding ({input_ids, attention_mask}, labels?)."""

    def _assemble(self, idx):
        ds = self.dataset
        ids, mask = self._batcher.gather_pad(
            ds.ragged, ds.offsets, ds.lengths, idx,
            pad_multiple=ds.pad_multiple,
        )
        batch = {"input_ids": ids, "attention_mask": mask}
        if ds.labels is not None:
            return batch, ds.labels[idx]
        return batch


# --------------------------------------------------------------------------- #
# Skew-reactive input rebalancing (ISSUE 14 tentpole c)
# --------------------------------------------------------------------------- #
#
# The fleet monitor (PR 5) can NAME the host whose input pipeline drags the
# pod; this layer is what finally acts on it.  Each global batch ("slice",
# batch_size × num_replicas rows) has a canonical per-host split — host r
# feeds rows [r·B, (r+1)·B) of the canonical order to its devices.  The
# rebalancer moves the READ work instead: host r reads a contiguous
# ``shares[r]``-row range of the canonical slice (shares sum to the slice,
# equal shares ≡ today's behavior: every host reads exactly its own rows
# and no collective runs).  When shares are shifted, the surplus rows ride
# ONE host-side allgather back to their canonical host — so the global
# batch, the per-epoch sample set, and every host's device feed are
# unchanged by construction; only who pays the disk/decode cost moves.
#
# Fleet-wide agreement without extra collectives: share updates are
# computed on the IDENTICAL exchanged fleet matrix on every host (the
# monitor's actuation is deterministic), and take effect at a future fetch
# index no host can have reached yet (yields are lockstep across SPMD
# hosts; fetches lead yields by at most the prefetch depth, so
# ``yields + apply_slack`` with slack > prefetch is a safe apply point).


class InputRebalancer:
    """Per-host read-share state + the deterministic apply protocol.

    ``shares[r]`` is how many rows of each canonical slice host ``r``
    reads; all hosts hold identical copies and evolve them identically.
    ``propose_shift`` (called by the fleet monitor at straggler-streak
    boundaries) schedules a bounded share move that becomes effective at a
    fetch index strictly ahead of every host's loader; the loader calls
    ``shares_for_fetch`` once per batch fetch and ``note_yield`` once per
    delivered batch.
    """

    def __init__(
        self,
        n_hosts: int,
        rank: int,
        batch_size: int,
        max_frac: float = 0.25,
        apply_slack: int = 4,
    ):
        if not (0 <= rank < max(n_hosts, 1)):
            raise ValueError(
                f"Stoke -- rebalancer rank {rank} out of range for "
                f"{n_hosts} hosts"
            )
        self.n_hosts = max(int(n_hosts), 1)
        self.rank = int(rank)
        self.batch_size = int(batch_size)
        #: hard bound: no host's share may leave
        #: [batch - max_shift, batch + max_shift]
        self.max_shift = int(float(max_frac) * self.batch_size)
        if self.max_shift < 1:
            # a bound that truncated to zero is a permanently-dead
            # actuator — the silently-ignored-knob anti-pattern the status
            # rules exist to prevent; loud, never a silent no-op
            raise ValueError(
                f"Stoke -- rebalance_max_frac={max_frac} of per-host "
                f"batch {self.batch_size} rounds to a zero-row share "
                f"bound; the actuator could never move work. Raise "
                f"rebalance_max_frac or the per-host batch, or drop "
                f"rebalance"
            )
        self.apply_slack = max(int(apply_slack), 1)
        self.shares: List[int] = [self.batch_size] * self.n_hosts
        self._pending: List[Any] = []  # (effective_fetch, shares) FIFO
        self._fetches = 0
        self._yields = 0
        self.shifts = 0
        self.rows_moved = 0

    def share_of(self, host: int) -> int:
        """The latest scheduled share of ``host`` (pending updates
        included — the value gauges/JSONL report)."""
        target = self._pending[-1][1] if self._pending else self.shares
        return int(target[host])

    @property
    def shifted(self) -> bool:
        target = self._pending[-1][1] if self._pending else self.shares
        return len(set(target)) > 1

    def note_yield(self) -> None:
        """One batch delivered to the training loop (lockstep across
        hosts — the apply-point anchor)."""
        self._yields += 1

    def propose_shift(self, from_host: int, to_host: int, rows: int) -> int:
        """Schedule moving ``rows`` of read work ``from_host → to_host``,
        clamped to the per-host bound; returns the rows actually moved
        (0 when the bound already binds).  Deterministic given identical
        call sequences — the fleet-wide agreement contract."""
        if from_host == to_host or rows <= 0:
            return 0
        base = list(self._pending[-1][1]) if self._pending else list(
            self.shares
        )
        lo = self.batch_size - self.max_shift
        hi = self.batch_size + self.max_shift
        rows = int(min(rows, base[from_host] - lo, hi - base[to_host]))
        if rows <= 0:
            return 0
        base[from_host] -= rows
        base[to_host] += rows
        eff = self._yields + self.apply_slack
        if self._pending:
            eff = max(eff, self._pending[-1][0])
        self._pending.append((eff, base))
        self.shifts += 1
        self.rows_moved += rows
        return rows

    def shares_for_fetch(self) -> List[int]:
        """The share vector governing the NEXT fetched batch; advances the
        fetch counter and applies any update whose effective index has
        arrived.  Every host calls this once per batch in the same order,
        so fetch ``f`` sees the same shares fleet-wide."""
        f = self._fetches
        self._fetches += 1
        while self._pending and self._pending[0][0] <= f:
            self.shares = list(self._pending.pop(0)[1])
        return list(self.shares)


def _tree_map_arrays(fn, tree):
    """Map ``fn`` over the array leaves of a batch pytree (jax's tree_map,
    imported lazily — this module stays importable without touching a
    backend; covers every container collate functions produce)."""
    import jax

    return jax.tree_util.tree_map(fn, tree)


def _pad_rows(tree, n: int):
    """Zero-pad every leaf's leading (row) axis to exactly ``n`` — the
    fixed-shape payload the exchange collective needs."""

    def leaf(x):
        x = np.asarray(x)
        if x.shape[0] == n:
            return x
        pad = np.zeros((n - x.shape[0],) + x.shape[1:], x.dtype)
        return np.concatenate([x, pad], axis=0)

    return _tree_map_arrays(leaf, tree)


def _default_allgather(tree):
    """Cross-host exchange of the padded read payload: every leaf gains a
    leading ``[n_hosts]`` axis.  Only invoked while shares are actually
    shifted — a balanced fleet reads its own rows and never collects."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(tree)
    return _tree_map_arrays(np.asarray, gathered)


def reassemble_from_gathered(gathered, shares, rank: int, batch_size: int):
    """Pick this host's canonical batch rows out of the gathered per-host
    read payloads.  Canonical row ``j`` was read by the host whose share
    range covers ``j``; the math is pure so the mp harness and the
    simulated-host unit tests exercise the SAME code."""
    cuts = np.concatenate([[0], np.cumsum(np.asarray(shares, np.int64))])
    j = np.arange(rank * batch_size, (rank + 1) * batch_size)
    host_of = np.searchsorted(cuts, j, side="right") - 1
    off = j - cuts[host_of]

    def leaf(x):
        x = np.asarray(x)  # [n_hosts, slice_size, ...]
        return x[host_of, off]

    return _tree_map_arrays(leaf, gathered)


def assemble_rebalanced_batch(
    per_replica, shares, rank: int, batch_size: int, assemble, allgather=None
):
    """One rebalanced batch: read this host's share of the canonical
    slice, exchange only when shares are shifted, return this host's
    canonical batch.  ``per_replica`` is the sampler's per-host index plan
    for one batch (``BucketedDistributedSampler.global_batches()`` entry);
    ``assemble(idx)`` reads + collates rows; ``allgather`` is injectable
    so single-process tests can simulate a fleet."""
    canonical = [i for sub in per_replica for i in sub]
    cuts = np.concatenate([[0], np.cumsum(np.asarray(shares, np.int64))])
    if int(cuts[-1]) != len(canonical):
        raise ValueError(
            f"Stoke -- rebalance shares {list(shares)} do not cover the "
            f"slice ({len(canonical)} rows)"
        )
    mine = canonical[int(cuts[rank]):int(cuts[rank + 1])]
    rows = assemble(mine)
    if max(shares) == min(shares):
        # balanced: this host read exactly its canonical batch — no
        # exchange, byte-identical to the non-rebalanced loader's output
        return rows
    # pad to the LARGEST share, not the whole slice: shares are identical
    # fleet-wide (the deterministic agreement protocol), so max(shares)
    # is a valid uniform collective shape at a fraction of the bytes —
    # reassembly only ever indexes off < shares[host]
    payload = _pad_rows(rows, int(max(shares)))
    gather = allgather if allgather is not None else _default_allgather
    return reassemble_from_gathered(
        gather(payload), shares, rank, batch_size
    )


class _RebalancedLoader:
    """Inner loader for the rebalanced read path: walks the sampler's
    GLOBAL batch plan, reads this host's share of each slice, and yields
    this host's canonical (host-side) batches.  Wrapped by
    :class:`StokeDataLoader` like any other inner loader, so placement,
    telemetry wait accounting, and prefetch are unchanged."""

    def __init__(
        self,
        dataset,
        sampler,
        batch_size: int,
        rebalancer: InputRebalancer,
        collate_fn=None,
        allgather=None,
    ):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.rebalancer = rebalancer
        self._collate = collate_fn or _default_collate
        self._allgather = allgather
        self._batcher = None
        if isinstance(dataset, ArrayDataset):
            from stoke_tpu.native import NativeBatcher

            self._batcher = NativeBatcher()

    def __len__(self):
        return len(self.sampler) // self.batch_size

    def _assemble(self, idx):
        if self._batcher is not None:
            gathered = np.asarray(idx, np.int64)
            batch = tuple(
                self._batcher.gather_rows(a, gathered)
                for a in self.dataset.arrays
            )
            return batch if len(batch) > 1 else batch[0]
        return self._collate([self.dataset[int(i)] for i in idx])

    def __iter__(self):
        rb = self.rebalancer
        for per_replica in self.sampler.global_batches():
            shares = rb.shares_for_fetch()
            yield assemble_rebalanced_batch(
                per_replica,
                shares,
                rb.rank,
                self.batch_size,
                self._assemble,
                self._allgather,
            )


# --------------------------------------------------------------------------- #
# Loader
# --------------------------------------------------------------------------- #


def _default_collate(samples: List[Any]):
    """Minimal numpy collate for the torch-free fallback path: stacks arrays
    (and array-likes) leaf-wise over tuples/lists/dicts."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(_default_collate(list(s)) for s in zip(*samples))
    if isinstance(first, dict):
        return {k: _default_collate([s[k] for s in samples]) for k in first}
    return np.stack([np.asarray(s) for s in samples])


class _FallbackLoader:
    """Dependency-free map-style loader used when torch is not importable.
    Supports batch_size/shuffle/sampler/drop_last/collate_fn, plus a
    thread-pool parallel path for ``num_workers > 0`` (the reference
    inherits torch's C++ multi-worker loader, SURVEY.md §2.6 #24; a
    torch-free image previously had no parallel path for generic map-style
    datasets — VERDICT r3 missing #3).

    Threads, not processes: dataset ``__getitem__`` for real workloads is
    IO/decode/numpy-bound (all GIL-releasing), batches need no pickling,
    and the in-repo native batcher already covers the pure-indexing
    ``ArrayDataset``/``RaggedSequenceDataset`` cases where threads would
    not help.  ``num_workers * prefetch_factor`` batches are assembled
    ahead, yielded strictly in order.

    THREAD-SAFETY CONTRACT (differs from torch!): torch's ``num_workers``
    forks per-worker dataset copies, so a dataset holding shared mutable
    state (e.g. one open file handle it seeks) is safe there but NOT here —
    ``__getitem__`` is called concurrently on the ONE shared dataset
    object.  Keep ``__getitem__`` stateless (open file handles per call,
    or guard shared state with a lock), or use ``num_workers=0``.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        sampler: Optional[Sequence[int]] = None,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        seed: int = 0,
        num_workers: int = 0,
        prefetch_factor: int = 2,
        **_unused,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.sampler = sampler
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self._epoch_seed = seed
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(1, int(prefetch_factor))

    def __len__(self):
        n = len(self.sampler) if self.sampler is not None else len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def _batch_indices(self):
        if self.sampler is not None:
            order = list(iter(self.sampler))
        else:
            order = list(range(len(self.dataset)))
            if self.shuffle:
                rng = np.random.default_rng(self._epoch_seed)
                self._epoch_seed += 1
                rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            idx = order[start : start + self.batch_size]
            if self.drop_last and len(idx) < self.batch_size:
                break
            yield idx

    def _assemble(self, idx):
        return self.collate_fn([self.dataset[i] for i in idx])

    def __iter__(self):
        if self.num_workers <= 0:
            for idx in self._batch_indices():
                yield self._assemble(idx)
            return
        # num_workers > 0 without torch: __getitem__ now runs CONCURRENTLY
        # on the one shared dataset object (torch would fork per-worker
        # copies).  Surface the semantic change once so a dataset with
        # shared mutable state (e.g. a seeked file handle) isn't silently
        # raced (ADVICE r4).
        global _WARNED_THREADED
        if not _WARNED_THREADED:
            _WARNED_THREADED = True
            warnings.warn(
                "torch-free fallback loader: num_workers>0 uses a THREAD "
                "pool over the shared dataset object; __getitem__ must be "
                "thread-safe (pass num_workers=0 for the sequential path)",
                stacklevel=2,
            )
        from concurrent.futures import ThreadPoolExecutor

        window = self.num_workers * self.prefetch_factor
        with ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="stoke-loader",
        ) as pool:
            pending: deque = deque()
            batches = self._batch_indices()
            try:
                for idx in batches:
                    pending.append(pool.submit(self._assemble, idx))
                    if len(pending) >= window:
                        yield pending.popleft().result()
                while pending:
                    yield pending.popleft().result()
            finally:
                # a consumer abandoning the iterator mid-epoch must not
                # leave workers assembling unwanted batches
                for f in pending:
                    f.cancel()


class StokeDataLoader:
    """Loader facade yielding device-resident, mesh-sharded batches.

    Built via ``Stoke.DataLoader`` (reference stoke.py:737-851), which injects
    ``batch_size`` (per-process) and ``place_fn`` (host batch → sharded device
    arrays) from the validated status — preserving the reference paradigm that
    "the flags only need to be set and never handled" (data.py:44-47).

    Accepts the torch DataLoader surface (num_workers, pin_memory is ignored,
    sampler, collate_fn, ...) and falls back to a dependency-free loader when
    torch is absent (``num_workers > 0`` then means a THREAD pool over the
    one shared dataset object — see the ``_FallbackLoader`` thread-safety
    contract — rather than torch's per-worker process copies).

    Args:
        prefetch: number of batches to keep in flight on device (default 2 =
            double buffering).  Transfers are async dispatches; lookahead
            overlaps host→HBM copy with device compute.
        place: set False to get host batches (escape hatch).
        telemetry: optional ``stoke_tpu.telemetry.Telemetry`` — the loader
            then accounts host-loader wait time (``data/loader_wait_s``)
            and post-warmup starvation (``data/starvation_s``: time the
            training loop sat blocked on ``next()`` after the prefetch
            window was primed — the input-pipeline-bound signal) into its
            registry.  Wired automatically by ``Stoke.DataLoader``.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        place_fn: Optional[Callable] = None,
        prefetch: int = 2,
        place: bool = True,
        telemetry=None,
        rebalancer: Optional[InputRebalancer] = None,
        rebalance_allgather=None,
        **kwargs,
    ):
        self._place_fn = place_fn if place else None
        self._prefetch = max(int(prefetch), 1)
        self._telemetry = telemetry
        self.batch_size = batch_size
        self._rebalancer = rebalancer
        if rebalancer is not None:
            # skew-reactive read rebalancing (ISSUE 14): needs the GLOBAL
            # batch plan, so the sampler must expose it
            sampler = kwargs.get("sampler")
            if sampler is None or not hasattr(sampler, "global_batches"):
                raise ValueError(
                    "Stoke -- input rebalancing (FleetConfig.rebalance) "
                    "requires a sampler exposing global_batches() — use "
                    "BucketedDistributedSampler (or drop rebalance)"
                )
            unconsumed = set(kwargs) - {"sampler", "collate_fn"}
            if unconsumed:
                # the rebalanced read path assembles rows itself — a
                # num_workers/drop_last/... silently ignored here would
                # change read semantics without a diagnostic (the
                # silently-ignored-knob anti-pattern)
                raise ValueError(
                    f"Stoke -- the rebalanced loader path consumes only "
                    f"sampler/collate_fn; {sorted(unconsumed)} would be "
                    f"silently ignored — drop them or turn off "
                    f"FleetConfig.rebalance"
                )
            self._loader = _RebalancedLoader(
                dataset,
                sampler,
                batch_size,
                rebalancer,
                collate_fn=kwargs.get("collate_fn"),
                allgather=rebalance_allgather,
            )
            return
        if isinstance(dataset, ArrayDataset):
            # native fast path: one GIL-free row-gather per array per batch
            self._loader = _NativeArrayLoader(dataset, batch_size=batch_size, **kwargs)
            return
        if isinstance(dataset, RaggedSequenceDataset):
            # native ragged fast path: gather + pad + mask in one call
            self._loader = _NativeRaggedLoader(dataset, batch_size=batch_size, **kwargs)
            return
        try:
            from torch.utils import data as torch_data

            if "collate_fn" not in kwargs:
                kwargs["collate_fn"] = _numpy_safe_torch_collate()
            if kwargs.get("num_workers", 0) > 0 and (
                "multiprocessing_context" not in kwargs
            ):
                # fork()ing a JAX process (multithreaded) can deadlock the
                # workers; default to forkserver, the same fix the reference
                # applies for horovod (stoke.py:809-820)
                import multiprocessing

                if "forkserver" in multiprocessing.get_all_start_methods():
                    kwargs["multiprocessing_context"] = "forkserver"
            self._loader = torch_data.DataLoader(
                dataset, batch_size=batch_size, **kwargs
            )
        except ImportError:
            self._loader = _FallbackLoader(dataset, batch_size=batch_size, **kwargs)

    def __len__(self):
        return len(self._loader)

    @property
    def sampler(self):
        return getattr(self._loader, "sampler", None)

    def set_epoch(self, epoch: int) -> None:
        """Forward to a distributed sampler when present (reference users call
        ``loader.sampler.set_epoch`` directly; this is a convenience)."""
        s = self.sampler
        if s is not None and hasattr(s, "set_epoch"):
            s.set_epoch(epoch)

    def _next_timed(self, it, wait_counter, starve_counter=None):
        """``next(it)`` with host-loader wait accounting: all wait lands in
        ``data/loader_wait_s``; post-warmup wait additionally counts as
        starvation (the device had nothing prefetched to hide it behind)."""
        import time

        t0 = time.perf_counter()
        try:
            return next(it)
        finally:
            dt = time.perf_counter() - t0
            wait_counter.inc(dt)
            if starve_counter is not None:
                starve_counter.inc(dt)

    def __iter__(self):
        if self._telemetry is None:
            yield from self._iter_batches()
            return
        reg = self._telemetry.registry
        wait = reg.counter(
            "data/loader_wait_s",
            help="host seconds blocked on the host-side loader",
        )
        starve = reg.counter(
            "data/starvation_s",
            help="post-warmup loader wait (device-starving portion)",
        )
        yield from self._iter_batches(wait, starve)

    def _iter_batches(self, wait_counter=None, starve_counter=None):
        from stoke_tpu.telemetry.tracing import trace_span

        def fetch(it, warm: bool):
            with trace_span("stoke/io", track="data"):
                if wait_counter is None:
                    return next(it)
                return self._next_timed(
                    it, wait_counter, starve_counter if warm else None
                )

        if self._place_fn is None:
            it = iter(self._loader)
            warm = False
            while True:
                try:
                    batch = fetch(it, warm)
                except StopIteration:
                    return
                warm = True
                self._note_yield()
                yield batch
            return
        # lookahead pipeline: keep `prefetch` placed batches in flight
        queue: List[Any] = []
        it = iter(self._loader)
        try:
            for _ in range(self._prefetch):
                queue.append(self._place_fn(fetch(it, warm=False)))
        except StopIteration:
            pass
        while queue:
            out = queue.pop(0)
            try:
                queue.append(self._place_fn(fetch(it, warm=True)))
            except StopIteration:
                pass
            self._note_yield()
            yield out

    def _note_yield(self) -> None:
        # rebalancer apply-point anchor (ISSUE 14): delivered-batch counts
        # are lockstep across SPMD hosts, unlike fetch counts, which lead
        # by up to the prefetch depth
        if self._rebalancer is not None:
            self._rebalancer.note_yield()


class _NumpySafeTorchCollate:
    """torch's default collate, post-converted to numpy so downstream device
    placement never touches torch dtypes XLA can't ingest (bf16 etc. stay on
    the JAX side of the fence).  A module-level class so multiprocessing
    workers (forkserver/spawn) can pickle it."""

    @staticmethod
    def _to_np(x):
        if hasattr(x, "detach"):
            return x.detach().cpu().numpy()
        return x

    def __call__(self, samples):
        from torch.utils.data._utils.collate import default_collate

        batch = default_collate(samples)
        if isinstance(batch, (tuple, list)):
            return type(batch)(self._to_np(b) for b in batch)
        if isinstance(batch, dict):
            return {k: self._to_np(v) for k, v in batch.items()}
        return self._to_np(batch)


def _numpy_safe_torch_collate():
    return _NumpySafeTorchCollate()


# --------------------------------------------------------------------------- #
# Bucketed distributed sampler (reference data.py:111-516)
# --------------------------------------------------------------------------- #


class BucketedDistributedSampler:
    """Distributed sampler drawing each batch from one similar-length bucket.

    Semantics mirror the reference (stoke/data.py:111-516): the caller
    provides ``sorted_idx`` — dataset indices pre-sorted by the bucketing
    characteristic (e.g. sequence length).  The index list is split into
    ``buckets`` contiguous buckets; every epoch each bucket is shuffled
    internally (seeded by ``seed + epoch``), carved into *slices* of
    ``batch_size × num_replicas``, and each replica takes a strided
    (``rank::num_replicas``) sub-batch of every slice, so all replicas see
    equal-size, similar-length batches.  Short final slices are padded by
    borrowing stride-aligned indices from the bucket head (reference
    data.py:450-498); with ``drop_last + allow_bucket_overlap`` the dropped
    residuals are regrouped into extra (mixed-length) batches
    (reference data.py:419-434).  Batch order is then shuffled across buckets
    so consecutive batches don't walk monotonically through lengths.

    Invariants (property-tested in tests/test_data.py, mirroring the asserts
    at reference data.py:409 and :447):
      * every yielded epoch has exactly ``len(self)`` indices;
      * each padded bucket expands to exactly
        ``num_slices_per_bucket × slice_size`` indices;
      * the union of all replicas' indices per slice is the slice itself.

    Args:
        dataset: sized dataset (only ``len`` is used).
        buckets: number of contiguous buckets.
        batch_size: per-replica batch size (for this framework: the
            *per-process* batch — batch_size_per_device × local mesh share).
        sorted_idx: dataset indices sorted by the bucketing key.
        num_replicas: loading processes (default ``jax.process_count()``).
        rank: this process (default ``jax.process_index()``).
        allow_bucket_overlap / shuffle / seed / drop_last / info_rank: as in
            the reference.
    """

    def __init__(
        self,
        dataset,
        buckets: int,
        batch_size: int,
        sorted_idx: Sequence[int],
        allow_bucket_overlap: bool = False,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
        info_rank: int = 0,
        backend: Any = None,  # parity arg; topology comes from JAX, not enums
    ):
        if num_replicas is None or rank is None:
            import jax

            num_replicas = num_replicas if num_replicas is not None else jax.process_count()
            rank = rank if rank is not None else jax.process_index()
        if not (0 <= rank < num_replicas):
            raise ValueError(
                f"Stoke -- sampler rank {rank} out of range for {num_replicas} replicas"
            )
        self.num_replicas = int(num_replicas)
        self.rank = int(rank)
        self.epoch = 0
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.buckets = int(buckets)
        self.batch_size = int(batch_size)
        self.sorted_idx = list(sorted_idx)
        self.allow_bucket_overlap = allow_bucket_overlap

        self.slice_size = self.batch_size * self.num_replicas
        n = len(dataset)
        self.num_samples_per_bucket = self._split_size(n, self.buckets, drop_last)
        self.num_slices_per_bucket = self._split_size(
            self.num_samples_per_bucket, self.slice_size, drop_last
        )
        # sanity gates mirroring reference data.py:228-243
        if self.num_samples_per_bucket < self.slice_size:
            raise ValueError(
                f"Stoke -- samples per bucket ({self.num_samples_per_bucket}) is "
                f"smaller than one slice (batch × replicas = {self.slice_size})"
            )
        if self.num_slices_per_bucket < 2:
            raise ValueError(
                f"Stoke -- only {self.num_slices_per_bucket} slice(s) per bucket; "
                f"need >= 2 (use fewer buckets or a smaller batch)"
            )
        if self.num_samples_per_bucket < 100:
            raise ValueError(
                f"Stoke -- {self.num_samples_per_bucket} samples per bucket < 100 "
                f"would drop excessive data (use fewer buckets)"
            )
        self.bucket_idx = [
            list(chunk) for chunk in np.array_split(np.asarray(self.sorted_idx), self.buckets)
        ]
        self.rounded_num_samples_per_bucket = (
            self.num_slices_per_bucket * self.slice_size
        )
        self.rounded_num_samples_per_replica = (
            self.num_slices_per_bucket * self.batch_size * self.buckets
        )
        if self.allow_bucket_overlap:
            residual = n - self.rounded_num_samples_per_bucket * self.buckets
            self.rounded_num_samples_per_replica += (
                residual // self.slice_size
            ) * self.batch_size
        if self.rank == info_rank:
            print(
                f"Stoke -- BucketedDistributedSampler -- samples/bucket: "
                f"{self.rounded_num_samples_per_bucket}, samples/replica: "
                f"{self.rounded_num_samples_per_replica}"
            )

    @staticmethod
    def _split_size(total: int, parts: int, drop_last: bool) -> int:
        return total // parts if drop_last else math.ceil(total / parts)

    # ------------------------------------------------------------------ #

    def _pad_bucket(self, bucket: List[int]) -> List[int]:
        """Extend a short bucket to exactly ``num_slices × slice_size``
        entries so the strided replica slicing stays aligned (reference
        ``_handle_padding``, data.py:450-498).

        The final (short) slice is padded by borrowing indices from the
        bucket head with stride ``num_replicas``, interleaved so that each
        replica's strided sub-batch reaches exactly ``batch_size``.
        """
        full = (self.num_slices_per_bucket - 1) * self.slice_size
        head, short = bucket[:full], bucket[full:]
        # how many each replica is short: replica r owns positions
        # r, r+num_replicas, ... of the slice
        per_replica = [
            len(short[r :: self.num_replicas]) for r in range(self.num_replicas)
        ]
        need = [self.batch_size - c for c in per_replica]
        # borrow stride-aligned values from the bucket head for each replica
        donors = [
            bucket[r : self.num_replicas * need[r] : self.num_replicas]
            for r in range(self.num_replicas)
        ]
        # if replicas need unequal amounts, rotate so the longest-need replica
        # leads and the interleave stays stride-consistent
        if len(set(need)) > 1:
            lead = need.index(max(need))
            donors = donors[lead:] + donors[:lead]
        pad = [
            v
            for v in itertools.chain(*itertools.zip_longest(*donors))
            if v is not None
        ]
        return head + short + pad

    def _epoch_rng(self) -> np.random.Generator:
        return np.random.default_rng(self.seed + self.epoch)

    def _epoch_slices(self) -> List[List[int]]:
        """This epoch's global slices (``slice_size`` indices each), in
        final yielded order — the rng call sequence (per-bucket shuffles,
        then the cross-bucket batch-order shuffle) is byte-identical to
        the pre-refactor ``__iter__``, so per-epoch streams are unchanged.
        Shared by ``__iter__`` (this replica's strided sub-batches) and
        ``global_batches`` (every replica's — the rebalanced read path)."""
        rng = self._epoch_rng()
        if self.shuffle:
            buckets = [list(np.asarray(b)[rng.permutation(len(b))]) for b in self.bucket_idx]
        else:
            buckets = [list(b) for b in self.bucket_idx]
        # pad any bucket that cannot fill its slices
        for i, b in enumerate(buckets):
            if len(b) < self.rounded_num_samples_per_bucket:
                padded = self._pad_bucket(b)
                assert len(padded) == self.rounded_num_samples_per_bucket
                buckets[i] = padded
        # carve into slices
        slices: List[List[int]] = []
        for b in buckets:
            for s in range(self.num_slices_per_bucket):
                slices.append(b[s * self.slice_size : (s + 1) * self.slice_size])
        # regroup dropped residuals into extra mixed slices
        if self.drop_last and self.allow_bucket_overlap:
            residual = list(
                itertools.chain(
                    *[b[self.rounded_num_samples_per_bucket :] for b in buckets]
                )
            )
            for s in range(len(residual) // self.slice_size):
                slices.append(residual[s * self.slice_size : (s + 1) * self.slice_size])
        if self.shuffle:
            order = rng.permutation(len(slices))
            slices = [slices[i] for i in order]
        return slices

    def _replica_batch(self, sl: List[int], rank: int) -> List[int]:
        return sl[rank : self.slice_size : self.num_replicas]

    def global_batches(self) -> List[List[List[int]]]:
        """EVERY replica's read plan for this epoch (ISSUE 14, the
        rebalanced loader's input): one entry per yielded batch, each a
        ``num_replicas``-list of canonical per-replica index lists.  Entry
        ``b[rank]`` equals batch ``b`` of this epoch's ``__iter__``
        stream for that rank — all replicas derive the identical plan."""
        return [
            [self._replica_batch(sl, r) for r in range(self.num_replicas)]
            for sl in self._epoch_slices()
        ]

    def __iter__(self) -> Iterator[int]:
        batches = [
            self._replica_batch(sl, self.rank) for sl in self._epoch_slices()
        ]
        flat = [int(i) for i in itertools.chain(*batches)]
        assert len(flat) == self.rounded_num_samples_per_replica
        return iter(flat)

    def __len__(self) -> int:
        return self.rounded_num_samples_per_replica

    def set_epoch(self, epoch: int) -> None:
        """Per-epoch reseed so replicas reshuffle consistently (reference
        data.py:503-516)."""
        self.epoch = epoch

"""Chaos-test worker (tests/test_resilience.py end-to-end): a tiny
deterministic training run that resumes on restart and records its loss
trajectory + final params.

Run under ``scripts/run_resilient.py`` with ``STOKE_CHAOS=kill_at_step=K``
to exercise the whole detect→save→restart→resume loop; run clean for the
uninterrupted reference trajectory.  Deterministic by construction: the
batch stream is derived from a fixed seed and indexed by optimizer step,
so a resumed attempt replays exactly the steps the preempted one never
ran.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

IN, OUT = 8, 4


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True,
                    help="workdir: checkpoints + trajectory + final params")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--resilience", action="store_true")
    ap.add_argument("--offload-saves", type=int, default=None,
                    help="periodic OFFLOAD-STAGED async saves every N "
                    "steps (ISSUE 14; the kill_during_save chaos target)")
    args = ap.parse_args()

    import optax

    from stoke_tpu import CheckpointConfig, ResilienceConfig, Stoke, \
        StokeOptimizer

    configs = []
    if args.offload_saves:
        configs.append(CheckpointConfig(
            async_save=True,
            offload_staging=True,
            save_every_n_steps=args.offload_saves,
            auto_path=os.path.join(args.root, "auto"),
        ))
    if args.resilience:
        configs.append(ResilienceConfig(
            save_path=os.path.join(args.root, "ckpts"),
        ))
    stoke = Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((IN, OUT), np.float32) * 0.1},
        batch_size_per_device=4,
        configs=configs,
        verbose=False,
    )
    if args.resilience:
        stoke.resume()

    rng = np.random.default_rng(7)
    W = rng.normal(size=(IN, OUT)).astype(np.float32)
    batches = []
    for _ in range(args.steps):
        x = rng.normal(size=(32, IN)).astype(np.float32)
        batches.append((x, (x @ W).astype(np.float32)))

    attempt = int(os.environ.get("STOKE_RESTART_ATTEMPT", "0") or 0)
    start = stoke.optimizer_steps  # 0 fresh; K after a resume
    with open(os.path.join(args.root, "trajectory.jsonl"), "a") as f:
        for i in range(start, args.steps):
            x, y = batches[i]
            report = stoke.train_step(x, (y,))
            f.write(json.dumps({
                "step": stoke.optimizer_steps,
                "loss": float(np.asarray(report)),
                "attempt": attempt,
            }) + "\n")
            f.flush()

    if args.offload_saves:
        stoke.wait_for_checkpoint()
    np.save(
        os.path.join(args.root, "final_w.npy"),
        np.asarray(stoke.params["w"]),
    )
    stoke.close_telemetry()
    return 0


if __name__ == "__main__":
    sys.exit(main())

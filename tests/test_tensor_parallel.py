"""Tensor-parallelism tests: PartitionRulesConfig path-regex overrides over
the tier rules, Megatron-style BERT rules, numerical equivalence of TP vs
pure-DP training on the simulated mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from stoke_tpu import (
    MeshConfig,
    PartitionRulesConfig,
    Stoke,
    StokeOptimizer,
    init_module,
)
from stoke_tpu.models import BertForSequenceClassification, bert_tensor_parallel_rules
from stoke_tpu.parallel.sharding import compile_partition_rules, sharding_tree


def test_override_beats_default(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices("cpu")).reshape(4, 2), ("data", "model"))
    overrides = compile_partition_rules(
        ((r"w1$", (None, "model")), (r"w2$", ("model", None)))
    )
    tree = {"w1": np.zeros((8, 64)), "w2": np.zeros((64, 8)), "b": np.zeros((64,))}
    sh = sharding_tree(tree, mesh, lambda shape: P(), overrides)
    assert sh["w1"].spec == P(None, "model")
    assert sh["w2"].spec == P("model", None)
    assert sh["b"].spec == P()  # no rule → default


def test_override_rank_mismatch_raises(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices("cpu")).reshape(4, 2), ("data", "model"))
    overrides = compile_partition_rules(((r"w1", (None, "model", None)),))
    with pytest.raises(ValueError):
        sharding_tree({"w1": np.zeros((8, 64))}, mesh, lambda s: P(), overrides)


def test_override_rank_mismatch_lenient_for_opt(devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices("cpu")).reshape(4, 2), ("data", "model"))
    overrides = compile_partition_rules(((r"w1", (None, "model", None)),))
    sh = sharding_tree(
        {"w1": np.zeros((8, 64))}, mesh, lambda s: P(), overrides,
        strict_overrides=False,
    )
    assert sh["w1"].spec == P()  # falls back


def _make_bert_stoke(tp: bool, rng_seed=0):
    model = BertForSequenceClassification(
        vocab_size=100, num_classes=2, size_name="tiny", max_len=64,
        dropout_rate=0.0,
    )
    ids = np.ones((2, 16), np.int32)
    variables = init_module(
        model, jax.random.PRNGKey(rng_seed), ids, np.ones_like(ids), train=False
    )
    configs = [MeshConfig(axes=("data", "model"), shape=(4, 2))]
    if tp:
        configs.append(PartitionRulesConfig(rules=bert_tensor_parallel_rules()))
    return Stoke(
        model=model,
        # SGD: linear in the gradients, so placement-only reordering noise
        # stays at float-epsilon scale (adam's sqrt-normalization amplifies
        # reassociation noise into O(lr) flips near zero gradients)
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
        ),
        loss=lambda logits, y: optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean(),
        params=variables,
        batch_size_per_device=2,
        device="cpu",
        distributed="dp",
        configs=configs,
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )


def _train(s, steps=4):
    r = np.random.default_rng(1)
    for _ in range(steps):
        ids = r.integers(1, 100, size=(8, 16)).astype(np.int32)
        mask = np.ones_like(ids)
        y = r.integers(0, 2, size=(8,))
        s.train_step((ids, mask), y)
    return s


@pytest.mark.slow
def test_bert_tp_placement(devices):
    s = _make_bert_stoke(tp=True)
    flat = jax.tree_util.tree_flatten_with_path(s.params)[0]
    placed = {
        "/".join(str(getattr(p, "key", p)) for p in path): leaf.sharding.spec
        for path, leaf in flat
    }
    qkv = [v for k, v in placed.items() if "qkv/kernel" in k]
    ffi = [v for k, v in placed.items() if "ff_in/kernel" in k]
    ffo = [v for k, v in placed.items() if "ff_out/kernel" in k]
    assert qkv and all(v == P(None, None, "model", None) for v in qkv)
    assert ffi and all(v == P(None, "model") for v in ffi)
    assert ffo and all(v == P("model", None) for v in ffo)


@pytest.mark.slow
def test_bert_tp_matches_dp(devices):
    """TP is placement-only: training numerics must equal pure DP."""
    s_dp = _train(_make_bert_stoke(tp=False))
    s_tp = _train(_make_bert_stoke(tp=True))
    a = jax.tree_util.tree_leaves(s_dp.params)
    b = jax.tree_util.tree_leaves(s_tp.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=5e-4, atol=5e-6
        )


@pytest.mark.slow
def test_tp_composes_with_fsdp(devices):
    """Rules override matching params; everything else follows the tier."""
    from stoke_tpu import FSDPConfig

    model = BertForSequenceClassification(
        vocab_size=100, num_classes=2, size_name="tiny", max_len=64,
        dropout_rate=0.0,
    )
    ids = np.ones((2, 16), np.int32)
    variables = init_module(
        model, jax.random.PRNGKey(0), ids, np.ones_like(ids), train=False
    )
    s = Stoke(
        model=model,
        optimizer=StokeOptimizer(
            optimizer=optax.adam, optimizer_kwargs={"learning_rate": 1e-3}
        ),
        loss=lambda logits, y: optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean(),
        params=variables,
        batch_size_per_device=2,
        device="cpu",
        distributed="dp",
        fsdp=True,
        configs=[
            MeshConfig(axes=("data", "model"), shape=(4, 2)),
            PartitionRulesConfig(rules=bert_tensor_parallel_rules()),
            FSDPConfig(min_weight_size=1),
        ],
        model_train_kwargs={"train": True},
        model_eval_kwargs={"train": False},
        verbose=False,
    )
    flat = jax.tree_util.tree_flatten_with_path(s.params)[0]
    placed = {
        "/".join(str(getattr(p, "key", p)) for p in path): leaf.sharding.spec
        for path, leaf in flat
    }
    # TP rule wins for matched params
    assert any(v == P(None, None, "model", None) for k, v in placed.items()
               if "qkv/kernel" in k)
    # unmatched params follow FSDP (sharded over data)
    emb = [v for k, v in placed.items() if "tok_emb" in k]
    assert emb and all("data" in str(v) for v in emb)
    _train(s, steps=2)
    assert s.optimizer_steps == 2


@pytest.mark.slow
def test_gpt_tp_matches_dp(devices):
    """The TP rules are family-wide: GPT shares TransformerBlock's param
    paths, so gpt_tensor_parallel_rules (= the bert rules) must place its
    qkv/ff weights on the model axis and train with numerics equal to DP."""
    from stoke_tpu.models import GPT, causal_lm_loss, gpt_tensor_parallel_rules

    def make(tp):
        model = GPT(vocab_size=64, size_name="tiny", max_len=32,
                    dropout_rate=0.0)
        seq = np.tile(np.arange(16, dtype=np.int32), 2)[None, :].repeat(8, 0)
        v = init_module(model, jax.random.PRNGKey(0), seq[:2], train=False)
        cfgs = [MeshConfig(axes=("data", "model"), shape=(4, 2))]
        if tp:
            cfgs.append(
                PartitionRulesConfig(rules=gpt_tensor_parallel_rules())
            )
        s = Stoke(
            model=model,
            # SGD, same reasoning as _make_bert_stoke: adam's sqrt
            # normalization turns TP reassociation noise on near-zero
            # gradients (e.g. the symmetric-init qkv bias) into O(lr) flips
            optimizer=StokeOptimizer(
                optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
            ),
            loss=causal_lm_loss,
            params=v,
            batch_size_per_device=1,
            device="cpu",
            distributed="dp",
            configs=cfgs,
            verbose=False,
        )
        for _ in range(5):
            s.train_step(seq, (seq,))
        return s

    s_tp = make(tp=True)
    w = s_tp.params["layer_0"]["attention"]["qkv"]["kernel"]
    assert "model" in jax.tree_util.tree_leaves(
        [w.sharding.spec]
    )[0] or "model" in tuple(w.sharding.spec), w.sharding.spec
    s_dp = make(tp=False)
    for x, y in zip(jax.tree_util.tree_leaves(s_dp.params),
                    jax.tree_util.tree_leaves(s_tp.params)):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=5e-4, atol=5e-6
        )

"""Structured-tracing tests (ISSUE 10): span-ring bounds, parent/child
nesting, Perfetto trace-event schema, per-request serve timelines,
cross-rank merge alignment, bundle trace.json, and the default-OFF
HLO-identity contract — all on the 8-device CPU mesh, no wall-clock
assertions (structural properties only)."""

import json
import os

import numpy as np
import pytest

import jax

from stoke_tpu.configs import TraceConfig
from stoke_tpu.status import StokeStatus, StokeValidationError
from stoke_tpu.telemetry.registry import MetricsRegistry
from stoke_tpu.telemetry.tracing import (
    TRACE_EVENT_KEYS,
    TraceRecorder,
    register_recorder,
    trace_point,
    trace_span,
    tracing_active,
    unregister_recorder,
)

pytestmark = pytest.mark.tracing


@pytest.fixture
def recorder(tmp_path):
    rec = TraceRecorder(ring_size=256, output_dir=str(tmp_path))
    register_recorder(rec)
    yield rec
    unregister_recorder(rec)


def _linear_stoke(tmp_path, with_trace: bool, **extra):
    import optax

    from stoke_tpu import Stoke, StokeOptimizer

    configs = list(extra.pop("configs", []))
    if with_trace:
        configs.append(TraceConfig(output_dir=str(tmp_path / "trace")))
    return Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.1}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((8, 4), np.float32)},
        batch_size_per_device=4,
        configs=configs or None,
        verbose=False,
        **extra,
    )


# --------------------------------------------------------------------------- #
# ring mechanics
# --------------------------------------------------------------------------- #


def test_ring_bounds_under_span_churn():
    """A full ring evicts oldest-first, counts every eviction, and never
    grows past its capacity — churning 10x the capacity through it."""
    registry = MetricsRegistry()
    rec = TraceRecorder(ring_size=16, registry=registry)
    for i in range(160):
        with rec.span(f"churn/{i % 4}"):
            pass
    assert len(rec) == 16
    assert rec.dropped == 160 - 16
    assert registry.get("trace/spans_total").value == 160
    assert registry.get("trace/dropped_total").value == 160 - 16
    # the ring holds the NEWEST spans (a post-mortem wants the recent
    # window), oldest first
    names = [s.name for s in rec.spans()]
    assert names[-1] == f"churn/{159 % 4}"
    assert all(n.startswith("churn/") for n in names)


def test_parent_child_nesting_and_self_time():
    rec = TraceRecorder(ring_size=64)
    with rec.span("outer"):
        with rec.span("mid"):
            with rec.span("inner"):
                pass
        with rec.span("mid2"):
            pass
    by_name = {s.name: s for s in rec.spans()}
    outer, mid, inner, mid2 = (
        by_name["outer"], by_name["mid"], by_name["inner"], by_name["mid2"]
    )
    assert outer.parent_id is None
    assert mid.parent_id == outer.span_id
    assert inner.parent_id == mid.span_id
    assert mid2.parent_id == outer.span_id
    # children close before parents: ids and the ring order agree
    assert [s.name for s in rec.spans()] == ["inner", "mid", "mid2", "outer"]
    # self-time discipline (structural, not wall-clock): a parent's self
    # time excludes its children's wall, and no span's self exceeds its
    # duration
    for s in rec.spans():
        assert 0.0 <= s.self_s <= s.dur_s + 1e-12
    assert outer.self_s <= outer.dur_s - (mid.dur_s + mid2.dur_s) + 1e-9


def test_explicit_intervals_and_points():
    rec = TraceRecorder(ring_size=64)
    rec.add("req/window", 10.0, 10.5, track="serve", request_id=7)
    rec.point("req/evict", track="serve", request_id=7)
    window, evict = rec.spans()
    assert window.dur_s == pytest.approx(0.5)
    assert window.request_id == 7 and evict.request_id == 7
    assert evict.dur_s == 0.0


def test_overlapping_slices_do_not_multiply_count_self_time():
    """Per-request timeline slices share one batch interval; with
    count_self=False they must not inflate the track's self-seconds or
    the critical-path partition (the owning span charges the wall)."""
    registry = MetricsRegistry()
    rec = TraceRecorder(ring_size=64, registry=registry)
    rec.add("serve/decode_step", 0.0, 1.0, track="serve")  # owns the wall
    for rid in range(8):  # 8 live requests riding the same interval
        rec.add("serve/decode", 0.0, 1.0, track="serve", request_id=rid,
                count_self=False)
    s = rec.summary()
    assert s["window_self_s"] == pytest.approx(1.0)
    assert registry.get("trace/serve_self_s").value == pytest.approx(1.0)
    # the slices still export with their full duration (the timeline)
    slices = [sp for sp in rec.spans() if sp.name == "serve/decode"]
    assert all(sp.dur_s == pytest.approx(1.0) for sp in slices)
    assert all(sp.self_s == 0.0 for sp in slices)


def test_summary_disambiguates_same_name_across_tracks():
    """'stoke/step' is both a facade phase and the engine apply dispatch;
    the summary must keep the two apart instead of mislabeling one."""
    rec = TraceRecorder(ring_size=64)
    rec.add("stoke/step", 0.0, 2.0, track="facade")
    rec.add("stoke/step", 0.5, 1.5, track="step")
    rec.add("stoke/place", 2.0, 2.5, track="facade")
    s = rec.summary()
    assert "stoke/step [facade]" in s["by_name"]
    assert "stoke/step [step]" in s["by_name"]
    assert s["by_name"]["stoke/step [facade]"]["track"] == "facade"
    assert s["by_name"]["stoke/step [step]"]["self_s"] == pytest.approx(1.0)
    # track-unique names keep their bare label
    assert "stoke/place" in s["by_name"]


def test_step_tagging():
    rec = TraceRecorder(ring_size=64)
    with rec.span("a"):
        pass
    rec.set_step(3)
    with rec.span("b"):
        pass
    steps = {s.name: s.step for s in rec.spans()}
    assert steps == {"a": 0, "b": 3}


# --------------------------------------------------------------------------- #
# the composed helper (the consolidation satellite)
# --------------------------------------------------------------------------- #


def test_trace_span_composes_timer_and_recorder(recorder):
    """One trace_span call must feed BOTH the registry timer and the span
    ring — the facade/telemetry layers no longer hand-roll the pairing."""
    registry = MetricsRegistry()
    timer = registry.timer("facade/work_s")
    with trace_span("stoke/work", track="facade", timer=timer):
        pass
    assert registry.get("facade/work_s").value > 0.0
    assert [s.name for s in recorder.spans()] == ["stoke/work"]


def test_trace_span_without_recorder_is_annotation_only():
    assert not tracing_active()
    cm = trace_span("stoke/bare")
    # no recorder, no timer: the composed helper degrades to the bare
    # xprof annotation (the pre-ISSUE-10 call-site behavior)
    with cm:
        pass
    trace_point("stoke/nothing")  # no-op, must not raise


def test_telemetry_phase_records_span(recorder):
    from stoke_tpu.telemetry import Telemetry

    t = Telemetry(None)
    with t.phase("step"):
        pass
    assert [s.name for s in recorder.spans()] == ["stoke/step"]
    assert t.registry.get("facade/step_s").value > 0.0
    t.close()


# --------------------------------------------------------------------------- #
# export schema
# --------------------------------------------------------------------------- #


def test_trace_event_json_schema(tmp_path):
    rec = TraceRecorder(ring_size=64, rank=3, output_dir=str(tmp_path))
    rec.set_step(5)
    with rec.span("outer", track="step"):
        with rec.span("inner", track="step"):
            pass
    rec.add("req/decode", 1.0, 2.0, track="serve", request_id=11)
    path = rec.export()
    assert os.path.basename(path) == "trace.rank3.json"
    doc = json.load(open(path))
    events = doc["traceEvents"]
    durations = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(durations) == 3
    for e in durations:
        # the Perfetto-required key set, on every duration event
        for key in TRACE_EVENT_KEYS:
            assert key in e, f"missing {key!r} in {e}"
        assert e["pid"] == 3
        assert e["dur"] >= 0
    # per-request spans get their own thread row; metadata names it
    req_events = [
        e for e in durations if e["args"].get("request_id") == 11
    ]
    assert len(req_events) == 1
    thread_names = {
        e["tid"]: e["args"]["name"] for e in meta
        if e["name"] == "thread_name"
    }
    assert thread_names[req_events[0]["tid"]] == "serve/req11"
    # nesting and steps survive the export
    inner = next(e for e in durations if e["name"] == "inner")
    outer = next(e for e in durations if e["name"] == "outer")
    assert inner["args"]["parent_id"] == outer["args"]["span_id"]
    assert outer["args"]["step"] == 5


def test_summary_critical_path():
    rec = TraceRecorder(ring_size=64)
    for _ in range(3):
        with rec.span("stoke/dispatch", track="step"):
            pass
    with rec.span("stoke/place", track="facade"):
        pass
    s = rec.summary(top=2)
    assert s["spans"] == 4
    assert s["by_name"]["stoke/dispatch"]["count"] == 3
    assert set(s["tracks"]) == {"step", "facade"}
    assert len(s["critical_path"]) == 2
    fracs = [c["frac"] for c in s["critical_path"]]
    assert all(0.0 <= f <= 1.0 for f in fracs)


# --------------------------------------------------------------------------- #
# serve request timelines
# --------------------------------------------------------------------------- #


def test_serve_request_id_correlation(recorder):
    """Every finished request's timeline must show admission, prefill,
    >= 1 decode slice, and the eviction marker, all sharing its
    request_id — TTFT/TPOT as visible span structure."""
    import optax

    from stoke_tpu.models.gpt import GPT
    from stoke_tpu.serving import ServingEngine
    from stoke_tpu.configs import ServeConfig
    from stoke_tpu.utils import init_module

    model = GPT(
        vocab_size=211, size_name="tiny", max_len=128, dropout_rate=0.0
    )
    variables = init_module(
        model, jax.random.PRNGKey(0), np.zeros((1, 8), np.int32), train=False
    )
    eng = ServingEngine(
        model,
        variables["params"],
        ServeConfig(
            max_seqs=2, kv_block_size=8, max_seq_len=64, max_new_tokens=3,
            prefill_pad_multiple=16,
        ),
    )
    r = np.random.default_rng(0)
    rids = [
        eng.submit(r.integers(1, 211, size=5).astype(np.int32))
        for _ in range(3)  # 3 requests through 2 slots: one must queue
    ]
    eng.run()
    spans = recorder.spans()
    by_rid = {}
    for s in spans:
        if s.request_id is not None:
            by_rid.setdefault(s.request_id, []).append(s)
    assert set(by_rid) == set(rids)
    for rid in rids:
        names = [s.name for s in by_rid[rid]]
        assert names.count("serve/admission") == 1
        assert names.count("serve/prefill") == 1
        # max_new_tokens=3: prefill token + 2 decode slices
        assert names.count("serve/decode") == 2
        assert names.count("serve/evict") == 1
        # the timeline is ordered: admission before prefill before the
        # decode slices (t_start monotone along the request's row)
        ordered = sorted(by_rid[rid], key=lambda s: s.t_start)
        seq = [s.name for s in ordered]
        assert seq[0] == "serve/admission" and seq[1] == "serve/prefill"
    # batch-level decode spans carry no request id but exist
    assert any(
        s.name == "serve/decode_step" and s.request_id is None
        for s in spans
    )


# --------------------------------------------------------------------------- #
# config / status / facade integration
# --------------------------------------------------------------------------- #


def test_trace_config_status_validation(tmp_path):
    with pytest.raises(StokeValidationError, match="ring_size"):
        StokeStatus(
            batch_size_per_device=1, configs=[TraceConfig(ring_size=0)]
        )
    # legal config validates clean
    StokeStatus(
        batch_size_per_device=1,
        configs=[TraceConfig(output_dir=str(tmp_path))],
    )


def test_trace_config_yaml_buildable(tmp_path):
    from stoke_tpu.utils.yaml_config import stoke_kwargs_from_config

    kwargs = stoke_kwargs_from_config({
        "batch_size_per_device": 4,
        "configs": {
            "TraceConfig": {
                "output_dir": str(tmp_path), "ring_size": 8,
                "export_on_close": False,
            }
        },
    })
    (cfg,) = kwargs["configs"]
    assert isinstance(cfg, TraceConfig)
    assert cfg.ring_size == 8 and cfg.export_on_close is False


def test_trace_config_off_hlo_bit_identical(tmp_path):
    """Acceptance: with a TraceConfig present (tracing ON — it is purely
    host-side) the training step-program HLO and dispatch counts are
    bit-identical to a config-less run, and params march in lockstep."""
    s_off = _linear_stoke(tmp_path, with_trace=False)
    s_on = _linear_stoke(tmp_path, with_trace=True)
    x = np.ones((4, 8), np.float32)
    y = np.zeros((4, 4), np.float32)
    try:
        for s in (s_off, s_on):
            for _ in range(3):
                s.train_step(x, (y,))
        assert s_on.dispatch_count == s_off.dispatch_count
        np.testing.assert_array_equal(
            np.asarray(s_on.params["w"]), np.asarray(s_off.params["w"])
        )

        def fused_hlo(s):
            from stoke_tpu.engine import DeferredOutput, is_deferred

            margs = s._place_batch((x,))
            sentinel = DeferredOutput(None, -1)
            flat, treedef = jax.tree_util.tree_flatten(
                ((sentinel, y), {}), is_leaf=is_deferred
            )
            arrays = s._place_batch(
                [leaf for leaf in flat if not is_deferred(leaf)]
            )
            deferred = tuple(
                (i, leaf._path)
                for i, leaf in enumerate(flat)
                if is_deferred(leaf)
            )
            fn = s._engine._build_fused(treedef, deferred, True)
            return fn.lower(
                s._variables, s._opt_state, s._grad_buf, s._scaler_state,
                s._comm_state, s._rng, margs, {}, arrays,
            ).as_text()

        strip = lambda t: "\n".join(
            ln for ln in t.splitlines() if not ln.startswith("HloModule")
        )
        assert strip(fused_hlo(s_on)) == strip(fused_hlo(s_off))
    finally:
        s_on.close_telemetry()


def test_facade_trace_summary_and_export(tmp_path):
    s = _linear_stoke(tmp_path, with_trace=True)
    x = np.ones((4, 8), np.float32)
    y = np.zeros((4, 4), np.float32)
    s.train_step(x, (y,))
    summary = s.trace_summary
    assert summary["spans"] > 0
    # ISSUE 16 satellite: the eviction count rides the summary under the
    # same key the registry counter and merge tool use
    assert summary["trace/dropped_total"] == 0
    # the engine dispatch and the facade phase both landed as spans
    assert "stoke/dispatch" in summary["by_name"]
    assert "stoke/train_step" in summary["by_name"]
    # dispatch nests inside the train_step phase span
    dispatch = next(
        sp for sp in s.tracer.spans() if sp.name == "stoke/dispatch"
    )
    phase = next(
        sp for sp in s.tracer.spans() if sp.name == "stoke/train_step"
    )
    assert dispatch.parent_id == phase.span_id
    s.close_telemetry()
    path = tmp_path / "trace" / "trace.rank0.json"
    assert path.exists()
    doc = json.load(open(path))
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
    # closed facade: the recorder is unregistered, later runs untraced
    assert not tracing_active()


def test_facade_without_config_has_no_tracer(tmp_path):
    s = _linear_stoke(tmp_path, with_trace=False)
    assert s.tracer is None
    assert s.trace_summary is None
    assert s.export_trace() is None


def test_bundle_contains_trace_json(tmp_path):
    from stoke_tpu import HealthConfig, TelemetryConfig

    s = _linear_stoke(
        tmp_path,
        with_trace=True,
        configs=[
            TelemetryConfig(
                output_dir=str(tmp_path / "t"), log_every_n_steps=1,
                prometheus=False, tensorboard=False,
                sample_device_time=False, track_hbm=False,
            ),
            HealthConfig(dump_signals=False),
        ],
    )
    x = np.ones((4, 8), np.float32)
    y = np.zeros((4, 4), np.float32)
    s.train_step(x, (y,))
    bundle = s.health.dump("tracing-test")
    try:
        doc = json.load(open(os.path.join(bundle, "trace.json")))
        events = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert events, "bundle trace.json carries no spans"
        assert any(e["name"] == "stoke/dispatch" for e in events)
    finally:
        s.close_telemetry()


# --------------------------------------------------------------------------- #
# cross-rank merge
# --------------------------------------------------------------------------- #


def _fake_trace(path, rank, clock_offset_us, steps=(1, 2)):
    """A rank's trace whose perf-clock origin is shifted by
    ``clock_offset_us`` — step k's first span starts at
    ``offset + k * 1000``."""
    events = [{
        "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
        "args": {"name": f"stoke rank{rank}"},
    }]
    for k in steps:
        events.append({
            "name": "stoke/dispatch", "ph": "X",
            "ts": clock_offset_us + k * 1000.0, "dur": 400.0,
            "pid": rank, "tid": 1, "args": {"step": k, "span_id": k},
        })
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)


def test_merge_rank_traces_alignment(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import merge_rank_traces as mrt

    _fake_trace(tmp_path / "trace.rank0.json", 0, clock_offset_us=0.0)
    _fake_trace(tmp_path / "trace.rank1.json", 1, clock_offset_us=5e6)
    out = tmp_path / "merged.json"
    rc = mrt.main([str(tmp_path), "--out", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    by_rank_step = {}
    for e in doc["traceEvents"]:
        if e.get("ph") != "X":
            continue
        by_rank_step[(e["pid"], e["args"]["step"])] = e["ts"]
    # anchor step 1 aligned exactly; step 2 keeps each rank's own spacing
    assert by_rank_step[(0, 1)] == pytest.approx(by_rank_step[(1, 1)])
    assert by_rank_step[(0, 2)] == pytest.approx(by_rank_step[(1, 2)])


def test_merge_rank_traces_refuses_duplicate_ranks(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import merge_rank_traces as mrt

    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir()
    b.mkdir()
    _fake_trace(a / "trace.rank0.json", 0, 0.0)
    _fake_trace(b / "trace.rank0.json", 0, 1e6)
    with pytest.raises(ValueError, match="rank 0 already provided"):
        mrt.discover_traces([str(a), str(b)])
    # and the CLI reports it as the documented nonzero exit
    assert mrt.main([str(a), str(b), "--out",
                     str(tmp_path / "m.json")]) == 2


def test_merge_rank_traces_unnamed_file_takes_free_index(tmp_path):
    """An unnamed bundle trace listed BEFORE a dir containing
    trace.rank0.json must not squat on rank 0 and refuse the named
    file's legitimate claim — fallback indices assign after all named
    claims are collected."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import merge_rank_traces as mrt

    bundle = tmp_path / "trace.json"  # no rank claim in the name
    _fake_trace(bundle, 0, clock_offset_us=3e6)
    _fake_trace(tmp_path / "trace.rank0.json", 0, clock_offset_us=0.0)
    found = dict(mrt.discover_traces([str(bundle), str(tmp_path)]))
    assert found[0].endswith("trace.rank0.json")
    assert found[1] == str(bundle)
    out = tmp_path / "merged.json"
    assert mrt.main([str(bundle), str(tmp_path), "--out", str(out)]) == 0


def test_merge_rank_traces_no_common_step(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import merge_rank_traces as mrt

    _fake_trace(tmp_path / "trace.rank0.json", 0, 0.0, steps=(1,))
    _fake_trace(tmp_path / "trace.rank1.json", 1, 0.0, steps=(2,))
    assert mrt.main([str(tmp_path), "--out",
                     str(tmp_path / "m.json")]) == 2


# --------------------------------------------------------------------------- #
# ISSUE 16 satellite: dropped-span surfacing (summary, helpers, merge)
# --------------------------------------------------------------------------- #


def test_recorder_summary_and_module_helpers_surface_dropped():
    """``TraceRecorder.summary`` carries ``trace/dropped_total`` (the key
    the facade's trace_summary and the merge tool share), and the
    module-level ``dropped_total``/``request_spans`` helpers aggregate
    over every registered recorder — the surfaces the SLO attribution
    walks."""
    from stoke_tpu.telemetry.tracing import dropped_total, request_spans

    rec = TraceRecorder(ring_size=4)
    register_recorder(rec)
    try:
        for i in range(10):
            with rec.span("churn", request_id=i % 2):
                pass
        assert rec.summary()["trace/dropped_total"] == rec.dropped == 6
        assert dropped_total() == 6
        # request_spans filters the surviving window by request id
        rids = {s.request_id for s in request_spans(1)}
        assert rids == {1}
        assert request_spans(99) == []
    finally:
        unregister_recorder(rec)
    # no registered recorder: unknown coverage reads as zero spans, and
    # the dropped aggregate is 0 (nothing is recording)
    assert request_spans(1) == []
    assert dropped_total() == 0


def test_merge_rank_traces_surfaces_dropped_counts(tmp_path, capsys):
    """The merged report carries per-rank eviction counts and the pod
    total; a file without exporter metadata (bare chrome-trace) reports
    ``None`` — unknown is never shown as zero."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import merge_rank_traces as mrt

    _fake_trace(tmp_path / "trace.rank0.json", 0, 0.0)
    # rank 1 carries the exporter's metadata block with a nonzero count
    p1 = tmp_path / "trace.rank1.json"
    _fake_trace(p1, 1, 5e6)
    doc = json.load(open(p1))
    doc["stoke"] = {"rank": 1, "dropped": 7}
    json.dump(doc, open(p1, "w"))
    assert mrt.load_dropped(str(p1)) == 7
    assert mrt.load_dropped(str(tmp_path / "trace.rank0.json")) is None
    rc = mrt.main([str(tmp_path), "--out", str(tmp_path / "m.json"),
                   "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["dropped_by_rank"] == {"0": None, "1": 7}
    assert report["trace/dropped_total"] == 7
    # human-read mode warns that the merged timeline is partial
    rc = mrt.main([str(tmp_path), "--out", str(tmp_path / "m2.json")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "dropped unknown" in out and "dropped 7" in out
    assert "PARTIAL" in out

"""Pod-scale resilience tests (ISSUE 7): preemption-aware emergency save,
manifest-verified resume with quarantine, supervised restarts with backoff,
and the deterministic chaos harness — including the end-to-end acceptance:
a run killed at an arbitrary step resumes under the supervisor and reaches
a bit-identical final-param state vs an uninterrupted run.

All CPU-only and deterministic on the 8-device simulated mesh (conftest).
"""

import json
import os
import random
import signal
import subprocess
import sys

import numpy as np
import optax
import pytest

from stoke_tpu import (
    PreemptedError,
    ResilienceConfig,
    Stoke,
    StokeOptimizer,
    StokeStatus,
    StokeValidationError,
    TelemetryConfig,
)
from stoke_tpu import io_ops, resilience
from stoke_tpu.resilience import (
    ChaosError,
    ChaosInjector,
    RestartBackoff,
    classify_exit,
    corrupt_checkpoint,
    find_latest_valid_checkpoint,
    parse_chaos,
    quarantine_checkpoint,
    verify_checkpoint,
    write_manifest,
)
from stoke_tpu.telemetry import read_step_events

pytestmark = pytest.mark.resilience

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "scripts"))
import run_resilient as run_resilient_mod  # noqa: E402

IN, OUT = 8, 4


def _make_stoke(tmp_path, *, resilience_over=None, telemetry=False,
                with_resilience=True, tag="run"):
    """Linear-regression overfit scenario on the 8-device CPU mesh."""
    configs = []
    if telemetry:
        configs.append(TelemetryConfig(
            output_dir=str(tmp_path / tag / "telemetry"),
            log_every_n_steps=1,
            sample_device_time=False,
            prometheus=False,
        ))
    if with_resilience:
        configs.append(ResilienceConfig(
            save_path=str(tmp_path / tag / "ckpts"),
            exit_on_preempt=False,
            **(resilience_over or {}),
        ))
    return Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((IN, OUT), np.float32) * 0.1},
        batch_size_per_device=4,
        configs=configs,
        verbose=False,
    )


def _batches(n, seed=7, batch=32):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(IN, OUT)).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.normal(size=(batch, IN)).astype(np.float32)
        out.append((x, (x @ W).astype(np.float32)))
    return out


def _fake_tag(root, step, name="emergency", payload=b"x" * 256):
    """A minimal on-disk checkpoint tag (meta.json + one payload file)."""
    tag_dir = os.path.join(root, f"stoke-{name}-backward-step-{step}")
    os.makedirs(tag_dir, exist_ok=True)
    with open(os.path.join(tag_dir, "meta.json"), "w") as f:
        json.dump({"format": "consolidated", "name": name}, f)
    with open(os.path.join(tag_dir, "state.bin"), "wb") as f:
        f.write(payload)
    return tag_dir


# --------------------------------------------------------------------------- #
# exit-code classification + restart backoff (jax-free supervisor primitives)
# --------------------------------------------------------------------------- #


def test_classify_exit():
    assert classify_exit(0) == "ok"
    assert classify_exit(113) == "resumable"   # health watchdog
    assert classify_exit(114) == "resumable"   # preemption drain
    assert classify_exit(-9) == "resumable"    # SIGKILL'd (preempted VM)
    assert classify_exit(-15) == "resumable"   # SIGTERM'd before handlers
    assert classify_exit(1) == "fatal"         # deterministic bug: stop
    assert classify_exit(2) == "fatal"
    assert classify_exit(7, extra_resumable=(7,)) == "resumable"
    # shell convention 128+signum: what wrapper launchers (including
    # run_resilient's own main()) report for a signal death
    assert classify_exit(137) == "resumable"   # 128+SIGKILL via a wrapper
    assert classify_exit(143) == "resumable"   # 128+SIGTERM via a wrapper
    assert classify_exit(128) == "fatal"       # not a signal death
    assert classify_exit(200) == "fatal"       # past the signal range


def test_backoff_schedule_and_budget():
    b = RestartBackoff(base_s=1.0, factor=2.0, max_s=5.0, jitter_frac=0.0,
                       max_restarts=4)
    assert [b.next_delay() for _ in range(4)] == [1.0, 2.0, 4.0, 5.0]
    assert b.exhausted
    assert b.next_delay() is None  # budget spent: no more restarts


def test_backoff_jitter_bounds_deterministic():
    b = RestartBackoff(base_s=2.0, factor=2.0, max_s=100.0, jitter_frac=0.5,
                       max_restarts=6, rng=random.Random(0))
    base = 2.0
    for _ in range(6):
        d = b.next_delay()
        # additive-uniform jitter in [0, 0.5 * delay]
        assert base <= d <= base * 1.5
        base = min(100.0, base * 2.0)
    # same seed -> same schedule (the determinism the tests rely on)
    b2 = RestartBackoff(base_s=2.0, factor=2.0, max_s=100.0, jitter_frac=0.5,
                        max_restarts=6, rng=random.Random(0))
    b3 = RestartBackoff(base_s=2.0, factor=2.0, max_s=100.0, jitter_frac=0.5,
                        max_restarts=6, rng=random.Random(0))
    assert [b2.next_delay() for _ in range(6)] == \
        [b3.next_delay() for _ in range(6)]


def test_backoff_rejects_bad_params():
    with pytest.raises(ValueError):
        RestartBackoff(base_s=-1.0)
    with pytest.raises(ValueError):
        RestartBackoff(factor=0.5)


def test_run_resilient_restarts_then_succeeds(tmp_path):
    """Injected clock + runner: 114 -> 113 -> 0 restarts twice with the
    exponential schedule, threads the attempt number through the env, and
    records one JSONL line per attempt — no subprocesses, no real sleeps."""
    codes = iter([114, 113, 0])
    envs = []

    def fake_run(argv, env):
        envs.append(dict(env))
        return next(codes)

    sleeps = []
    rec_path = str(tmp_path / "restarts.jsonl")
    out = run_resilient_mod.run_resilient(
        ["worker"], max_restarts=5, base_s=1.0, jitter_frac=0.0, seed=0,
        record_path=rec_path, run=fake_run, sleep=sleeps.append,
    )
    assert out["ok"] and out["attempts"] == 3 and out["restarts"] == 2
    assert sleeps == [1.0, 2.0]
    assert [e["STOKE_RESTART_ATTEMPT"] for e in envs] == ["0", "1", "2"]
    with open(rec_path) as f:
        records = [json.loads(ln) for ln in f]
    assert [r["exit_code"] for r in records] == [114, 113, 0]
    assert [r["class"] for r in records] == ["resumable", "resumable", "ok"]


def test_run_resilient_fatal_stops_immediately():
    calls = []

    def fake_run(argv, env):
        calls.append(1)
        return 1  # generic crash: a deterministic bug

    out = run_resilient_mod.run_resilient(
        ["worker"], max_restarts=5, run=fake_run,
        sleep=lambda s: pytest.fail("fatal exit must not back off"),
    )
    assert not out["ok"] and out["fatal"] and out["exit_code"] == 1
    assert len(calls) == 1  # restarting a deterministic bug burns budget


def test_run_resilient_budget_exhaustion():
    out = run_resilient_mod.run_resilient(
        ["worker"], max_restarts=2, base_s=0.0, jitter_frac=0.0,
        run=lambda argv, env: 114, sleep=lambda s: None,
    )
    assert not out["ok"] and out["exhausted"] and out["attempts"] == 3


def test_supervise_exit_codes_in_sync():
    """scripts/_supervise.py keeps jax-free copies of the exit codes; they
    must never drift from the authority in stoke_tpu/resilience.py."""
    import _supervise

    assert _supervise.PREEMPTION_EXIT_CODE == resilience.PREEMPTION_EXIT_CODE
    assert (_supervise.HEALTH_WATCHDOG_EXIT_CODE
            == resilience._WATCHDOG_EXIT_CODE)


def test_tag_regex_in_sync_with_io_ops():
    # resilience duplicates the tag regex to stay importable without jax;
    # io_ops._TAG_RE is the authority
    assert resilience._TAG_RE.pattern == io_ops._TAG_RE.pattern


# --------------------------------------------------------------------------- #
# manifests, verification, quarantine, discovery
# --------------------------------------------------------------------------- #


def test_manifest_roundtrip_and_verify(tmp_path):
    tag = _fake_tag(str(tmp_path), 10)
    ok, reason = verify_checkpoint(tag)
    assert ok and "no manifest" in reason  # legacy tags stay loadable
    assert not verify_checkpoint(tag, require_manifest=True)[0]
    write_manifest(tag, extra={"backward_step": 10})
    ok, reason = verify_checkpoint(tag)
    assert ok and reason == "ok"
    with open(os.path.join(tag, resilience.MANIFEST_NAME)) as f:
        manifest = json.load(f)
    assert set(manifest["files"]) == {"meta.json", "state.bin"}
    assert manifest["backward_step"] == 10


def test_kill_during_metadata_write_leaves_unloadable_tag(
    tmp_path, monkeypatch
):
    """extras.pkl is written BEFORE meta.json (the tag's loadable marker):
    a hard kill landing between the two must leave a tag verify_checkpoint
    rejects as a partial write — the reverse order would let resume
    silently restore WITHOUT the rng/EMA/EF extras and break the
    bit-identical-resume guarantee."""
    from stoke_tpu.configs import CheckpointConfig

    def boom(*a, **kw):
        raise OSError("simulated hard kill mid-extras-write")

    monkeypatch.setattr(io_ops.pickle, "dump", boom)
    with pytest.raises(OSError, match="simulated hard kill"):
        io_ops.save_checkpoint(
            str(tmp_path),
            "emerg",
            variables={"w": np.zeros((2, 2), np.float32)},
            opt_state={},
            scaler_state={},
            counters={"optimizer_step": 3, "backward_step": 3},
            status={},
            extras={"resilience": {"optimizer_step": 3}},
            config=CheckpointConfig(),
            backward_step=3,
            manifest=True,
        )
    tags = [d for d in os.listdir(tmp_path) if "emerg" in d]
    assert len(tags) == 1
    ok, reason = verify_checkpoint(os.path.join(str(tmp_path), tags[0]))
    assert not ok and "meta.json" in reason


def test_verify_catches_corruption_truncation_and_loss(tmp_path):
    tag = _fake_tag(str(tmp_path), 4)
    write_manifest(tag)
    # bit rot: same size, different bytes
    assert corrupt_checkpoint(tag) is not None
    ok, reason = verify_checkpoint(tag)
    assert not ok and "digest mismatch" in reason
    # truncation
    tag2 = _fake_tag(str(tmp_path), 6)
    write_manifest(tag2)
    with open(os.path.join(tag2, "state.bin"), "wb") as f:
        f.write(b"x")
    assert "size mismatch" in verify_checkpoint(tag2)[1]
    # a listed file vanished
    tag3 = _fake_tag(str(tmp_path), 8)
    write_manifest(tag3)
    os.remove(os.path.join(tag3, "state.bin"))
    assert "missing file" in verify_checkpoint(tag3)[1]
    # meta-less dir = partial write by construction
    tag4 = os.path.join(str(tmp_path), "stoke-emergency-backward-step-9")
    os.makedirs(tag4)
    assert "partial" in verify_checkpoint(tag4)[1]


def test_quarantine_moves_never_deletes(tmp_path):
    tag = _fake_tag(str(tmp_path), 3, payload=b"evidence")
    dest = quarantine_checkpoint(tag, reason="digest mismatch")
    assert dest is not None and not os.path.exists(tag)
    assert os.path.dirname(dest) == str(tmp_path / "quarantine")
    # the bytes are evidence: payload preserved, reason recorded
    with open(os.path.join(dest, "state.bin"), "rb") as f:
        assert f.read() == b"evidence"
    with open(os.path.join(dest, "QUARANTINED.json")) as f:
        assert json.load(f)["reason"] == "digest mismatch"


def test_discovery_falls_back_past_corrupt_latest(tmp_path):
    root = str(tmp_path)
    for step in (2, 4, 6):
        write_manifest(_fake_tag(root, step))
    newest = os.path.join(root, "stoke-emergency-backward-step-6")
    corrupt_checkpoint(newest)
    seen = []
    cand = find_latest_valid_checkpoint(
        [(root, "emergency")],
        on_quarantine=lambda t, d, r: seen.append((t, d, r)),
    )
    assert cand is not None and cand["step"] == 4
    assert not os.path.exists(newest)  # quarantined, not deleted
    assert len(os.listdir(os.path.join(root, "quarantine"))) == 1
    assert len(seen) == 1 and "digest mismatch" in seen[0][2]
    # quarantine=False leaves the corrupt tag in place and still skips it
    corrupt_checkpoint(os.path.join(root, "stoke-emergency-backward-step-4"))
    cand2 = find_latest_valid_checkpoint(
        [(root, "emergency")], quarantine=False
    )
    assert cand2["step"] == 2
    assert os.path.exists(
        os.path.join(root, "stoke-emergency-backward-step-4")
    )


def test_discovery_scopes_by_name_and_handles_empty(tmp_path):
    root = str(tmp_path)
    write_manifest(_fake_tag(root, 5, name="other"))
    assert find_latest_valid_checkpoint([(root, "emergency")]) is None
    assert find_latest_valid_checkpoint([(root, None)])["step"] == 5
    assert find_latest_valid_checkpoint(
        [(str(tmp_path / "missing"), None)]
    ) is None


# --------------------------------------------------------------------------- #
# chaos harness
# --------------------------------------------------------------------------- #


def test_parse_chaos_grammar():
    assert parse_chaos(None) is None
    assert parse_chaos("  ") is None
    spec = parse_chaos("kill_at_step=5,kill_mode=sigkill")
    assert spec.kill_at_step == 5 and spec.kill_mode == "sigkill"
    spec = parse_chaos("corrupt_save=2, wedge_at_step=3, wedge_s=0.5")
    assert (spec.corrupt_save, spec.wedge_at_step, spec.wedge_s) == \
        (2, 3, 0.5)
    # a typo'd plan silently injecting nothing would fake a green test
    with pytest.raises(ValueError, match="unknown chaos key"):
        parse_chaos("kil_at_step=5")
    with pytest.raises(ValueError, match="kill_mode"):
        parse_chaos("kill_mode=nuke")
    with pytest.raises(ValueError, match="integer"):
        parse_chaos("kill_at_step=soon")
    with pytest.raises(ValueError, match="key=value"):
        parse_chaos("chaos!")
    # an armed injector that can never fire (corrupt_save is 1-based,
    # kill/wedge fire on steps >= 1) is the same fake-green hazard
    with pytest.raises(ValueError, match=">= 1"):
        parse_chaos("corrupt_save=0")
    with pytest.raises(ValueError, match=">= 1"):
        parse_chaos("kill_at_step=0")
    with pytest.raises(ValueError, match=">= 1"):
        parse_chaos("wedge_at_step=-3")
    with pytest.raises(ValueError, match="wedge_s"):
        parse_chaos("wedge_at_step=3,wedge_s=-1")
    # wedge_s=0 stays legal: fires without stalling (how these tests
    # exercise injector logic without real sleeps)
    assert parse_chaos("wedge_at_step=3,wedge_s=0").wedge_s == 0.0


def test_injector_kill_window_and_resume_anchor():
    inj = ChaosInjector(parse_chaos("kill_at_step=5,kill_mode=exception"))
    inj.on_step(3)  # before the window: nothing
    with pytest.raises(ChaosError):
        inj.on_step(6, window=4)  # 2 < 5 <= 6: K inside the window
    # a resumed process whose counter starts AT k never re-fires — the
    # supervised restart must make forward progress
    inj2 = ChaosInjector(parse_chaos("kill_at_step=5,kill_mode=exception"))
    inj2.note_resumed(5)
    inj2.on_step(6)
    inj2.on_step(7)


def test_injector_wedge_never_refires_after_resume():
    """A resumed process that restored step >= K must not re-arm the wedge
    (the per-process _wedged flag resets each restart) — otherwise every
    supervised attempt of a wedge-chaos run wedges again and the restart
    budget burns out without forward progress."""
    spec = "wedge_at_step=2,wedge_s=0"
    inj = ChaosInjector(parse_chaos(spec))
    inj.on_step(2)
    inj.on_dispatch("train_step")  # this process crossed K: wedges once
    assert inj._wedged
    inj2 = ChaosInjector(parse_chaos(spec))
    inj2.note_resumed(2)  # restored AT K: fired in a previous life
    inj2.on_dispatch("train_step")
    inj2.on_step(3)
    inj2.on_dispatch("train_step")
    assert not inj2._wedged


def test_injector_corrupt_save(tmp_path):
    inj = ChaosInjector(parse_chaos("corrupt_save=2"))
    t1 = _fake_tag(str(tmp_path), 1)
    t2 = _fake_tag(str(tmp_path), 2)
    write_manifest(t1)
    write_manifest(t2)
    inj.note_saved(t1)  # save #1: untouched
    assert verify_checkpoint(t1)[0]
    inj.note_saved(t2)  # save #2: corrupted
    assert not verify_checkpoint(t2)[0]
    assert inj.corrupted


# --------------------------------------------------------------------------- #
# satellite: wait_for_saves reports EVERY failed tag dir
# --------------------------------------------------------------------------- #


def test_wait_for_saves_reports_all_failures():
    first = OSError("disk full")
    io_ops._ASYNC_ERRORS.extend([
        ("/ckpts/tag-a", first),
        ("/ckpts/tag-b", ValueError("serialization failed")),
    ])
    try:
        with pytest.raises(RuntimeError) as ei:
            io_ops.wait_for_saves()
        msg = str(ei.value)
        # the full casualty list, not "first (+1 more)"
        assert "/ckpts/tag-a" in msg and "/ckpts/tag-b" in msg
        assert "disk full" in msg and "serialization failed" in msg
        assert ei.value.__cause__ is first
        assert not io_ops._ASYNC_ERRORS  # cleared: no double-raise later
    finally:
        io_ops._ASYNC_ERRORS.clear()


# --------------------------------------------------------------------------- #
# status rules
# --------------------------------------------------------------------------- #


def _status(configs, **kw):
    return StokeStatus(batch_size_per_device=4, configs=configs, **kw)


def test_status_validates_resilience(tmp_path):
    root = str(tmp_path / "ckpts")
    with pytest.raises(StokeValidationError, match="1..255"):
        _status([ResilienceConfig(save_path=root, exit_code=0)])
    with pytest.raises(StokeValidationError, match="collides"):
        _status([ResilienceConfig(save_path=root, exit_code=113)])
    with pytest.raises(StokeValidationError, match="preempt_signals"):
        _status([ResilienceConfig(save_path=root, preempt_signals=())])
    with pytest.raises(StokeValidationError, match="unknown"):
        _status([ResilienceConfig(save_path=root,
                                  preempt_signals=("SIGBOGUS",))])
    with pytest.raises(StokeValidationError, match="max_to_keep"):
        _status([ResilienceConfig(save_path=root, max_to_keep=0)])
    with pytest.raises(StokeValidationError, match="chaos"):
        _status([ResilienceConfig(save_path=root, chaos="kil_at=3")])
    # valid combination passes
    _status([ResilienceConfig(save_path=root)])


def test_status_rejects_typod_chaos_env(tmp_path, monkeypatch):
    monkeypatch.setenv(resilience.CHAOS_ENV, "kill_at=3")
    with pytest.raises(StokeValidationError, match="chaos"):
        _status([ResilienceConfig(save_path=str(tmp_path / "c"))])
    # the config field overrides (and validates instead of) the env
    monkeypatch.setenv(resilience.CHAOS_ENV, "also=bogus")
    _status([ResilienceConfig(save_path=str(tmp_path / "c"),
                              chaos="kill_at_step=3")])


def test_resilience_config_yaml_buildable(tmp_path):
    from stoke_tpu.utils import stoke_kwargs_from_config

    kwargs = stoke_kwargs_from_config({
        "batch_size_per_device": 4,
        "configs": {
            "ResilienceConfig": {
                "save_path": str(tmp_path / "ckpts"),
                "exit_code": 115,
                "max_to_keep": 5,
            },
        },
    })
    by_type = {type(c).__name__: c for c in kwargs["configs"]}
    cfg = by_type["ResilienceConfig"]
    assert cfg.exit_code == 115 and cfg.max_to_keep == 5


# --------------------------------------------------------------------------- #
# default-OFF identity (acceptance: bit-identical step programs)
# --------------------------------------------------------------------------- #


def test_resilience_off_is_bit_identical_and_on_adds_no_dispatches(
    tmp_path, devices
):
    """The whole subsystem is host-side: the engine dispatch count AND the
    lowered step-program HLO are identical with the config absent vs
    present (same technique as the PR 3/4/5 acceptance)."""
    import jax

    s_off = _make_stoke(tmp_path, with_resilience=False, tag="off")
    s_on = _make_stoke(tmp_path, tag="on")
    batches = _batches(4)
    for s in (s_off, s_on):
        for x, y in batches:
            s.train_step(x, (y,))
    assert s_on.dispatch_count == s_off.dispatch_count
    np.testing.assert_array_equal(
        np.asarray(s_on.params["w"]), np.asarray(s_off.params["w"])
    )
    x, y = batches[0]

    def fused_hlo(s):
        from stoke_tpu.engine import DeferredOutput, is_deferred

        margs = s._place_batch((x,))
        sentinel = DeferredOutput(None, -1)
        flat, treedef = jax.tree_util.tree_flatten(
            ((sentinel, y), {}), is_leaf=is_deferred
        )
        arrays = s._place_batch([l for l in flat if not is_deferred(l)])
        deferred = tuple(
            (i, l._path) for i, l in enumerate(flat) if is_deferred(l)
        )
        fn = s._engine._build_fused(treedef, deferred, True)
        return fn.lower(
            s._variables, s._opt_state, s._grad_buf, s._scaler_state,
            s._comm_state, s._rng, margs, {}, arrays,
        ).as_text()

    assert fused_hlo(s_on) == fused_hlo(s_off)
    s_on.close_telemetry()
    s_off.close_telemetry()


def test_signal_handlers_installed_and_restored(tmp_path):
    prev = signal.getsignal(signal.SIGTERM)
    s = _make_stoke(tmp_path)
    assert signal.getsignal(signal.SIGTERM) is not prev
    s.close_telemetry()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_signal_handlers_overlapping_monitors(tmp_path):
    """Resume-while-old-run-open (telemetry_smoke's own pattern): closing
    the OLDER monitor must not strip the live one's handler, and the final
    close must restore the pre-Stoke handler, not a closed monitor's."""
    prev = signal.getsignal(signal.SIGTERM)
    a = _make_stoke(tmp_path, tag="ovl-a")
    b = _make_stoke(tmp_path, tag="ovl-b")
    assert signal.getsignal(signal.SIGTERM) == b.resilience._on_signal
    a.close_telemetry()
    # B installed over A, so A's close must leave B's handler in place
    assert signal.getsignal(signal.SIGTERM) == b.resilience._on_signal
    b.close_telemetry()
    assert signal.getsignal(signal.SIGTERM) is prev
    # reverse order: the newer monitor closing first hands SIGTERM back to
    # the still-open older one, whose close restores the original
    c = _make_stoke(tmp_path, tag="ovl-c")
    d = _make_stoke(tmp_path, tag="ovl-d")
    d.close_telemetry()
    assert signal.getsignal(signal.SIGTERM) == c.resilience._on_signal
    c.close_telemetry()
    assert signal.getsignal(signal.SIGTERM) is prev


# --------------------------------------------------------------------------- #
# preemption → emergency save → resume (the in-process cycle)
# --------------------------------------------------------------------------- #


def test_preemption_cycle_bit_identical_trajectory(tmp_path, devices):
    """A preempted-and-resumed run must reach a bit-identical final-param
    state vs an uninterrupted one: the emergency extras carry rng/EMA and
    the checkpoint the full optimizer state."""
    n = 6
    batches = _batches(n)
    ref = _make_stoke(tmp_path, tag="ref")
    for x, y in batches:
        ref.train_step(x, (y,))
    ref.close_telemetry()

    run = _make_stoke(tmp_path, tag="pre")
    for x, y in batches[:3]:
        run.train_step(x, (y,))
    run.resilience.request_preemption("test")
    with pytest.raises(PreemptedError) as ei:
        run.train_step(*_pair(batches[3]))
    # the in-flight step FINISHED before the drain: step 4 applied + saved
    assert ei.value.step == 4
    assert run.optimizer_steps == 4
    tag_dir = ei.value.tag_dir
    assert tag_dir and os.path.exists(
        os.path.join(tag_dir, resilience.MANIFEST_NAME)
    )
    assert verify_checkpoint(tag_dir, require_manifest=True)[0]
    summary = run.resilience_summary
    assert summary["preemptions"] == 1 and summary["emergency_saves"] == 1
    run.close_telemetry()

    resumed = _make_stoke(tmp_path, tag="pre")  # same save_path
    assert resumed.resume()
    assert resumed.optimizer_steps == 4
    rz = resumed.resilience_summary
    assert rz["resumed_step"] == 4 and rz["lost_steps"] == 0
    for x, y in batches[4:]:
        resumed.train_step(x, (y,))
    assert resumed.optimizer_steps == n
    np.testing.assert_array_equal(
        np.asarray(resumed.params["w"]), np.asarray(ref.params["w"])
    )
    resumed.close_telemetry()


def _pair(b):
    x, y = b
    return x, (y,)


def test_resume_without_checkpoint_starts_fresh(tmp_path):
    s = _make_stoke(tmp_path, tag="fresh")
    assert not s.resume()
    assert s.optimizer_steps == 0
    s.close_telemetry()


def test_corrupt_latest_quarantined_resume_falls_back(tmp_path, devices):
    """The corrupted-latest acceptance path: resume() quarantines the bad
    newest tag, restores the previous valid one, and charges the gap to
    lost_steps."""
    run = _make_stoke(tmp_path, tag="qr")
    batches = _batches(4)
    root = run.resilience.cfg.save_path
    for x, y in batches[:2]:
        run.train_step(x, (y,))
    run.save(root, name="emergency")          # valid tag at backward step 2
    for x, y in batches[2:]:
        run.train_step(x, (y,))
    newest = run.save(root, name="emergency")  # newest tag at step 4
    run.close_telemetry()
    assert corrupt_checkpoint(newest) is not None

    resumed = _make_stoke(tmp_path, tag="qr")
    assert resumed.resume()
    assert resumed.optimizer_steps == 2  # fell back past the corrupt tag
    rz = resumed.resilience_summary
    assert rz["quarantined_ckpts"] == 1
    assert rz["resumed_step"] == 2 and rz["lost_steps"] == 2
    assert not os.path.exists(newest)
    qdir = os.path.join(root, resilience.QUARANTINE_DIRNAME)
    assert len(os.listdir(qdir)) == 1
    resumed.close_telemetry()


def test_emergency_prune_skips_inflight_tags(tmp_path, devices):
    """Satellite regression: the emergency save's prune must never touch a
    tag an async save is still writing — a meta-less in-flight dir looks
    exactly like a crashed leftover, and deleting it mid-write would
    corrupt the concurrent checkpoint the drain is about to finish."""
    run = _make_stoke(tmp_path, resilience_over={"max_to_keep": 1},
                      tag="race")
    root = run.resilience.cfg.save_path
    os.makedirs(root, exist_ok=True)
    # simulate the race: an async save claimed its (still meta-less) tag
    # dir but has not finished when the preemption save prunes
    inflight = os.path.join(root, "stoke-emergency-backward-step-99")
    os.makedirs(inflight)
    io_ops._INFLIGHT_TAGS.add(inflight)
    # and a crashed leftover that is NOT in flight — prune must remove it
    leftover = os.path.join(root, "stoke-emergency-backward-step-98")
    os.makedirs(leftover)
    try:
        x, y = _batches(1)[0]
        run.train_step(x, (y,))
        run.resilience.request_preemption("test")
        with pytest.raises(PreemptedError):
            run.train_step(x, (y,))
        assert os.path.exists(inflight)       # guarded: still being written
        assert not os.path.exists(leftover)   # stale: pruned as always
        assert run.resilience_summary["emergency_saves"] == 1
    finally:
        io_ops._INFLIGHT_TAGS.discard(inflight)
        run.close_telemetry()


def test_chaos_exception_mode_via_facade(tmp_path, devices):
    run = _make_stoke(
        tmp_path, resilience_over={"chaos": "kill_at_step=2,"
                                   "kill_mode=exception"}, tag="chaos",
    )
    batches = _batches(3)
    run.train_step(*_pair(batches[0]))
    with pytest.raises(ChaosError):
        run.train_step(*_pair(batches[1]))
    run.close_telemetry()


def test_chaos_corrupt_save_via_facade(tmp_path, devices):
    run = _make_stoke(
        tmp_path, resilience_over={"chaos": "corrupt_save=1"}, tag="cor",
    )
    x, y = _batches(1)[0]
    run.train_step(x, (y,))
    tag = run.save(run.resilience.cfg.save_path, name="emergency")
    assert not verify_checkpoint(tag)[0]
    run.close_telemetry()


# --------------------------------------------------------------------------- #
# telemetry surface
# --------------------------------------------------------------------------- #


def test_resilience_jsonl_fields(tmp_path, devices):
    s = _make_stoke(tmp_path, telemetry=True, tag="tel")
    for x, y in _batches(2):
        s.train_step(x, (y,))
    s.close_telemetry()
    records = read_step_events(
        str(tmp_path / "tel" / "telemetry" / "steps.jsonl")
    )
    rec = records[-1]
    assert rec["resilience/preemptions"] == 0.0
    assert rec["resilience/emergency_saves"] == 0.0
    assert rec["resilience/quarantined"] == 0.0
    assert rec["resilience/restarts"] == 0.0
    assert rec["resilience/resumed_step"] is None
    assert rec["resilience/lost_steps"] is None
    # without the config the keys never appear (PR 1 registry contract)
    s_off = _make_stoke(tmp_path, telemetry=True, with_resilience=False,
                        tag="tel_off")
    for x, y in _batches(2):
        s_off.train_step(x, (y,))
    s_off.close_telemetry()
    rec_off = read_step_events(
        str(tmp_path / "tel_off" / "telemetry" / "steps.jsonl")
    )[-1]
    assert "resilience/preemptions" not in rec_off


def test_restart_attempt_env_surfaces(tmp_path, monkeypatch, devices):
    monkeypatch.setenv(resilience.RESTART_ATTEMPT_ENV, "3")
    s = _make_stoke(tmp_path, tag="att")
    assert s.resilience.restarts == 3
    assert s.resilience_summary["restarts"] == 3
    s.close_telemetry()


# --------------------------------------------------------------------------- #
# end-to-end acceptance: chaos kill + supervised restart, bit-identical
# --------------------------------------------------------------------------- #


def test_chaos_kill_supervised_restart_bit_identical(tmp_path):
    """The full detect→save→restart→resume loop as real processes: a
    worker SIGTERM'd at step 3 by the injector drains, saves, and exits
    114; run_resilient restarts it; the resumed attempt finishes and the
    final params + overlapping loss trajectory are bit-identical to an
    uninterrupted reference run."""
    worker = os.path.join(_REPO, "tests", "_resilience_worker.py")
    supervisor = os.path.join(_REPO, "scripts", "run_resilient.py")
    steps = 6

    def run_worker(root, chaos=None, supervised=False):
        env = {k: v for k, v in os.environ.items() if k != "STOKE_CHAOS"}
        env["PYTHONPATH"] = _REPO
        env.setdefault("JAX_PLATFORMS", "cpu")
        if chaos:
            env["STOKE_CHAOS"] = chaos
        worker_cmd = [sys.executable, worker, "--root", root,
                      "--steps", str(steps), "--resilience"]
        if supervised:
            cmd = [sys.executable, supervisor, "--max-restarts", "3",
                   "--base-s", "0.01", "--jitter-frac", "0",
                   "--record", os.path.join(root, "restarts.jsonl"),
                   "--"] + worker_cmd
        else:
            cmd = worker_cmd
        return subprocess.run(
            cmd, env=env, cwd=_REPO, timeout=240,
            capture_output=True, text=True,
        )

    ref_root = str(tmp_path / "ref")
    chaos_root = str(tmp_path / "chaos")
    os.makedirs(ref_root)
    os.makedirs(chaos_root)
    ref = run_worker(ref_root)
    assert ref.returncode == 0, ref.stderr
    out = run_worker(chaos_root, chaos="kill_at_step=3,kill_mode=sigterm",
                     supervised=True)
    assert out.returncode == 0, out.stderr

    # supervisor record: attempt 0 preempted (114, resumable), attempt 1 ok
    with open(os.path.join(chaos_root, "restarts.jsonl")) as f:
        records = [json.loads(ln) for ln in f]
    assert [r["exit_code"] for r in records] == [114, 0]
    assert records[0]["class"] == "resumable"
    summary = json.loads(
        [ln for ln in out.stdout.splitlines() if "run_resilient" in ln][-1]
    )["run_resilient"]
    assert summary["ok"] and summary["restarts"] == 1

    # the emergency checkpoint exists with its manifest
    ckpts = resilience.list_checkpoints(
        os.path.join(chaos_root, "ckpts"), "emergency"
    )
    assert ckpts and ckpts[0]["step"] == 3
    assert verify_checkpoint(ckpts[0]["tag_dir"], require_manifest=True)[0]

    # bit-identical final params vs the uninterrupted reference
    w_ref = np.load(os.path.join(ref_root, "final_w.npy"))
    w_chaos = np.load(os.path.join(chaos_root, "final_w.npy"))
    np.testing.assert_array_equal(w_chaos, w_ref)

    # and a bit-identical loss trajectory on every step both runs logged
    # (the killed step's line is missing by construction: the update was
    # applied and saved, but the worker exited before logging it)
    def traj(root):
        with open(os.path.join(root, "trajectory.jsonl")) as f:
            return {r["step"]: r["loss"] for r in map(json.loads, f)}

    t_ref, t_chaos = traj(ref_root), traj(chaos_root)
    assert set(t_chaos) == {1, 2, 4, 5, 6}
    for step, loss in t_chaos.items():
        assert loss == t_ref[step], f"step {step} diverged"
    # the resumed steps ran on attempt 1
    with open(os.path.join(chaos_root, "trajectory.jsonl")) as f:
        by_attempt = {}
        for r in map(json.loads, f):
            by_attempt.setdefault(r["attempt"], []).append(r["step"])
    assert by_attempt == {0: [1, 2], 1: [4, 5, 6]}

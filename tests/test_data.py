"""Data layer tests: BucketedDistributedSampler invariants (the index math of
reference data.py:380-498, property-tested per SURVEY.md §7 hard part #5) and
StokeDataLoader placement."""

import itertools

import jax
import numpy as np
import pytest

from stoke_tpu.data import BucketedDistributedSampler, StokeDataLoader


class SizedDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.float32([i, i + 0.5])


def make_sampler(n=1000, buckets=4, batch=8, replicas=2, rank=0, **kw):
    return BucketedDistributedSampler(
        SizedDataset(n),
        buckets=buckets,
        batch_size=batch,
        sorted_idx=list(range(n)),
        num_replicas=replicas,
        rank=rank,
        **kw,
    )


def test_len_matches_iteration():
    s = make_sampler()
    idx = list(iter(s))
    assert len(idx) == len(s)  # invariant at reference data.py:447


def test_all_replicas_cover_slices_disjointly():
    """Within an epoch, replicas' index sets are disjoint and equal-sized."""
    per_rank = []
    for r in range(2):
        s = make_sampler(rank=r, shuffle=False, drop_last=True)
        per_rank.append(list(iter(s)))
    assert len(per_rank[0]) == len(per_rank[1])
    assert set(per_rank[0]).isdisjoint(set(per_rank[1]))


def test_batches_stay_within_buckets():
    """Every per-replica batch must draw from ONE bucket (the whole point:
    similar-length samples batch together)."""
    n, buckets, batch = 1024, 4, 8
    s = make_sampler(n=n, buckets=buckets, batch=batch, replicas=2, rank=0, drop_last=True)
    idx = list(iter(s))
    bucket_of = lambda i: i * buckets // n  # sorted_idx == range → contiguous buckets
    for b in range(0, len(idx), batch):
        bs = {bucket_of(i) for i in idx[b : b + batch]}
        assert len(bs) == 1, f"batch {b // batch} mixes buckets {bs}"


def test_padding_short_buckets():
    """n chosen so buckets don't divide evenly: short final slices must be
    padded to full batch size (reference data.py:450-498)."""
    s = make_sampler(n=1010, buckets=3, batch=8, replicas=2, shuffle=True)
    idx = list(iter(s))
    assert len(idx) == len(s)
    assert len(idx) % 8 == 0  # whole batches only


def test_epoch_reshuffle_deterministic():
    s = make_sampler(shuffle=True, seed=11)
    s.set_epoch(0)
    a0 = list(iter(s))
    s.set_epoch(0)
    assert list(iter(s)) == a0  # same epoch → same order
    s.set_epoch(1)
    a1 = list(iter(s))
    assert a1 != a0  # new epoch → reshuffled
    assert sorted(set(a1)) == sorted(set(a1))


def test_replicas_agree_on_slices():
    """The union of all replicas' strided sub-batches per slice must be the
    slice itself: checked by summing coverage across replicas."""
    replicas = 4
    all_idx = []
    for r in range(replicas):
        s = make_sampler(n=1600, buckets=2, batch=4, replicas=replicas, rank=r,
                         shuffle=True, seed=3, drop_last=True)
        all_idx.append(list(iter(s)))
    lengths = {len(a) for a in all_idx}
    assert len(lengths) == 1
    combined = list(itertools.chain(*all_idx))
    # with drop_last each kept index appears exactly once across replicas
    assert len(combined) == len(set(combined))


@pytest.mark.parametrize("n", [1000, 1024, 1111])
@pytest.mark.parametrize("buckets", [2, 5])
@pytest.mark.parametrize("replicas", [1, 2, 4])
@pytest.mark.parametrize("drop_last", [False, True])
def test_sampler_invariants_grid(n, buckets, replicas, drop_last):
    """Grid over sampler parameters: every epoch yields exactly len(self)
    indices in whole batches, and replicas stay disjoint with drop_last."""
    batch = 8
    per_rank = []
    for r in range(replicas):
        s = make_sampler(n=n, buckets=buckets, batch=batch, replicas=replicas,
                         rank=r, shuffle=True, seed=7, drop_last=drop_last)
        idx = list(iter(s))
        assert len(idx) == len(s)
        assert len(idx) % batch == 0
        assert all(0 <= i < n for i in idx)
        per_rank.append(idx)
    assert len({len(a) for a in per_rank}) == 1
    if drop_last:
        combined = list(itertools.chain(*per_rank))
        assert len(combined) == len(set(combined))


def test_bucket_overlap_residuals():
    base = make_sampler(n=1100, buckets=2, batch=8, replicas=2, drop_last=True)
    overlap = make_sampler(
        n=1100, buckets=2, batch=8, replicas=2, drop_last=True, allow_bucket_overlap=True
    )
    assert len(overlap) >= len(base)


def test_validation_gates():
    # bucket smaller than one slice
    with pytest.raises(ValueError):
        make_sampler(n=120, buckets=8, batch=8, replicas=4)
    # fewer than 2 slices per bucket
    with pytest.raises(ValueError):
        make_sampler(n=200, buckets=1, batch=100, replicas=2)
    # bad rank
    with pytest.raises(ValueError):
        make_sampler(rank=5, replicas=2)


# ----------------------------- loader ------------------------------------- #


def test_loader_places_on_device():
    calls = []

    def place(b):
        calls.append(1)
        return jax.tree_util.tree_map(jax.numpy.asarray, b)

    dl = StokeDataLoader(SizedDataset(64), batch_size=16, place_fn=place)
    batches = list(dl)
    assert len(batches) == 4 and len(calls) == 4
    assert isinstance(batches[0], jax.Array)
    assert batches[0].shape == (16, 2)


def test_loader_len_and_epoch_forwarding():
    s = make_sampler(n=1000, buckets=2, batch=16, replicas=1, rank=0)
    dl = StokeDataLoader(SizedDataset(1000), batch_size=16, place_fn=None, sampler=s)
    assert len(dl) > 0
    dl.set_epoch(3)
    assert s.epoch == 3


def test_loader_no_place_passthrough():
    dl = StokeDataLoader(SizedDataset(8), batch_size=4, place_fn=None, place=False)
    b = next(iter(dl))
    assert isinstance(b, np.ndarray)


@pytest.mark.slow
def test_loader_torch_workers():
    """Multi-worker host loading through the torch path still yields numpy
    batches in order."""
    dl = StokeDataLoader(
        SizedDataset(64), batch_size=16, place_fn=None, num_workers=2,
        shuffle=False,
    )
    batches = list(dl)
    assert len(batches) == 4
    assert isinstance(batches[0], np.ndarray)
    np.testing.assert_allclose(batches[0][0], [0.0, 0.5])


def test_loader_prefetch_order_preserved():
    dl = StokeDataLoader(
        SizedDataset(64), batch_size=8, place_fn=lambda b: b, prefetch=3, shuffle=False
    )
    firsts = [b[0][0] for b in dl]
    assert firsts == sorted(firsts)


def test_fallback_loader_threaded_matches_serial():
    """Torch-free threaded path (VERDICT r3 missing #3): num_workers>0
    assembles batches in a thread pool but yields them in exactly the
    serial order, including the drop_last tail rule."""
    from stoke_tpu.data import _FallbackLoader

    ds = SizedDataset(50)
    serial = list(_FallbackLoader(ds, batch_size=8, drop_last=False))
    threaded = list(
        _FallbackLoader(ds, batch_size=8, drop_last=False, num_workers=3)
    )
    assert len(threaded) == len(serial) == 7
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a, b)
    # shuffle determinism: same seed -> same order, serial or threaded
    s1 = list(_FallbackLoader(ds, batch_size=8, shuffle=True, seed=3))
    s2 = list(_FallbackLoader(ds, batch_size=8, shuffle=True, seed=3,
                              num_workers=2))
    for a, b in zip(s1, s2):
        np.testing.assert_array_equal(a, b)


def test_fallback_loader_threaded_abandon_midway():
    """Abandoning the iterator mid-epoch must not hang or leak workers."""
    from stoke_tpu.data import _FallbackLoader

    dl = _FallbackLoader(SizedDataset(256), batch_size=4, num_workers=2)
    it = iter(dl)
    for _ in range(3):
        next(it)
    it.close()  # generator close runs the finally/cancel path


def test_fallback_loader_threaded_sampler():
    from stoke_tpu.data import _FallbackLoader

    order = [5, 1, 9, 3]
    dl = _FallbackLoader(
        SizedDataset(16), batch_size=2, sampler=order, num_workers=2
    )
    batches = list(dl)
    np.testing.assert_array_equal(batches[0][:, 0], [5.0, 1.0])
    np.testing.assert_array_equal(batches[1][:, 0], [9.0, 3.0])

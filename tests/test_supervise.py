"""Tests for scripts/_supervise.py — the tunnel-supervisor watchdogs.

ADVICE r4: a worker that wedges after writing a PARTIAL line (no trailing
newline) must still trip the idle watchdog; a blocking readline() after
select() would stall the supervisor inside the read and disable both
watchdogs.
"""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(
    __file__.rsplit("/tests/", 1)[0], "scripts"))
import _supervise  # noqa: E402
from _supervise import supervise  # noqa: E402


@pytest.fixture(autouse=True)
def skip_device_probe(monkeypatch):
    """The relay/watchdog logic under test does not need the real jax
    device probe — and the probe subprocess would dial the remote TPU
    tunnel when run outside the repo's pinned env (PYTHONPATH override),
    hanging both tests for 120s each on a wedged relay."""

    class _Probe:
        returncode = 0
        stderr = ""

    monkeypatch.setattr(
        _supervise.subprocess, "run", lambda *a, **k: _Probe()
    )


def test_idle_watchdog_fires_on_partial_line_hang(tmp_path, capsys):
    worker = tmp_path / "wedge.py"
    worker.write_text(
        "import sys, time\n"
        "sys.stdout.write('partial-no-newline')\n"
        "sys.stdout.flush()\n"
        "time.sleep(300)\n"
    )
    t0 = time.time()
    rc = supervise(str(worker), [], watchdog_seconds=240, idle_seconds=5)
    elapsed = time.time() - t0
    assert rc == 1
    # the idle watchdog (5s), not the absolute backstop (240s), fired
    assert elapsed < 120, elapsed
    out = capsys.readouterr().out
    assert "partial-no-newline" in out
    assert "no output for 5s" in out


def test_idle_watchdog_fires_after_stdout_eof(tmp_path, capsys):
    """A worker that CLOSES stdout and keeps computing must not busy-spin
    the supervisor (select() reports an EOF fd ready forever); the idle
    watchdog still fires on schedule."""
    worker = tmp_path / "eof.py"
    worker.write_text(
        "import os, time\n"
        "print('about to close stdout', flush=True)\n"
        "os.close(1)\n"
        "time.sleep(300)\n"
    )
    t0 = time.time()
    rc = supervise(str(worker), [], watchdog_seconds=240, idle_seconds=5)
    elapsed = time.time() - t0
    assert rc == 1
    assert elapsed < 120, elapsed
    out = capsys.readouterr().out
    assert "about to close stdout" in out
    assert "no output for 5s" in out


def test_supervise_relays_output_and_exit_code(tmp_path, capsys):
    worker = tmp_path / "ok.py"
    worker.write_text(
        "import json\n"
        "print(json.dumps({'phase': 'done'}))\n"
    )
    rc = supervise(str(worker), [], watchdog_seconds=120, idle_seconds=60)
    assert rc == 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1]) == {"phase": "done"}


def test_watchdog_exit_code_surfaced_with_bundle(tmp_path, capsys):
    """A worker killed by the in-process stoke health watchdog (exit 113)
    produces a structured supervisor line carrying the exit code and the
    bundle paths the worker's flight recorder reported through the
    STOKE_HEALTH_BUNDLE_FILE handshake — not a bare nonzero exit."""
    worker = tmp_path / "wd.py"
    worker.write_text(
        "import json, os, sys\n"
        "print(json.dumps({'phase': 'running'}), flush=True)\n"
        "with open(os.environ['STOKE_HEALTH_BUNDLE_FILE'], 'a') as f:\n"
        "    f.write('/tmp/fake-postmortem-dir\\n')\n"
        "os._exit(113)\n"
    )
    rc = supervise(str(worker), [], watchdog_seconds=120, idle_seconds=60)
    assert rc == _supervise.HEALTH_WATCHDOG_EXIT_CODE == 113
    out = capsys.readouterr().out
    line = json.loads(out.strip().splitlines()[-1])
    assert line["watchdog_exit_code"] == 113
    assert "health watchdog" in line["error"]
    assert line["bundles"] == ["/tmp/fake-postmortem-dir"]


def test_timeout_attaches_bundle_paths(tmp_path, capsys):
    """The absolute-backstop kill attaches any bundles the worker wrote
    before wedging, instead of a bare 'timed out'."""
    worker = tmp_path / "hang.py"
    worker.write_text(
        "import json, os, time\n"
        "print(json.dumps({'phase': 'running'}), flush=True)\n"
        "with open(os.environ['STOKE_HEALTH_BUNDLE_FILE'], 'a') as f:\n"
        "    f.write('/tmp/pre-wedge-bundle\\n')\n"
        "time.sleep(300)\n"
    )
    rc = supervise(str(worker), [], watchdog_seconds=120, idle_seconds=5)
    assert rc == 1
    out = capsys.readouterr().out
    line = json.loads(out.strip().splitlines()[-1])
    assert "no output for 5s" in line["error"]
    assert line["bundles"] == ["/tmp/pre-wedge-bundle"]

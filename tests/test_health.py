"""Training-health-monitor tests (ISSUE 3): on-device sentinels with zero
extra dispatches, detector math, NaN detection + post-mortem bundles, halt
propagation, ring-buffer bounds, the hang watchdog, and the status rules.

All CPU-only and deterministic on the 8-device simulated mesh (conftest).
"""

import json
import os
import time

import jax
import numpy as np
import optax
import pytest

from stoke_tpu import (
    HealthConfig,
    HealthHaltError,
    Stoke,
    StokeOptimizer,
    StokeStatus,
    StokeValidationError,
    TelemetryConfig,
)
from stoke_tpu.telemetry import read_step_events
from stoke_tpu.telemetry.health import (
    SENTINEL_FIELDS,
    SENTINEL_INDEX,
    GradNormSpikeDetector,
    HangWatchdog,
    LossSpikeDetector,
    _RunningStats,
    unpack_sentinels,
)
from stoke_tpu.telemetry.recorder import FlightRecorder

pytestmark = pytest.mark.health

IN, OUT = 8, 4


def _make_stoke(tmp_path, *, health=True, distributed=None, grad_accum=1,
                tag="run", health_over=None, telemetry_over=None):
    """Linear-regression overfit scenario; optional 8-device dp mesh."""
    configs = [TelemetryConfig(
        output_dir=str(tmp_path / tag / "telemetry"),
        log_every_n_steps=1,
        grad_norm=True,
        sample_device_time=False,
        prometheus=False,
        **(telemetry_over or {}),
    )]
    if health:
        configs.append(HealthConfig(
            dump_signals=False, **(health_over or {})
        ))
    return Stoke(
        model=lambda p, x: x @ p["w"],
        optimizer=StokeOptimizer(
            optimizer=optax.sgd, optimizer_kwargs={"learning_rate": 0.05}
        ),
        loss=lambda o, y: ((o - y) ** 2).mean(),
        params={"w": np.ones((IN, OUT), np.float32) * 0.1},
        batch_size_per_device=4,
        grad_accum=grad_accum,
        distributed=distributed,
        configs=configs,
        verbose=False,
    )


def _batches(n, rng, nan_at=None, batch=32):
    """Deterministic overfit batches; ``nan_at`` poisons that step's batch
    (0-indexed) with a NaN — the injected fault the detectors must catch."""
    W = rng.normal(size=(IN, OUT)).astype(np.float32)
    out = []
    for i in range(n):
        x = rng.normal(size=(batch, IN)).astype(np.float32)
        if nan_at is not None and i == nan_at:
            x = x.copy()
            x[0, 0] = np.nan
        out.append((x, (x @ W).astype(np.float32)))
    return out


# --------------------------------------------------------------------------- #
# detector math
# --------------------------------------------------------------------------- #


def test_running_stats_ema_math():
    s = _RunningStats(alpha=0.5)
    assert s.zscore(1.0) is None  # no baseline yet
    s.update(10.0)
    assert s.mean == 10.0 and s.var == 0.0
    # constant stream: mean stays, variance stays 0, zscore of the
    # constant is 0
    for _ in range(5):
        s.update(10.0)
    assert s.mean == pytest.approx(10.0)
    assert s.var == pytest.approx(0.0)
    assert s.zscore(10.0) == 0.0
    # a deviation with zero variance is infinitely surprising
    assert s.zscore(11.0) == float("inf")
    # after noisy updates the variance is positive and the z-score scales
    # linearly with the deviation
    for v in (9.0, 11.0, 9.0, 11.0):
        s.update(v)
    assert s.var > 0
    z1 = s.zscore(s.mean + (s.var ** 0.5))
    assert z1 == pytest.approx(1.0)
    z3 = s.zscore(s.mean + 3 * (s.var ** 0.5))
    assert z3 == pytest.approx(3.0)


def test_loss_spike_detector_zscore_fires_and_baseline_clamps():
    det = LossSpikeDetector("record", zscore=3.0, warmup=4, alpha=0.2)
    # steady regime: a deterministic +/-1% oscillation around 1.0 keeps
    # the running variance positive and never crosses 3 sigma
    for step in range(20):
        obs = {"step_loss": 1.0 + (0.01 if step % 2 else -0.01)}
        assert det.check(step, obs, None) is None
    a = det.check(99, {"step_loss": 50.0}, None)
    assert a is not None
    assert a.detector == "loss_spike" and a.step == 99 and a.value == 50.0
    assert "sigma" in a.message
    # the spike must not normalize the baseline: a repeat spike re-fires
    assert det.check(100, {"step_loss": 50.0}, None) is not None


def test_spike_detector_warmup_and_nonfinite_guard():
    det = GradNormSpikeDetector("record", zscore=1.0, warmup=50, alpha=0.1)
    for step in range(10):
        assert det.check(step, {"grad_norm": 1.0}, None) is None
    # under warmup even a huge value stays silent
    assert det.check(10, {"grad_norm": 1e9}, None) is None
    # non-finite values are the NonFiniteDetector's job and must not
    # poison the EMA
    assert det.check(11, {"grad_norm": float("nan")}, None) is None
    assert np.isfinite(det.stats.mean)


# --------------------------------------------------------------------------- #
# flight-recorder ring
# --------------------------------------------------------------------------- #


def test_ring_buffer_bounds(tmp_path):
    rec = FlightRecorder(str(tmp_path / "b"), ring_size=5)
    for i in range(12):
        rec.record("note", {"i": i})
    assert len(rec) == 5
    ring = rec.ring
    assert [e["i"] for e in ring] == [7, 8, 9, 10, 11]


def test_bundle_dump_contents(tmp_path):
    rec = FlightRecorder(
        str(tmp_path / "b"),
        ring_size=8,
        status_dict={"device": "cpu"},
        mesh_info={"axes": ["data"]},
        snapshot_fn=lambda: {"m": {"kind": "counter", "value": 1.0}},
    )
    rec.record("note", {"msg": "hello"})
    path = rec.dump("unit-test", extra={"k": "v"})
    files = set(os.listdir(path))
    assert {
        "manifest.json", "ring.jsonl", "config.json", "mesh.json",
        "environment.json", "registry.json", "stacks.txt",
    } <= files
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["reason"] == "unit-test"
    assert manifest["extra"] == {"k": "v"}
    stacks = open(os.path.join(path, "stacks.txt")).read()
    assert "test_bundle_dump_contents" in stacks  # all-thread stacks: ours
    # a second dump with the same reason gets a distinct directory
    path2 = rec.dump("unit-test")
    assert path2 != path and os.path.isdir(path2)
    assert rec.dumps == [path, path2]


def test_bundle_path_reported_to_supervisor_handshake(tmp_path, monkeypatch):
    handshake = tmp_path / "bundles.txt"
    monkeypatch.setenv("STOKE_HEALTH_BUNDLE_FILE", str(handshake))
    rec = FlightRecorder(str(tmp_path / "b"), ring_size=2)
    path = rec.dump("handshake")
    assert handshake.read_text().strip() == path


# --------------------------------------------------------------------------- #
# sentinels on the 8-device mesh: values + zero extra dispatches
# --------------------------------------------------------------------------- #


def test_sentinels_in_jsonl_with_zero_extra_dispatches(tmp_path, devices):
    """Acceptance criterion: with health on, per-step sentinels appear in
    the JSONL step events and the engine dispatch count is UNCHANGED vs
    health-off (the vector rides the existing compiled programs)."""
    rng = np.random.default_rng(7)
    batches = _batches(6, rng)

    def run(tag, health):
        s = _make_stoke(
            tmp_path, health=health, distributed="dp", tag=tag
        )
        for x, y in batches[:3]:
            s.train_step(x, (y,))      # fused path
        for x, y in batches[3:]:
            out = s.model(x)           # 4-call path
            loss = s.loss(out, y)
            s.backward(loss)
            s.step()
        s.close_telemetry()
        return s

    s_off = run("off", health=False)
    s_on = run("on", health=True)
    assert s_on.dispatch_count == s_off.dispatch_count
    assert s_on.optimizer_steps == s_off.optimizer_steps == 6

    recs = read_step_events(
        os.path.join(str(tmp_path / "on" / "telemetry"), "steps.jsonl")
    )
    assert len(recs) == 6
    for rec in recs:
        assert rec["grad_norm"] is not None and rec["grad_norm"] > 0
        assert rec["param_norm"] is not None and rec["param_norm"] > 0
        assert rec["update_ratio"] is not None and rec["update_ratio"] > 0
        assert rec["nonfinite_leaves"] == 0.0
        assert rec["health_anomalies"] == 0.0
    # both the fused and the 4-call records carry sentinel values — the
    # old host-side sampling could never observe the fused path's buffer
    assert recs[0]["grad_norm"] > 0 and recs[-1]["grad_norm"] > 0


def test_sentinel_grad_norm_matches_host_sampling(tmp_path, devices):
    """Satellite: TelemetryConfig.grad_norm delegates to the sentinel
    vector (no second reduction); the values must agree with the retired
    host-side sampling path on identical steps."""
    rng_a = np.random.default_rng(11)
    rng_b = np.random.default_rng(11)
    s_off = _make_stoke(tmp_path, health=False, tag="host")
    s_on = _make_stoke(tmp_path, health=True, tag="sentinel")
    for s, rng in ((s_off, rng_a), (s_on, rng_b)):
        for x, y in _batches(3, rng):
            out = s.model(x)
            loss = s.loss(out, y)
            s.backward(loss)
            s.step()
        s.close_telemetry()
    recs_off = read_step_events(
        os.path.join(str(tmp_path / "host" / "telemetry"), "steps.jsonl")
    )
    recs_on = read_step_events(
        os.path.join(str(tmp_path / "sentinel" / "telemetry"), "steps.jsonl")
    )
    for a, b in zip(recs_off, recs_on):
        assert b["grad_norm"] == pytest.approx(a["grad_norm"], rel=1e-4)


def test_sentinel_fields_unpack_roundtrip():
    vec = np.arange(len(SENTINEL_FIELDS), dtype=np.float32)
    d = unpack_sentinels(vec)
    assert list(d) == list(SENTINEL_FIELDS)
    assert d["step_loss"] == 0.0
    assert d[SENTINEL_FIELDS[-1]] == float(len(SENTINEL_FIELDS) - 1)
    assert SENTINEL_INDEX["grad_norm"] == 1


# --------------------------------------------------------------------------- #
# NaN injection: detection at step k + post-mortem bundle
# --------------------------------------------------------------------------- #


def test_nan_batch_detected_at_injection_step_with_bundle(tmp_path, devices):
    """Acceptance criterion: a NaN injected at step k fires the nonfinite
    detector AT step k and dumps a bundle whose ring contains the k-th
    step's sentinel entry."""
    s = _make_stoke(tmp_path, health=True, distributed="dp", tag="nan")
    rng = np.random.default_rng(3)
    k = 4  # poisoned optimizer step (1-indexed: the 4th train_step call)
    for i, (x, y) in enumerate(_batches(5, rng, nan_at=k - 1)):
        s.train_step(x, (y,))
    h = s.health
    fired = [a for a in h.anomalies if a.detector == "nonfinite_grads"]
    assert fired, "nonfinite detector never fired"
    assert fired[0].step == k
    # default nonfinite action is "dump": exactly the poisoned steps wrote
    # bundles (capped at max_dumps)
    assert h.recorder.dumps, "no post-mortem bundle written"
    bundle = h.recorder.dumps[0]
    ring = [
        json.loads(ln)
        for ln in open(os.path.join(bundle, "ring.jsonl"))
        if ln.strip()
    ]
    sentinel_steps = [
        e["step"] for e in ring if e["kind"] == "sentinels"
    ]
    assert k in sentinel_steps  # the k-th event is in the ring
    nan_entry = next(
        e for e in ring
        if e["kind"] == "sentinels" and e["step"] == k
    )
    assert nan_entry["values"]["nonfinite_leaves"] > 0
    anomalies = [e for e in ring if e["kind"] == "anomaly"]
    assert any(e["detector"] == "nonfinite_grads" for e in anomalies)
    # counters surfaced through the registry (→ Prometheus/JSONL for free)
    reg = s.telemetry.registry
    assert reg.counter("health/anomalies_total").value >= 1
    assert reg.counter("health/anomaly_nonfinite_grads_total").value >= 1
    s.close_telemetry()


def test_nan_detected_inside_train_steps_segment(tmp_path, devices):
    """Multi-step scan path: sentinel rows come back stacked [n, S] and
    the detector attributes the firing to the right step inside the
    segment."""
    s = _make_stoke(tmp_path, health=True, distributed="dp", tag="multi")
    rng = np.random.default_rng(5)
    batches = _batches(4, rng, nan_at=2)  # 3rd window of the segment
    xs = np.stack([x for x, _ in batches])
    ys = np.stack([y for _, y in batches])
    s.train_steps(xs, (ys,))
    fired = [
        a for a in s.health.anomalies if a.detector == "nonfinite_grads"
    ]
    assert fired and fired[0].step == 3
    s.close_telemetry()


def test_health_halt_error_propagates(tmp_path, devices):
    """halt action: HealthHaltError raises out of the facade call, carries
    the anomaly + bundle path, and the bundle exists on disk."""
    s = _make_stoke(
        tmp_path, health=True, tag="halt",
        health_over={"nonfinite_action": "halt"},
    )
    rng = np.random.default_rng(9)
    batches = _batches(3, rng, nan_at=1)
    s.train_step(batches[0][0], (batches[0][1],))
    with pytest.raises(HealthHaltError) as ei:
        s.train_step(batches[1][0], (batches[1][1],))
    err = ei.value
    assert err.anomalies and err.anomalies[0].detector == "nonfinite_grads"
    assert err.bundle and os.path.isdir(err.bundle)
    assert "health halt" in str(err)
    s.close_telemetry()


# --------------------------------------------------------------------------- #
# watchdog
# --------------------------------------------------------------------------- #


def test_watchdog_unit_fires_once_per_arm():
    trips = []
    wd = HangWatchdog(0.15, lambda: trips.append(time.monotonic()))
    try:
        wd.arm()
        deadline = time.monotonic() + 3.0
        while not trips and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(trips) == 1
        time.sleep(0.3)  # disarmed after firing: no repeat
        assert len(trips) == 1
        # a completed (disarmed) dispatch never fires
        wd.arm()
        wd.disarm()
        time.sleep(0.3)
        assert len(trips) == 1
    finally:
        wd.stop()


def test_watchdog_trips_on_stalled_step_and_dumps_stacks(
    tmp_path, devices, monkeypatch
):
    """Acceptance criterion: a stalled step trips the watchdog, which
    writes a bundle with all-thread stacks (watchdog_kill off so the test
    process survives)."""
    s = _make_stoke(
        tmp_path, health=True, tag="wd",
        health_over={
            "watchdog": True,
            "watchdog_timeout_s": 0.2,
            # no warm-up allowance: the stall IS the first dispatch here
            "watchdog_compile_grace_s": 0.0,
        },
    )
    engine = s._engine
    real_fused = engine.fused_step

    def stalled(*args, **kwargs):
        time.sleep(0.8)  # the "wedged collective": dispatch never returns
        return real_fused(*args, **kwargs)

    monkeypatch.setattr(engine, "fused_step", stalled)
    rng = np.random.default_rng(1)
    (x, y), = _batches(1, rng)
    s.train_step(x, (y,))
    h = s.health
    assert h.watchdog.trips >= 1
    assert (
        s.telemetry.registry.counter("health/watchdog_trips_total").value
        >= 1
    )
    wd_bundles = [p for p in h.recorder.dumps if "watchdog" in p]
    assert wd_bundles
    stacks = open(os.path.join(wd_bundles[0], "stacks.txt")).read()
    # the stalled training thread is visible in the all-thread dump
    assert "stalled" in stacks
    s.close_telemetry()


def test_watchdog_no_false_trip_on_compile_or_segments(tmp_path, devices):
    """A tight per-step timeout must not kill healthy runs: warm-up
    compilation rides the compile grace, and a train_steps(n) segment —
    one dispatch legitimately covering n steps — re-arms with n x the
    timeout.  (Both would false-trip a fixed per-dispatch deadline.)"""
    s = _make_stoke(
        tmp_path, health=True, distributed="dp", tag="wd-ok",
        health_over={
            "watchdog": True,
            # far below the first-dispatch compile time on this machine,
            # and below a multi-step segment's run time
            "watchdog_timeout_s": 0.75,
            "watchdog_compile_grace_s": 120.0,
        },
    )
    rng = np.random.default_rng(6)
    batches = _batches(8, rng)
    xs = np.stack([x for x, _ in batches])
    ys = np.stack([y for _, y in batches])
    s.train_steps(xs, (ys,))  # first dispatch: compile >> timeout
    assert s.health.watchdog.trips == 0
    s.train_steps(xs, (ys,))  # warm 8-step segment under the scaled deadline
    assert s.health.watchdog.trips == 0
    assert s.optimizer_steps == 16
    s.close_telemetry()


def test_watchdog_bundle_counted_in_registry(tmp_path, devices, monkeypatch):
    """Every bundle — including a watchdog trip's — counts into
    health/bundles_total (the Prometheus 'post-mortem bundles written'
    series must not under-report)."""
    s = _make_stoke(
        tmp_path, health=True, tag="wd-count",
        health_over={
            "watchdog": True,
            "watchdog_timeout_s": 0.2,
            "watchdog_compile_grace_s": 0.0,
        },
    )
    real_fused = s._engine.fused_step

    def stalled(*args, **kwargs):
        time.sleep(0.8)
        return real_fused(*args, **kwargs)

    monkeypatch.setattr(s._engine, "fused_step", stalled)
    rng = np.random.default_rng(8)
    (x, y), = _batches(1, rng)
    s.train_step(x, (y,))
    reg = s.telemetry.registry
    assert reg.counter("health/watchdog_trips_total").value >= 1
    assert reg.counter("health/bundles_total").value >= 1
    s.close_telemetry()


def test_exception_in_step_path_dumps_bundle(tmp_path, devices, monkeypatch):
    s = _make_stoke(tmp_path, health=True, tag="exc")

    def boom(*args, **kwargs):
        raise RuntimeError("synthetic step failure")

    monkeypatch.setattr(s._engine, "fused_step", boom)
    rng = np.random.default_rng(2)
    (x, y), = _batches(1, rng)
    with pytest.raises(RuntimeError, match="synthetic step failure"):
        s.train_step(x, (y,))
    exc_bundles = [p for p in s.health.recorder.dumps if "exception" in p]
    assert exc_bundles
    manifest = json.load(
        open(os.path.join(exc_bundles[0], "manifest.json"))
    )
    assert "synthetic step failure" in manifest["extra"]["error"]
    s.close_telemetry()


def test_exception_dump_once_per_exception_and_capped(
    tmp_path, devices, monkeypatch
):
    """Nested guarded calls (chunked train_steps recursion) must write ONE
    bundle per exception, and repeated failing calls stop dumping at the
    max_dumps budget."""
    s = _make_stoke(
        tmp_path, health=True, tag="exc-cap",
        health_over={"max_dumps": 2},
    )

    def boom(*args, **kwargs):
        raise RuntimeError("chunk failure")

    monkeypatch.setattr(s._engine, "multi_step", boom)
    rng = np.random.default_rng(12)
    batches = _batches(4, rng)
    xs = np.stack([x for x, _ in batches])
    ys = np.stack([y for _, y in batches])
    # chunked: the outer train_steps recurses into guarded inner calls
    with pytest.raises(RuntimeError, match="chunk failure"):
        s.train_steps(xs, (ys,), segment_size=2)
    assert len(s.health.recorder.dumps) == 1  # not one per nesting level
    # retry loop: the exception-dump budget (max_dumps=2) caps the corpses
    for _ in range(4):
        with pytest.raises(RuntimeError):
            s.train_steps(xs, (ys,), segment_size=2)
    assert len(s.health.recorder.dumps) == 2
    s.close_telemetry()


def test_anomaly_totals_survive_bounded_object_window(tmp_path, devices):
    """anomaly_count / per-detector counts are cumulative counters, not
    len() of the bounded retained-object deque."""
    from collections import deque

    s = _make_stoke(
        tmp_path, health=True, tag="bounded",
        health_over={"nonfinite_action": "record"},
    )
    h = s.health
    h.anomalies = deque(maxlen=2)  # shrink the retention window
    row = np.zeros(len(SENTINEL_FIELDS), np.float32)
    row[SENTINEL_INDEX["nonfinite_leaves"]] = 1.0
    for step in range(1, 6):
        h.observe(step, row)
    assert len(h.anomalies) == 2  # bounded objects
    assert h.anomaly_count == 5   # unbounded totals
    assert h.anomaly_counts_by_detector() == {"nonfinite_grads": 5}
    s.close_telemetry()


def test_concurrent_dumps_get_distinct_directories(tmp_path):
    """Same-second dumps from concurrent crash paths must not share (and
    silently overwrite) one bundle directory."""
    import threading

    rec = FlightRecorder(str(tmp_path / "b"), ring_size=4)
    paths = []
    lock = threading.Lock()

    def one():
        p = rec.dump("race")
        with lock:
            paths.append(p)

    threads = [threading.Thread(target=one) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(paths)) == 4
    for p in paths:
        assert os.path.exists(os.path.join(p, "manifest.json"))


# --------------------------------------------------------------------------- #
# default-off identity
# --------------------------------------------------------------------------- #


def test_default_off_is_inert(tmp_path):
    """No HealthConfig: no monitor, no sentinels, the engine compiles the
    sentinel slot as an empty pytree, and training works untouched."""
    s = _make_stoke(tmp_path, health=False, tag="inert")
    assert s.health is None
    assert not s._engine.sentinels_enabled
    rng = np.random.default_rng(4)
    (x, y), = _batches(1, rng)
    s.train_step(x, (y,))
    assert s._last_sentinels is None
    recs = read_step_events(
        os.path.join(str(tmp_path / "inert" / "telemetry"), "steps.jsonl")
    )
    assert recs[0]["param_norm"] is None
    assert recs[0]["health_anomalies"] is None
    s.close_telemetry()


# --------------------------------------------------------------------------- #
# status rules
# --------------------------------------------------------------------------- #


def test_status_sentinels_require_telemetry():
    with pytest.raises(StokeValidationError, match="TelemetryConfig"):
        StokeStatus(batch_size_per_device=1, configs=[HealthConfig()])
    # sentinels=False decouples from telemetry (detector-only mode)
    st = StokeStatus(
        batch_size_per_device=1, configs=[HealthConfig(sentinels=False)]
    )
    assert st.health_config is not None


def test_status_halt_on_nonfinite_rejected_under_fp16(tmp_path):
    tele = TelemetryConfig(output_dir=str(tmp_path / "t"))
    with pytest.raises(StokeValidationError, match="fp16"):
        StokeStatus(
            batch_size_per_device=1,
            precision="fp16",
            configs=[tele, HealthConfig(nonfinite_action="halt")],
        )
    # the same config is legal at full precision
    StokeStatus(
        batch_size_per_device=1,
        configs=[tele, HealthConfig(nonfinite_action="halt")],
    )


def test_status_watchdog_requires_positive_timeout(tmp_path):
    tele = TelemetryConfig(output_dir=str(tmp_path / "t"))
    with pytest.raises(StokeValidationError, match="watchdog_timeout_s"):
        StokeStatus(
            batch_size_per_device=1,
            configs=[
                tele,
                HealthConfig(watchdog=True, watchdog_timeout_s=0.0),
            ],
        )


def test_status_unknown_action_rejected(tmp_path):
    tele = TelemetryConfig(output_dir=str(tmp_path / "t"))
    with pytest.raises(StokeValidationError, match="loss_spike_action"):
        StokeStatus(
            batch_size_per_device=1,
            configs=[tele, HealthConfig(loss_spike_action="explode")],
        )


def test_health_config_yaml_buildable(tmp_path):
    """HealthConfig builds from the declarative YAML schema like every
    other config class (configs: {HealthConfig: {...}})."""
    from stoke_tpu.utils.yaml_config import stoke_kwargs_from_config

    kwargs = stoke_kwargs_from_config({
        "batch_size_per_device": 8,
        "configs": {
            "TelemetryConfig": {"output_dir": str(tmp_path / "t")},
            "HealthConfig": {
                "watchdog": True,
                "watchdog_timeout_s": 120,
                "nonfinite_action": "halt",
            },
        },
    })
    (hcfg,) = [
        c for c in kwargs["configs"] if type(c).__name__ == "HealthConfig"
    ]
    assert hcfg.watchdog and hcfg.watchdog_timeout_s == 120
    assert hcfg.nonfinite_action == "halt"
